"""Prometheus text exposition for the live counters and summaries.

Runs are already observable via the metrics JSONL — but a scraper
should not have to tail and parse a file. This module renders the live
state (train health, serve TTFT/occupancy/rejects, goodput/MFU) in the
Prometheus text format (version 0.0.4), served at:

- ``GET /metricsz`` on the serve HTTP frontend (serve/server.py);
- ``GET /metricsz`` on the trainer's optional metrics port
  (``--metrics_port``; :class:`MetricsPort` below).

Zero dependencies: the format is lines of ``# HELP`` / ``# TYPE``
comments and ``name{label="v"} value`` samples. :func:`validate_promtext`
is the matching lint — metric/label name validity, quote escaping, no
duplicate samples, TYPE-before-samples — run by the smoke tier against
both expositions so a renderer regression fails tier-1 fast (the
trace-schema validator's sibling).

StatSummary snapshots render as Prometheus ``summary`` families:
``name{quantile="0.5"|"0.95"}``, ``name_sum``, ``name_count`` (plus
``name_min``/``name_max`` gauges — the snapshot carries exact
extremes, and dropping them would waste the only exact tail signal a
reservoir summary has).
"""

from __future__ import annotations

import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)(\s+-?\d+)?$"
)
_LABEL_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"'
)
_TYPES = ("counter", "gauge", "summary", "histogram", "untyped")

CONTENT_TYPE = "text/plain; version=0.0.4"


def _escape(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_value(v: float) -> str:
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class PromBuilder:
    """Accumulate samples per metric family, render once.

    Families keep insertion order; samples within a family keep theirs.
    ``add`` validates names eagerly (a bad series should fail where it
    was written, not at scrape time) and rejects duplicate
    (name, labelset) samples — the lint's rules, enforced at build.
    """

    def __init__(self):
        # name -> {"type": str, "help": str|None, "samples": [(labels, v)]}
        self._families: dict[str, dict] = {}
        self._seen: set = set()

    def add(
        self,
        name: str,
        value,
        *,
        labels: Optional[dict] = None,
        metric_type: str = "gauge",
        help: Optional[str] = None,
    ) -> "PromBuilder":
        if value is None:
            return self  # absent metric, not zero — same rule as MFU
        if not _METRIC_NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        if metric_type not in _TYPES:
            raise ValueError(f"bad metric type {metric_type!r}")
        labels = dict(labels or {})
        for k in labels:
            if not _LABEL_NAME_RE.match(k):
                raise ValueError(f"bad label name {k!r} on {name}")
        key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
        if key in self._seen:
            raise ValueError(f"duplicate sample {name} {labels}")
        self._seen.add(key)
        fam = self._families.setdefault(
            name, {"type": metric_type, "help": help, "samples": []}
        )
        if fam["type"] != metric_type:
            raise ValueError(
                f"{name}: conflicting types {fam['type']} vs {metric_type}"
            )
        fam["samples"].append((labels, float(value)))
        return self

    def summary(
        self,
        name: str,
        snapshot: Optional[dict],
        *,
        labels: Optional[dict] = None,
        help: Optional[str] = None,
    ) -> "PromBuilder":
        """A StatSummary ``snapshot()`` → one summary family (+ exact
        min/max gauges). Empty snapshots render count 0 only."""
        snap = snapshot or {}
        count = int(snap.get("count", 0))
        base = dict(labels or {})
        self.add(
            f"{name}_count", count, labels=base,
            metric_type="counter", help=help,
        )
        if count == 0:
            return self
        # Prefer the exact running sum; mean×count is the fallback for
        # foreign snapshots and is NOT monotone under mean rounding.
        total = (
            float(snap["sum"])
            if "sum" in snap
            else float(snap["mean"]) * count
        )
        self.add(f"{name}_sum", total, labels=base, metric_type="counter")
        for q, field in (("0.5", "p50"), ("0.95", "p95")):
            if field in snap:
                self.add(
                    name, snap[field],
                    labels={**base, "quantile": q},
                    metric_type="summary",
                )
        for ext in ("min", "max"):
            if ext in snap:
                self.add(f"{name}_{ext}", snap[ext], labels=base)
        return self

    def render(self) -> str:
        lines: list[str] = []
        for name, fam in self._families.items():
            if fam["help"]:
                lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {fam['type']}")
            for labels, value in fam["samples"]:
                if labels:
                    body = ",".join(
                        f'{k}="{_escape(v)}"'
                        for k, v in sorted(labels.items())
                    )
                    lines.append(f"{name}{{{body}}} {_fmt_value(value)}")
                else:
                    lines.append(f"{name} {_fmt_value(value)}")
        return "\n".join(lines) + "\n"


def validate_promtext(text: str) -> int:
    """Lint a text exposition; → sample count, raises ValueError.

    Checks the rules scrapers actually break on: name/label validity,
    quote escaping (labels must reconstruct exactly), float-parseable
    values, no duplicate (name, labelset) samples, at most one TYPE
    per family and declared before its samples, trailing newline.
    """
    if not text.endswith("\n"):
        raise ValueError("exposition must end with a newline")
    samples = 0
    seen: set = set()
    typed: dict[str, str] = {}
    sampled_names: set[str] = set()
    for n, line in enumerate(text.split("\n"), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("TYPE", "HELP"):
                mname = parts[2]
                if not _METRIC_NAME_RE.match(mname):
                    raise ValueError(f"line {n}: bad metric name {mname!r}")
                if parts[1] == "TYPE":
                    if len(parts) < 4 or parts[3] not in _TYPES:
                        raise ValueError(f"line {n}: bad TYPE line")
                    if mname in typed:
                        raise ValueError(f"line {n}: duplicate TYPE {mname}")
                    if mname in sampled_names:
                        raise ValueError(
                            f"line {n}: TYPE {mname} after its samples"
                        )
                    typed[mname] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {n}: unparseable sample {line!r}")
        name, _, labelbody, value, _ts = m.groups()
        pairs: tuple = ()
        if labelbody:
            found = _LABEL_PAIR_RE.findall(labelbody)
            rebuilt = ",".join(f'{k}="{v}"' for k, v in found)
            if rebuilt != labelbody.rstrip(","):
                raise ValueError(f"line {n}: malformed labels {labelbody!r}")
            names = [k for k, _ in found]
            if len(set(names)) != len(names):
                raise ValueError(f"line {n}: repeated label name")
            pairs = tuple(sorted(found))
        if value not in ("NaN", "+Inf", "-Inf"):
            try:
                float(value)
            except ValueError:
                raise ValueError(f"line {n}: bad value {value!r}")
        # quantile/le label participates in dedup — identical full
        # labelsets are what scrapers reject.
        key = (name, pairs)
        if key in seen:
            raise ValueError(f"line {n}: duplicate sample {name} {pairs}")
        seen.add(key)
        # A summary's name_sum/name_count samples belong to family
        # `name`; approximate by exact-name tracking (enough to catch
        # TYPE-after-sample for the family head).
        sampled_names.add(name)
        samples += 1
    return samples


# ---- renderers -------------------------------------------------------


def _render_build_info(b: PromBuilder, bi: Optional[dict], name: str) -> None:
    """The ``ddp_tpu_build_info`` provenance gauge (value 1, identity
    in the labels — the Prometheus *_info idiom). Shared by both
    exporters so a fleet scrape spots version skew in one query;
    absent when the snapshot carries no block (pre-build-info
    streams stay byte-identical)."""
    if not bi:
        return
    b.add(
        name, 1,
        labels={k: str(v) for k, v in sorted(bi.items())},
        help="package/jax/backend provenance (value is always 1)",
    )


def _render_slo(b: PromBuilder, slo: Optional[dict]) -> None:
    """SLO gauges (obs/slo.py): target/current/burn-rate/breached per
    objective. Absent-key gated — an engine without --slo renders no
    ddp_tpu_slo_* series at all (the disabled-pin convention)."""
    if not slo:
        return
    for obj in slo.get("objectives") or []:
        labels = {"objective": obj["name"]}
        b.add(
            "ddp_tpu_slo_target", obj.get("target"), labels=labels,
            help="objective bound (seconds, or fraction for "
            "availability)",
        )
        b.add(
            "ddp_tpu_slo_current", obj.get("current"), labels=labels,
            help="fast-window SLI value (absent until observed)",
        )
        for window, key in (("fast", "burn_rate_fast"),
                            ("slow", "burn_rate_slow")):
            b.add(
                "ddp_tpu_slo_burn_rate", obj.get(key),
                labels={**labels, "window": window},
                help="error-budget burn rate (1.0 = budget consumed "
                "exactly)",
            )
        b.add(
            "ddp_tpu_slo_breached",
            1 if obj.get("breached") else 0,
            labels=labels,
            help="1 while the current windowed value violates the "
            "objective",
        )


def render_serve(
    stats: dict,
    *,
    up: Optional[bool] = None,
    draining: Optional[bool] = None,
) -> str:
    """ServeEngine.stats() → exposition (the /metricsz payload)."""
    b = PromBuilder()
    if up is not None:
        b.add(
            "ddp_tpu_serve_up", 1 if up else 0,
            help="1 while the engine loop is healthy",
        )
    if draining is not None:
        b.add(
            "ddp_tpu_serve_draining", 1 if draining else 0,
            help="1 while shutdown drain rejects new admissions",
        )
    b.add("ddp_tpu_serve_slots", stats.get("slots"), help="decode lanes")
    b.add(
        "ddp_tpu_serve_active_slots", stats.get("active"),
        help="lanes bound to a request",
    )
    slots = stats.get("slots") or 0
    if slots:
        b.add(
            "ddp_tpu_serve_slot_occupancy",
            (stats.get("active") or 0) / slots,
            help="active / slots",
        )
    b.add("ddp_tpu_serve_queue_depth", stats.get("queue_depth"))
    b.add(
        "ddp_tpu_serve_steps_total", stats.get("steps"),
        metric_type="counter", help="engine iterations",
    )
    for reason, count in sorted((stats.get("rejects") or {}).items()):
        b.add(
            "ddp_tpu_serve_rejects_total", count,
            labels={"reason": reason}, metric_type="counter",
        )
    for status, count in sorted(
        (stats.get("requests_by_status") or {}).items()
    ):
        b.add(
            "ddp_tpu_serve_requests_total", count,
            labels={"status": status}, metric_type="counter",
        )
    b.add(
        "ddp_tpu_serve_tokens_total", stats.get("tokens_total"),
        metric_type="counter",
        help="tokens scheduled across all requests (the aggregator's "
        "fleet tokens/s source)",
    )
    b.summary(
        "ddp_tpu_serve_ttft_seconds", stats.get("ttft_s"),
        help="submit to first token",
    )
    b.summary(
        "ddp_tpu_serve_tpot_seconds", stats.get("tpot_s"),
        help="decode seconds per output token (per request)",
    )
    b.summary(
        "ddp_tpu_serve_queue_wait_seconds", stats.get("queue_s"),
        help="submit to decode-lane bind",
    )
    b.summary(
        "ddp_tpu_serve_decode_tokens_per_second",
        stats.get("decode_tokens_per_s"),
    )
    b.summary(
        "ddp_tpu_serve_step_latency_seconds", stats.get("step_latency_s")
    )
    _render_slo(b, stats.get("slo"))
    _render_build_info(b, stats.get("build_info"), "ddp_tpu_build_info")
    for prog, count in sorted((stats.get("compile_counts") or {}).items()):
        b.add(
            "ddp_tpu_serve_compiled_programs", count,
            labels={"program": prog},
            help="jit cache entries (static-shape pin observable)",
        )
    # Decode hot path (ISSUE 10): cache footprint + speculative
    # acceptance. Absent keys (pre-decode-path engines, spec off)
    # render nothing — absent and zero are different facts.
    dp = stats.get("decode_path") or {}
    b.add(
        "ddp_tpu_serve_cache_bytes_per_slot",
        dp.get("cache_bytes_per_slot"),
        help="KV-cache HBM per decode lane, int8 scales included",
    )
    b.add(
        "ddp_tpu_serve_spec_drafted_total", dp.get("spec_drafted_total"),
        metric_type="counter", help="draft tokens proposed",
    )
    b.add(
        "ddp_tpu_serve_spec_accepted_total",
        dp.get("spec_accepted_total"),
        metric_type="counter", help="draft tokens the target accepted",
    )
    b.add(
        "ddp_tpu_serve_spec_acceptance", dp.get("spec_acceptance"),
        help="lifetime accepted/drafted fraction",
    )
    # Paged KV + radix prefix cache (PR 12): pool occupancy and
    # prefix-reuse counters. The whole block is absent-key gated on
    # the engine's paged mode, so a fixed-lane engine's exposition
    # stays byte-identical.
    pg = stats.get("paged") or {}
    b.add(
        "ddp_tpu_serve_prefix_hits_total", pg.get("prefix_hits"),
        metric_type="counter",
        help="requests that matched cached prefix pages at bind",
    )
    b.add(
        "ddp_tpu_serve_prefix_misses_total", pg.get("prefix_misses"),
        metric_type="counter",
    )
    b.add(
        "ddp_tpu_serve_prefix_hit_rate", pg.get("prefix_hit_rate"),
        help="prompt tokens served from cached pages / prompt tokens "
        "admitted (token-level, lifetime)",
    )
    b.add(
        "ddp_tpu_serve_pages_free", pg.get("pages_free"),
        help="allocatable pages (excluding evictable cached prefixes)",
    )
    b.add(
        "ddp_tpu_serve_pages_resident", pg.get("pages_resident"),
        help="pages holding live KV: lane-mapped or prefix-cached",
    )
    b.add(
        "ddp_tpu_serve_pages_shared", pg.get("pages_shared"),
        help="pages mapped by two or more lanes (copy-free forks)",
    )
    gp = stats.get("goodput") or {}
    b.add("ddp_tpu_serve_productive_seconds_total", gp.get("productive_s"),
          metric_type="counter")
    b.add("ddp_tpu_serve_goodput", gp.get("goodput"))
    # Model-lifecycle block (hot-swap tentpole): absent until the
    # engine carries a model version or has swapped/rolled back, so a
    # pre-lifecycle exposition stays byte-identical.
    lc = stats.get("lifecycle") or {}
    b.add(
        "ddp_tpu_serve_reloads_total", lc.get("reloads_total"),
        metric_type="counter",
        help="verified hot-swaps committed (install_params)",
    )
    b.add(
        "ddp_tpu_serve_rollbacks_total", lc.get("rollbacks_total"),
        metric_type="counter",
        help="mid-swap failures rolled back to the previous weights",
    )
    if lc.get("model_version"):
        b.add(
            "ddp_tpu_serve_model_info", 1,
            labels={"version": str(lc["model_version"])},
            help="serving model version (checkpoint@epoch), value "
            "always 1",
        )
    # Compiled-program introspection (obs/xprof.py, engine xprof=...):
    # absent keys render nothing, so an xprof-less engine's exposition
    # stays byte-identical.
    xp = stats.get("xprof") or {}
    b.add(
        "ddp_tpu_serve_compiled_executables", xp.get("programs"),
        help="xprof compile-ledger entries",
    )
    b.add(
        "ddp_tpu_serve_compile_seconds_total", xp.get("compile_s_total"),
        metric_type="counter", help="XLA compile wall time paid",
    )
    mem = xp.get("hbm") or {}
    b.add("ddp_tpu_serve_hbm_used_bytes", mem.get("hbm_used_bytes"))
    b.add(
        "ddp_tpu_serve_hbm_high_water_bytes",
        mem.get("hbm_high_water_bytes"),
        help="peak device memory observed",
    )
    b.add(
        "ddp_tpu_serve_hbm_headroom_frac", mem.get("hbm_headroom_frac"),
        help="1 - high_water/limit (absent off-TPU: no honest limit)",
    )
    return b.render()


def render_fleet(
    snap: dict,
    *,
    up: Optional[bool] = None,
    draining: Optional[bool] = None,
) -> str:
    """Fleet router/manager snapshot → exposition (the fleet
    frontend's /metricsz; serve/fleet.py ``Router.state()`` merged
    with the manager's counters). Per-replica serving metrics stay on
    each replica's own /metricsz — these are the ROUTER's facts:
    health gating, breaker state, replay/hedge accounting, restarts.
    """
    b = PromBuilder()
    if up is not None:
        b.add(
            "ddp_tpu_fleet_up", 1 if up else 0,
            help="1 while at least one replica is dispatchable",
        )
    if draining is not None:
        b.add(
            "ddp_tpu_fleet_draining", 1 if draining else 0,
            help="1 while the fleet frontend rejects new admissions",
        )
    b.add(
        "ddp_tpu_fleet_replicas", snap.get("replicas"),
        help="supervised replica processes",
    )
    b.add(
        "ddp_tpu_fleet_replicas_healthy", snap.get("replicas_healthy"),
        help="replicas passing /healthz and accepting dispatch",
    )
    b.add(
        "ddp_tpu_fleet_replicas_draining", snap.get("replicas_draining"),
    )
    b.add(
        "ddp_tpu_fleet_replicas_dead", snap.get("replicas_dead"),
        help="replicas whose process is down (restarting or out of "
        "restart budget)",
    )
    b.add(
        "ddp_tpu_fleet_breaker_open", snap.get("breaker_open"),
        help="replicas whose circuit breaker is not closed (open or "
        "half-open: shedding user traffic)",
    )
    b.add(
        "ddp_tpu_fleet_breaker_opens_total", snap.get("breaker_opens_total"),
        metric_type="counter",
        help="lifetime closed->open transitions across all breakers",
    )
    b.add(
        "ddp_tpu_fleet_dispatched_total", snap.get("dispatched_total"),
        metric_type="counter", help="requests the router dispatched",
    )
    b.add(
        "ddp_tpu_fleet_retries_total", snap.get("retries_total"),
        metric_type="counter",
        help="re-dispatches after a failed/backpressured attempt",
    )
    b.add(
        "ddp_tpu_fleet_replays_total", snap.get("replays_total"),
        metric_type="counter",
        help="in-flight requests replayed to a surviving replica "
        "after their replica died mid-request",
    )
    b.add(
        "ddp_tpu_fleet_hedges_total", snap.get("hedges_total"),
        metric_type="counter",
        help="straggler requests duplicated to a second replica",
    )
    b.add(
        "ddp_tpu_fleet_hedge_wins_total", snap.get("hedge_wins_total"),
        metric_type="counter",
        help="hedged requests the SECOND replica answered first",
    )
    b.add(
        "ddp_tpu_fleet_restarts_total", snap.get("restarts_total"),
        metric_type="counter",
        help="replica process restarts the manager performed",
    )
    b.add(
        "ddp_tpu_fleet_rolling_restarts_total",
        snap.get("rolling_restarts_total"),
        metric_type="counter",
        help="completed fleet-wide rolling restarts (drain -> wait "
        "-> restart -> re-admit, one replica at a time)",
    )
    # Model-lifecycle series: absent until a fleet reload ran / any
    # replica advertises a version (the gated-state convention).
    b.add(
        "ddp_tpu_fleet_reloads_total", snap.get("fleet_reloads_total"),
        metric_type="counter",
        help="completed fleet-wide verified hot-swaps (/reloadz: one "
        "member /reload at a time, zero process churn)",
    )
    for version, count in sorted(
        (snap.get("model_versions") or {}).items()
    ):
        b.add(
            "ddp_tpu_fleet_model_version", count,
            labels={"version": str(version)},
            help="replicas serving each model version (one series "
            "while converged, two mid-roll)",
        )
    # Disaggregation series (PR 16): every key below is ABSENT from a
    # classic router's state(), so PromBuilder renders nothing and the
    # exposition stays byte-identical when the feature is off.
    for index, role in sorted(
        (snap.get("replica_roles") or {}).items()
    ):
        b.add(
            "ddp_tpu_fleet_role", 1,
            labels={"replica": str(index), "role": role},
            help="replica's serving role (prefill | decode | hybrid)",
        )
    b.add(
        "ddp_tpu_fleet_prefill_handoffs_total",
        snap.get("prefill_handoffs_total"),
        metric_type="counter",
        help="long prompts prefilled on the prefill tier before "
        "their pages migrated to a decode replica",
    )
    b.add(
        "ddp_tpu_fleet_migrations_total", snap.get("migrations_total"),
        metric_type="counter",
        help="completed KV-page migrations (export + install over "
        "POST /pages)",
    )
    b.add(
        "ddp_tpu_fleet_migration_failures_total",
        snap.get("migration_failures_total"),
        metric_type="counter",
        help="migrations abandoned (export miss, pool full, "
        "transport death) — the request replayed from the prompt",
    )
    b.add(
        "ddp_tpu_fleet_pages_migrated_total",
        snap.get("pages_migrated_total"),
        metric_type="counter",
        help="KV pages physically copied between replicas",
    )
    b.add(
        "ddp_tpu_fleet_directory_pulls_total",
        snap.get("directory_pulls_total"),
        metric_type="counter",
        help="prefix-directory lookups that found another replica "
        "owning the prompt's pages and attempted a pull",
    )
    b.add(
        "ddp_tpu_fleet_directory_pull_hits_total",
        snap.get("directory_pull_hits_total"),
        metric_type="counter",
        help="directory pulls whose pages installed on the target",
    )
    b.add(
        "ddp_tpu_fleet_directory_size", snap.get("directory_size"),
        help="distinct leading-page prefixes the router can locate",
    )
    if "migration_seconds" in snap:
        # summary() renders a count-0 series for an EMPTY snapshot, so
        # the absent-key gate lives here, not inside the helper.
        b.summary(
            "ddp_tpu_fleet_migration_seconds",
            snap.get("migration_seconds"),
            help="one migration's export + push wall time",
        )
    # Fleet-tracing series (PR 19): keys absent from an untraced
    # router's state(), so the exposition stays byte-identical with
    # tracing off (the same gate the disaggregation block rides).
    b.add(
        "ddp_tpu_fleet_trace_propagated_total",
        snap.get("trace_propagated_total"),
        metric_type="counter",
        help="completed requests whose serving replica adopted the "
        "router's trace context (echoed the trace id back)",
    )
    b.add(
        "ddp_tpu_fleet_trace_orphaned_total",
        snap.get("trace_orphaned_total"),
        metric_type="counter",
        help="completed requests whose replica did NOT echo the "
        "router's trace id — its timeline is orphaned from the hops",
    )
    for kind, hop_snap in sorted(
        (snap.get("hop_seconds") or {}).items()
    ):
        b.summary(
            "ddp_tpu_fleet_hop_seconds", hop_snap,
            labels={"hop": kind},
            help="per-hop router latency (dispatch, prefill_handoff, "
            "migrate, breaker_wait, ...) by hop kind",
        )
    _render_build_info(b, snap.get("build_info"), "ddp_tpu_build_info")
    return b.render()


def render_train(snap: dict) -> str:
    """Trainer telemetry snapshot → exposition.

    ``snap`` is the trainer's live dict (step/loss/grad_norm/mfu/
    goodput/recompiles/health events/step-time summary); absent keys
    render no series — absent and zero are different facts.
    """
    b = PromBuilder()
    b.add("ddp_tpu_train_up", 1, help="trainer process is live")
    b.add("ddp_tpu_train_step", snap.get("step"), help="global step")
    b.add("ddp_tpu_train_epoch", snap.get("epoch"))
    b.add("ddp_tpu_train_loss", snap.get("loss"), help="last logged loss")
    b.add("ddp_tpu_train_grad_norm", snap.get("grad_norm"))
    b.add("ddp_tpu_train_learning_rate", snap.get("lr"))
    b.add("ddp_tpu_train_accuracy", snap.get("accuracy"))
    b.add("ddp_tpu_train_mfu", snap.get("mfu"), help="model FLOP/s / peak")
    b.add(
        "ddp_tpu_train_goodput", snap.get("goodput"),
        help="productive seconds / wall since first launch",
    )
    b.add(
        "ddp_tpu_train_examples_per_second", snap.get("images_per_sec")
    )
    b.add(
        "ddp_tpu_train_recompiles_total", snap.get("recompiles"),
        metric_type="counter",
    )
    for det, count in sorted((snap.get("health_events") or {}).items()):
        b.add(
            "ddp_tpu_train_health_events_total", count,
            labels={"detector": det}, metric_type="counter",
            help="anomaly sentry detections",
        )
    if snap.get("nonfinite_layer") is not None or (
        snap.get("nonfinite_step") is not None
    ):
        b.add(
            "ddp_tpu_train_nonfinite", 1,
            labels={
                "layer": snap.get("nonfinite_layer") or "unknown",
                "step": str(snap.get("nonfinite_step")),
            },
            help="first non-finite gradient/loss observation",
        )
    # Compiled-program introspection (--xprof, obs/xprof.py): compile
    # ledger totals and the device-memory sampler's view. Absent keys
    # render no series — an xprof-off trainer's exposition is
    # byte-identical to the pre-xprof one.
    b.add(
        "ddp_tpu_train_compiled_executables", snap.get("compile_programs"),
        help="xprof compile-ledger entries",
    )
    b.add(
        "ddp_tpu_train_compile_seconds_total",
        snap.get("compile_seconds_total"),
        metric_type="counter", help="XLA compile wall time paid",
    )
    b.add("ddp_tpu_train_hbm_used_bytes", snap.get("hbm_used_bytes"))
    b.add(
        "ddp_tpu_train_hbm_high_water_bytes",
        snap.get("hbm_high_water_bytes"),
        help="peak device memory observed",
    )
    b.add(
        "ddp_tpu_train_hbm_headroom_frac", snap.get("hbm_headroom_frac"),
        help="1 - high_water/limit (absent off-TPU: no honest limit)",
    )
    b.summary("ddp_tpu_train_step_seconds", snap.get("step_time"))
    _render_build_info(b, snap.get("build_info"), "ddp_tpu_build_info")
    return b.render()


# ---- the trainer's metrics port --------------------------------------


class MetricsPort:
    """Minimal HTTP endpoint: GET /metricsz → ``text_fn()``.

    ``port=0`` binds ephemeral (tests); ``.port`` is the bound one.
    One daemon thread; ``stop()`` (or context exit) shuts it down.
    The handler never lets a renderer exception kill the scrape
    endpoint — it answers 500 with the error text instead.
    """

    def __init__(
        self,
        text_fn: Callable[[], str],
        *,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: D102
                pass

            def _send(self, status: int, body: str, ctype: str) -> None:
                data = body.encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                if self.path == "/metricsz":
                    try:
                        text = outer.text_fn()
                    except Exception as e:  # noqa: BLE001
                        self._send(500, f"render failed: {e}\n", "text/plain")
                        return
                    self._send(200, text, CONTENT_TYPE)
                elif self.path == "/healthz":
                    self._send(200, '{"ok": true}', "application/json")
                else:
                    self._send(404, f"no route {self.path}\n", "text/plain")

        self.text_fn = text_fn
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "MetricsPort":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="ddp-tpu-metrics-port",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "MetricsPort":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

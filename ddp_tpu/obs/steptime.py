"""Step-time attribution: where did this training step's wall time go?

A JAX training step has three host-observable segments:

- **input wait** — the time ``next()`` on the loader blocks before the
  batch exists on the host (data pipeline stall);
- **dispatch** — the time the (async) step call takes to RETURN: under
  normal operation this is trace/lowering-cache lookup plus enqueue
  (sub-ms); a recompile or a full device pipeline shows up here;
- **device compute** — ``block_until_ready`` on a step output after
  dispatch returns: the device-side cost of the step (plus any queue
  ahead of it).

Attribution deliberately synchronizes every step (the bounded-inflight
overlap the trainer normally runs is what it measures AWAY), so it is
a diagnosis mode, not the default — enabled by ``--trace_dir`` and
costing nothing when off (``StepAttributor(enabled=False)`` hands back
the caller's iterator unchanged and ``on_step`` returns immediately).

Recompiles are counted process-wide via ``jax.monitoring`` compile
events (one ``backend_compile`` per executable built), not per-function
``_cache_size()`` probes: the trainer's step may be a lambda over a
jitted inner function, and a *process-level* counter also catches
compiles hiding in eval, checkpoint restore, or a library call — if
the count moved during a step, that step paid for a compile, whoever
owned it.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Iterable, Iterator, Optional

from ddp_tpu.obs.tracer import Tracer

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class CompileCounter:
    """Process-wide XLA compile counter (lazy jax.monitoring listener).

    ``install()`` is idempotent and only ever called from enabled
    attribution paths, so tracing-off runs never register the listener
    (and never import jax from this module) — part of the disabled-
    mode-is-free pin. jax.monitoring has no unregister; the listener
    is one integer increment per *compilation*, which is noise even if
    it outlives the attributor that installed it.
    """

    _count = 0
    _installed = False

    @classmethod
    def install(cls) -> None:
        if cls._installed:
            return
        import jax

        def _on_event(name: str, *args: Any, **kw: Any) -> None:
            if name == _COMPILE_EVENT:
                cls._count += 1

        jax.monitoring.register_event_duration_secs_listener(_on_event)
        cls._installed = True

    @classmethod
    def installed(cls) -> bool:
        return cls._installed

    @classmethod
    def count(cls) -> int:
        return cls._count


@dataclass
class StepTiming:
    """One step's attribution (seconds; recompiles is a count).

    ``compiles`` names the culprits when the step paid for one and an
    :class:`~ddp_tpu.obs.xprof.Xprof` instruments the hot path: one
    dict per compile with the responsible label, arg-shape signature,
    shape-diff vs the label's previous compile, and compile seconds —
    the recompile-storm sentry's count, upgraded to an attribution.
    None when nothing compiled (or nothing was instrumented).
    """

    input_wait_s: float
    dispatch_s: float
    compute_s: float
    recompiles: int
    compiles: Optional[list] = None

    @property
    def wall_s(self) -> float:
        return self.input_wait_s + self.dispatch_s + self.compute_s


@dataclass
class EpochAttribution:
    """Sums over one epoch of attributed steps."""

    steps: int = 0
    input_wait_s: float = 0.0
    dispatch_s: float = 0.0
    compute_s: float = 0.0
    recompiles: int = 0

    def add(self, t: StepTiming) -> None:
        self.steps += 1
        self.input_wait_s += t.input_wait_s
        self.dispatch_s += t.dispatch_s
        self.compute_s += t.compute_s
        self.recompiles += t.recompiles


class StepAttributor:
    """Per-step input-wait / dispatch / compute / recompile splitter.

    Usage (the trainer's host loop)::

        for batch in attr.batches(loader.epoch(e)):
            state, metrics = train_step(state, ...)
            timing = attr.on_step(metrics.loss)  # None when disabled

    ``batches`` times the gap between iterations (input wait);
    ``on_step`` times dispatch-return vs block_until_ready and reads
    the compile-counter delta. Each attributed segment also lands in
    ``tracer`` as a span, so the JSONL numbers and the Perfetto
    picture come from the same measurements.
    """

    def __init__(
        self,
        *,
        enabled: bool = False,
        tracer: Optional[Tracer] = None,
        xprof=None,
    ):
        self.enabled = bool(enabled)
        self.tracer = tracer if tracer is not None else Tracer()
        # Compile attribution (obs/xprof.py): when the hot path is
        # instrumented, a step whose compile counter moved also gets
        # the ledger events that landed during it — label, shape-diff,
        # compile seconds. None/disabled adds nothing.
        self.xprof = xprof if (xprof is not None and xprof.enabled) else None
        self.epoch_totals = EpochAttribution()
        self._input_wait = 0.0
        self._fetch_end = 0.0
        self._compiles_at_fetch = 0
        self._xprof_seq_at_fetch = 0
        if self.enabled:
            CompileCounter.install()

    def batches(self, iterable: Iterable) -> Iterator:
        """Wrap a batch iterator, timing each ``next()``.

        Disabled mode returns ``iter(iterable)`` itself — no wrapper
        generator, no per-item overhead (pinned by tests).
        """
        if not self.enabled:
            return iter(iterable)
        return self._timed_iter(iterable)

    def _timed_iter(self, iterable: Iterable) -> Iterator:
        it = iter(iterable)
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            self._fetch_end = time.perf_counter()
            self._input_wait = self._fetch_end - t0
            self._compiles_at_fetch = CompileCounter.count()
            if self.xprof is not None:
                self._xprof_seq_at_fetch = self.xprof.event_seq
            yield batch

    def on_step(self, sync_ref: Any) -> Optional[StepTiming]:
        """Call right after the step call returns; blocks on
        ``sync_ref`` to split dispatch from device compute."""
        if not self.enabled:
            return None
        import jax

        dispatched = time.perf_counter()
        jax.block_until_ready(sync_ref)
        done = time.perf_counter()
        timing = StepTiming(
            input_wait_s=self._input_wait,
            dispatch_s=dispatched - self._fetch_end,
            compute_s=done - dispatched,
            recompiles=CompileCounter.count() - self._compiles_at_fetch,
        )
        if timing.recompiles and self.xprof is not None:
            # The ledger events that landed during this step ARE the
            # culprits — the process counter says a compile happened,
            # the ledger says which label and what shape changed.
            self._xprof_seq_at_fetch, events = self.xprof.events_after(
                self._xprof_seq_at_fetch
            )
            timing.compiles = events or None
        self.epoch_totals.add(timing)
        tr = self.tracer
        if tr.enabled:
            # Retroactive spans: begin/end stamps are already in hand.
            tr.complete(
                "step.input_wait",
                self._fetch_end - timing.input_wait_s,
                timing.input_wait_s,
            )
            tr.complete("step.dispatch", self._fetch_end, timing.dispatch_s)
            compute_args = None
            if timing.recompiles:
                compute_args = {"recompiles": timing.recompiles}
                if timing.compiles:
                    compute_args["compiled"] = [
                        f"{e.get('label')} ({e.get('compile_time_s')}s)"
                        for e in timing.compiles
                    ]
            tr.complete(
                "step.compute", dispatched, timing.compute_s, compute_args,
            )
        # Prime for a loop body that never re-enters the iterator
        # (last batch): keep fetch_end monotone.
        self._fetch_end = done
        self._input_wait = 0.0
        self._compiles_at_fetch = CompileCounter.count()
        if self.xprof is not None:
            self._xprof_seq_at_fetch = self.xprof.event_seq
        return timing

    def finish_epoch(self) -> EpochAttribution:
        """Return and reset the epoch accumulator."""
        totals = self.epoch_totals
        self.epoch_totals = EpochAttribution()
        return totals


def dispatch_compute_split(run, *args) -> tuple[Any, float, float, int]:
    """Time one whole-epoch dispatch (the ``--fast_epoch`` path).

    Returns ``(result, dispatch_s, compute_s, recompiles)`` where
    ``result`` is whatever ``run(*args)`` returned, dispatch is the
    call-return time and compute the ``block_until_ready`` tail on its
    outputs. Per-epoch granularity is all the host can see of a
    compiled epoch — the scan body is one XLA program.
    """
    import jax

    CompileCounter.install()
    c0 = CompileCounter.count()
    t0 = time.perf_counter()
    result = run(*args)
    t1 = time.perf_counter()
    jax.block_until_ready(result)
    t2 = time.perf_counter()
    return result, t1 - t0, t2 - t1, CompileCounter.count() - c0

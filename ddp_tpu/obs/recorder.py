"""Flight recorder: a bounded ring of step records, dumped post-mortem.

A crashed, SIGTERM'd, or watchdog-killed run leaves stack traces but
no record of the steps that led up to the kill — the part a post-
mortem actually needs. The recorder keeps the last N step/log/health
records (host-side dicts, bounded deque — recording costs one append,
no device sync) plus a one-time context snapshot (config, environment
subset, mesh shape), and dumps the whole thing as one JSON file:

- crash-safely: temp file + ``os.replace`` (the tracer's discipline) —
  a crash mid-dump leaves the previous dump intact, never a half file;
- per rank: ``flight_rank{rank}.json`` in the configured directory;
- on every exit class: trainer exceptions, the SIGTERM/preemption
  handler, the end-of-run non-finite gate, and — via
  ``utils.watchdog.register_forensics`` — the watchdog's ``os._exit``
  path, so a hang leaves the same artifact as a crash.

``dump()`` never raises (it IS the error path); it returns the path or
None. Non-finite floats sanitize to null like the metrics stream —
divergence is precisely when the dump gets read.
"""

from __future__ import annotations

import json
import math
import os
import platform
import sys
import time
from collections import deque
from typing import Any, Optional

FLIGHT_FILENAME = "flight_rank{rank}.json"

# Environment keys worth a post-mortem (never the whole environ: it
# can carry credentials).
_ENV_PREFIXES = ("JAX_", "XLA_", "DDP_TPU_", "TPU_", "LIBTPU")


def snapshot_env() -> dict:
    """Interpreter + relevant env vars, JSON-ready."""
    env = {
        k: v
        for k, v in sorted(os.environ.items())
        if k.startswith(_ENV_PREFIXES)
    }
    out = {
        "python": sys.version.split()[0],
        "platform": platform.platform(),
        "argv": list(sys.argv),
        "env": env,
    }
    try:
        import jax

        out["jax"] = jax.__version__
    except Exception:  # pragma: no cover - jax is always present here
        pass
    return out


def build_info() -> dict:
    """The provenance block every long-lived process should publish.

    Package version, jax version, backend and platform — the fields
    bench.py's ``_env_fields()`` made load-bearing for the perf
    trajectory (a CPU-fallback capture must never be read as an
    on-chip one), now stamped on trainer ``run_start`` records,
    ``/statusz``, and the linted ``ddp_tpu_build_info`` gauge on both
    ``/metricsz`` exporters, so an aggregator scraping a fleet can
    tell a version-skewed endpoint at a glance.
    """
    import jax

    import ddp_tpu

    return {
        "version": ddp_tpu.__version__,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "platform": jax.devices()[0].platform,
    }


def _sanitize(obj):
    """Strict-JSON form: non-finite floats → null, keys → str."""
    if isinstance(obj, dict):
        return {str(k): _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return str(obj)


class FlightRecorder:
    """Bounded ring of records + context, crash-safe JSON dump.

    ``capacity <= 0`` disables everything (record/dump are no-ops) so
    callers wire it unconditionally from config.
    """

    def __init__(
        self,
        directory: Optional[str],
        *,
        rank: int = 0,
        capacity: int = 256,
        clock=time.time,
    ):
        self.enabled = bool(directory) and capacity > 0
        self.directory = directory
        self.rank = int(rank)
        self.capacity = max(0, int(capacity))
        self.clock = clock
        self._ring: deque = deque(maxlen=max(1, self.capacity))
        self._context: dict[str, Any] = {}
        self._providers: dict[str, Any] = {}
        self._dumps = 0

    @property
    def path(self) -> Optional[str]:
        if not self.enabled:
            return None
        return os.path.join(
            self.directory, FLIGHT_FILENAME.format(rank=self.rank)
        )

    def set_context(self, **ctx) -> None:
        """Merge one-time context (config/env/mesh snapshots)."""
        if not self.enabled:
            return
        self._context.update(ctx)

    def set_provider(self, name: str, fn) -> None:
        """Register a live-state provider collected AT DUMP TIME.

        Unlike ``set_context`` (a snapshot frozen when set), a
        provider is called when the dump happens — the xprof compile
        ledger and the last device-memory sample belong here: an OOM
        post-mortem needs the state at death, not at construction.
        Each provider runs inside its own guard; a raising provider
        contributes an error marker, never kills the dump.
        """
        if not self.enabled:
            return
        self._providers[name] = fn

    def record(self, kind: str, **fields) -> None:
        """Append one record to the ring (host dict append — cheap
        enough for every step; no device sync implied)."""
        if not self.enabled:
            return
        self._ring.append(
            {"kind": kind, "time": round(self.clock(), 3), **fields}
        )

    def dump(self, reason: str) -> Optional[str]:
        """Write the dump; never raises. → path or None."""
        if not self.enabled:
            return None
        path = self.path
        try:
            os.makedirs(self.directory, exist_ok=True)
            extras: dict[str, Any] = {}
            for name, fn in self._providers.items():
                try:
                    extras[name] = fn()
                except Exception as e:  # noqa: BLE001 — never kill a dump
                    extras[name] = {"provider_error": type(e).__name__}
            doc = _sanitize(
                {
                    "reason": reason,
                    "rank": self.rank,
                    "dumped_at": round(self.clock(), 3),
                    "dumps": self._dumps + 1,
                    "context": self._context,
                    **({"extras": extras} if extras else {}),
                    "records": list(self._ring),
                }
            )
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
            self._dumps += 1
            return path
        except Exception:  # noqa: BLE001 — dump() IS the error path
            # Not just OSError: surrogate-escaped env bytes can make
            # json/f.write raise UnicodeEncodeError (a ValueError),
            # and this runs inside signal handlers and except blocks
            # where a second exception destroys the graceful exit.
            return None


def load_dump(path: str) -> dict:
    """Read a dump back (tests, tooling); plain json.load with a
    schema sanity check naming the file on violation."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "records" not in doc or "reason" not in doc:
        raise ValueError(f"{path}: not a flight-recorder dump")
    return doc

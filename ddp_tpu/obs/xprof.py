"""Compiled-program observability: what did XLA actually build?

Everything else in ``ddp_tpu.obs`` measures from the host side (wall
clocks, loss reads) or computes by hand (analytic FLOPs estimators,
the zero strategy's ring-model ``comm_bytes``). The compiler already
knows the ground truth: ``jit(f).lower(args).compile()`` exposes
``cost_analysis()`` (XLA-counted FLOPs, bytes accessed) and
``memory_analysis()`` (argument/output/temp/generated-code bytes), the
optimized HLO names every collective with its payload shape, and TPU
devices expose live ``memory_stats()``. This module surfaces all of
it:

- :class:`Xprof` — instruments a jitted callable so the compile the
  hot path was going to pay anyway happens in OUR hands: the wrapper
  owns the signature→executable cache (ahead-of-time
  ``lower().compile()``, then dispatch through the compiled object —
  bit-identical results, ONE compile per signature, donation
  preserved), and each compile is recorded as a ledger entry carrying
  the function label, arg-shape signature, compile wall-time,
  XLA-measured FLOPs/bytes-accessed, the full memory breakdown, and
  the per-kind collective payload parsed from the optimized HLO.
  Recompiles become attributable events: label + shape-diff vs the
  previous signature + compile seconds (obs/steptime.py attaches them
  to the step that paid).
- :class:`DeviceMemorySampler` — per-step device-memory high-water /
  headroom: ``device.memory_stats()`` where the runtime provides it
  (TPU), live-buffer accounting over ``jax.live_arrays()`` elsewhere
  (the ``parallel/zero.opt_bytes_per_device`` convention — per-shard
  bytes on each device, max over devices).
- cross-checks — :func:`ring_collective_traffic` converts HLO payload
  shapes into the same ring model ``zero_comm_bytes`` prices, so the
  hand ledger is validated against the compiled program
  (:meth:`Xprof.comm_check`); ``Xprof.measured_flops`` validates the
  analytic MFU estimators (tests/test_xprof.py pins per-family
  tolerance bands).

Disabled mode is FREE, the tracer's discipline: ``instrument`` returns
the caller's function object unchanged (not a wrapper), the sampler
returns ``{}``, and nothing imports beyond this module's top level —
pinned by tests.

Instrumentation is a diagnosis mode like ``--trace_dir``: dispatching
through ``Compiled`` objects skips jit's C++ fast path, so expect a
few extra microseconds of host overhead per step while ``--xprof`` is
on.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

# Bytes per element for the HLO shape grammar (f32[8,28]{1,0} etc).
_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# `%name = SHAPES op-name(...)` in optimized HLO. SHAPES is one shape
# or a tuple of them. Async collectives appear as a `-start`/`-done`
# pair: the `-start` result is a TUPLE that aliases the operand
# buffer(s) alongside the destination (counting it would overstate
# the payload ~2x), while the `-done` result is exactly the
# collective's result — so `-done` is counted and `-start` skipped
# (sync ops, with no suffix, count their own result).
_COLLECTIVE_OPS = (
    "all-reduce", "reduce-scatter", "all-gather", "all-to-all",
    "collective-permute",
)
# The shapes group must admit TPU post-optimization layouts — tiling
# and memory-space annotations like f32[1024,8]{1,0:T(8,128)} or
# f32[512]{0:S(1)} carry uppercase letters and parens (a char class
# without them silently parses ZERO collectives on exactly the
# backend this exists for). The lazy match stays anchored by the
# literal op-name keyword, so widening it cannot over-consume.
_COLLECTIVE_RE = re.compile(
    r"=\s*(\(?[a-zA-Z0-9\[\]{},():*\s]+?\)?)\s+("
    + "|".join(_COLLECTIVE_OPS)
    + r")(-start|-done)?\("
)
# The `%name` defining the instruction, scanned BACKWARD from a
# collective match: async `-done` ops reference their `-start` by this
# name, which is how the done's bytes re-join the start's groups.
_DEF_NAME_RE = re.compile(r"%([\w.\-]+)\s*$")
_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")
_SHAPE_RE = re.compile(r"(pred|[suf]\d+|bf16|c\d+)\[([0-9,]*)\]")
# The two HLO spellings of group membership: explicit nested braces
# (`replica_groups={{0,1},{2,3}}`) and the iota/v2 form
# (`replica_groups=[2,2]<=[4]` — reshape iota(4) to [2,2], each row a
# group — optionally with a transpose, `<=[2,2]T(1,0)`).
_GROUPS_EXPLICIT_RE = re.compile(r"replica_groups=\{(\{[0-9,{}]*\})\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?"
)


def _shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _HLO_DTYPE_BYTES.get(dtype, 4)


def _parse_replica_groups(line: str) -> Optional[list[list[int]]]:
    """The collective's replica groups from its HLO line, or None when
    the op carries none (= one group of the whole world)."""
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        import numpy as np

        gshape = [int(x) for x in m.group(1).split(",")]
        rshape = [int(x) for x in m.group(2).split(",")]
        ids = np.arange(int(np.prod(rshape))).reshape(rshape)
        if m.group(3):
            perm = [int(x) for x in m.group(3).split(",")]
            ids = ids.transpose(perm)
        return [
            [int(v) for v in row]
            for row in ids.reshape(-1).reshape(gshape)
        ]
    m = _GROUPS_EXPLICIT_RE.search(line)
    if m:
        groups = []
        for grp in re.findall(r"\{([0-9,\s]*)\}", m.group(1)):
            ids = [int(v) for v in grp.split(",") if v.strip()]
            if ids:
                groups.append(ids)
        return groups or None
    return None


def parse_hlo_collectives(hlo_text: str) -> dict[str, dict]:
    """Optimized-HLO text → per-kind ``{count, result_bytes, ops}``.

    ``result_bytes`` sums each collective's RESULT shape(s): the full
    array for all-reduce/all-gather, the 1/N shard for reduce-scatter
    — :func:`ring_collective_traffic` converts to wire traffic.

    ``ops`` lists each instance as ``{result_bytes, groups}`` where
    ``groups`` is the parsed ``replica_groups`` membership (None = the
    whole world in one group). SUBGROUP collectives — the hierarchical
    zero step's within-slice scatter and cross-slice shard exchange —
    ring-model over their own group size, and the membership is what
    :func:`hlo_axis_traffic` attributes to ICI vs DCN. Async pairs:
    the ``-start`` op carries the attributes but its tuple result
    aliases the operand, so the groups are recorded at ``-start``
    keyed by its instruction NAME and the bytes counted at the
    ``-done`` that references that name as its operand — an overlapped
    schedule may retire dones out of start order, so FIFO pairing
    would cross-wire groups (positional fallback only when the
    operand reference is unresolvable).
    """
    out: dict[str, dict] = {}
    pending: dict[str, dict] = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        shapes, op, suffix = m.group(1), m.group(2), m.group(3)
        bol = hlo_text.rfind("\n", 0, m.start()) + 1
        eol = hlo_text.find("\n", m.end())
        line = hlo_text[m.end() : eol if eol >= 0 else len(hlo_text)]
        if suffix == "-start":
            named = _DEF_NAME_RE.search(hlo_text[bol : m.start()].rstrip())
            key = named.group(1) if named else f"?{len(pending)}"
            pending.setdefault(op, {})[key] = _parse_replica_groups(line)
            continue  # its tuple aliases the operand; `-done` counts
        if suffix == "-done":
            queued = pending.get(op, {})
            ref = _OPERAND_NAME_RE.search(line)
            if ref is not None and ref.group(1) in queued:
                groups = queued.pop(ref.group(1))
            elif queued:  # unresolvable reference: oldest pending
                groups = queued.pop(next(iter(queued)))
            else:
                groups = _parse_replica_groups(line)
        else:
            groups = _parse_replica_groups(line)
        total = sum(
            _shape_bytes(dt, dims) for dt, dims in _SHAPE_RE.findall(shapes)
        )
        ent = out.setdefault(
            op, {"count": 0, "result_bytes": 0, "ops": []}
        )
        ent["count"] += 1
        ent["result_bytes"] += total
        ent["ops"].append({"result_bytes": total, "groups": groups})
    return out


def _op_ring_bytes(op: str, result_bytes: int, group: int) -> int:
    """One collective instance → per-replica ring traffic over its own
    group: all-reduce 2·(g−1)/g of the full bytes, all-gather (g−1)/g
    of its (full) result, reduce-scatter (g−1)·its (shard) result,
    permute one hop."""
    if group <= 1:
        return 0
    frac = (group - 1) / group
    if op == "all-reduce":
        return int(2 * frac * result_bytes)
    if op == "reduce-scatter":
        return int((group - 1) * result_bytes)
    if op == "collective-permute":
        return int(result_bytes)
    return int(frac * result_bytes)  # all-gather / all-to-all


def ring_collective_traffic(
    collectives: dict[str, dict], world: int
) -> dict[str, int]:
    """HLO result bytes → per-replica ring traffic, the model
    ``parallel/zero.zero_comm_bytes`` prices. Subgroup-aware: an op
    whose ``replica_groups`` name a smaller group ring-models over
    THAT size (groups absent = one ring over ``world``), so the
    hierarchical step's within-slice and cross-slice collectives each
    price over their own fabric's group.
    """
    traffic = {}
    for op, key in (
        ("all-reduce", "all_reduce"),
        ("all-gather", "all_gather"),
        ("reduce-scatter", "reduce_scatter"),
        ("collective-permute", "collective_permute"),
        ("all-to-all", "all_to_all"),
    ):
        ent = collectives.get(op, {})
        ops = ent.get("ops")
        if ops is None:
            # Pre-extension dict (stored records): aggregate math.
            ops = [
                {"result_bytes": ent.get("result_bytes", 0), "groups": None}
            ] if ent else []
        traffic[key] = sum(
            _op_ring_bytes(
                op,
                o["result_bytes"],
                len(o["groups"][0]) if o.get("groups") else world,
            )
            for o in ops
        )
    traffic["total"] = sum(traffic.values())
    return traffic


def hlo_axis_traffic(
    collectives: dict[str, dict], *, slice_size: int, world: int
) -> dict[str, dict[str, int]]:
    """Ring traffic split by fabric: ``ici`` (every group stays inside
    one slice block) vs ``dcn`` (any group spans slices).

    Replica ids group into contiguous per-slice blocks of
    ``slice_size`` because the mesh's ``dcn`` axis is OUTERMOST
    (runtime/mesh.py ``slice_block_size``) — so id//slice_size is the
    slice, and a group with members in two slices rides the slow
    fabric. Ops without groups span the world: dcn iff
    ``world > slice_size``.
    """
    out = {
        "ici": {"total": 0}, "dcn": {"total": 0},
    }
    for op, key in (
        ("all-reduce", "all_reduce"),
        ("all-gather", "all_gather"),
        ("reduce-scatter", "reduce_scatter"),
        ("collective-permute", "collective_permute"),
        ("all-to-all", "all_to_all"),
    ):
        for axis in out:
            out[axis].setdefault(key, 0)
        for o in collectives.get(op, {}).get("ops", []):
            groups = o.get("groups")
            if groups:
                g = len(groups[0])
                crossing = any(
                    len({i // max(1, slice_size) for i in grp}) > 1
                    for grp in groups
                )
            else:
                g = world
                crossing = world > slice_size
            b = _op_ring_bytes(op, o["result_bytes"], g)
            axis = "dcn" if crossing else "ici"
            out[axis][key] += b
            out[axis]["total"] += b
    return out


def _leaf_sig(leaf) -> str:
    dtype = getattr(leaf, "dtype", None)
    shape = getattr(leaf, "shape", None)
    if dtype is None or shape is None:
        return type(leaf).__name__
    short = (
        str(dtype)
        .replace("bfloat", "bf").replace("float", "f")
        .replace("uint", "u").replace("int", "i")
        .replace("bool", "pred").replace("complex", "c")
    )
    return f"{short}[{','.join(str(d) for d in shape)}]"


def shape_signature(args: tuple) -> str:
    """Human-readable arg-shape signature: ``f32[8,28,28,1]|i32[8]``
    over the FLATTENED leaves (pytree args summarize as leaf count +
    total elements — a 50-leaf param tree must not make the ledger
    unreadable)."""
    import jax

    parts = []
    for a in args:
        leaves = jax.tree_util.tree_leaves(a)
        if len(leaves) == 1:
            parts.append(_leaf_sig(leaves[0]))
        else:
            elems = sum(
                int(getattr(l, "size", 0) or 0) for l in leaves
            )
            parts.append(f"tree({len(leaves)} leaves, {elems} elems)")
    return "|".join(parts)


def shape_diff(old: str, new: str) -> str:
    """Positional diff of two signatures: ``arg2: i32[8]->i32[16]``."""
    olds, news = old.split("|"), new.split("|")
    diffs = [
        f"arg{i}: {o}->{n}"
        for i, (o, n) in enumerate(zip(olds, news))
        if o != n
    ]
    if len(olds) != len(news):
        diffs.append(f"arity: {len(olds)}->{len(news)}")
    return "; ".join(diffs) or "(identical signature)"


@dataclass
class ProgramProfile:
    """One compiled executable's ledger entry (JSON-ready via
    :meth:`record`)."""

    label: str
    signature: str
    compile_time_s: float
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    memory: dict = field(default_factory=dict)
    collectives: dict = field(default_factory=dict)
    shape_diff: Optional[str] = None  # vs the label's previous compile
    fallback: bool = False  # observe-only (no AOT introspection)
    calls: int = 0
    # Host-side facts XLA can't see (e.g. the effective Pallas block_k
    # after divisor fallback), attached via :meth:`Xprof.annotate`.
    notes: dict = field(default_factory=dict)

    def record(self) -> dict:
        out = {
            "label": self.label,
            "signature": self.signature,
            "compile_time_s": round(self.compile_time_s, 4),
            "calls": self.calls,
        }
        if self.notes:
            out["notes"] = dict(self.notes)
        if self.flops is not None:
            out["flops"] = self.flops
        if self.bytes_accessed is not None:
            out["bytes_accessed"] = self.bytes_accessed
        if self.memory:
            out["memory"] = dict(self.memory)
        if self.collectives:
            out["collectives"] = dict(self.collectives)
        if self.shape_diff:
            out["shape_diff"] = self.shape_diff
        if self.fallback:
            out["fallback"] = True
        return out


def _introspect(compiled) -> tuple[Optional[float], Optional[float], dict]:
    """(flops, bytes_accessed, memory breakdown) from a Compiled.

    Every accessor is best-effort: introspection must never turn a
    working program into a crash (backends may return None or raise
    on any of these)."""
    flops = bytes_accessed = None
    try:
        ca = compiled.cost_analysis()
        ca0 = ca[0] if isinstance(ca, (list, tuple)) else ca
        if ca0:
            f = ca0.get("flops")
            flops = float(f) if f is not None else None
            b = ca0.get("bytes accessed")
            bytes_accessed = float(b) if b is not None else None
    except Exception:  # noqa: BLE001 — introspection is best-effort
        pass
    memory: dict = {}
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            for key in (
                "argument_size_in_bytes",
                "output_size_in_bytes",
                "temp_size_in_bytes",
                "alias_size_in_bytes",
                "generated_code_size_in_bytes",
            ):
                v = getattr(ma, key, None)
                if v is not None:
                    memory[key.replace("_size_in_bytes", "_bytes")] = int(v)
    except Exception:  # noqa: BLE001
        pass
    return flops, bytes_accessed, memory


class _Instrumented:
    """The enabled-mode wrapper: owns the signature→executable cache.

    Dispatch path: key the FLATTENED avals (shape/dtype/weak_type/
    sharding — exactly what jit's cache keys, so a weak-type or
    sharding change recompiles here too, attributed instead of
    silent); on miss, ``lower().compile()`` under a timer, introspect,
    ledger, then call the compiled object. ``_cache_size()`` mirrors
    jit's for the serve engine's static-shape pins.

    Callables without ``.lower`` (epoch-runner closures) fall back to
    observe-only: the first call per signature is timed as a whole
    (compile + run — flagged ``fallback`` in the ledger, honest about
    what was measurable) and later calls pass straight through.
    """

    def __init__(self, xprof: "Xprof", fn: Callable, label: str):
        self._xprof = xprof
        self._fn = fn
        self.label = label
        self._compiled: dict = {}
        self._aot = hasattr(fn, "lower")

    def _key(self, args: tuple):
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(args)
        return (
            treedef,
            tuple(
                (
                    getattr(l, "shape", None),
                    str(getattr(l, "dtype", type(l).__name__)),
                    bool(getattr(l, "weak_type", False)),
                    getattr(l, "sharding", None),
                )
                for l in leaves
            ),
        )

    def _cache_size(self) -> int:
        return len(self._compiled)

    def __getattr__(self, name):
        # Delegate everything the wrapper doesn't own (e.g. an epoch
        # runner's steps_per_epoch attribute). Deliberately NOT
        # ``lower``: re-instrumenting a wrapper must not build a
        # second AOT layer.
        if name == "lower":
            raise AttributeError(name)
        return getattr(self._fn, name)

    def __call__(self, *args):
        key = self._key(args)
        hit = self._compiled.get(key)
        if hit is not None:
            hit[1].calls += 1
            return hit[0](*args) if self._aot else self._fn(*args)
        if not self._aot:
            t0 = time.perf_counter()
            out = self._fn(*args)
            profile = self._xprof._record_compile(
                self, args, time.perf_counter() - t0, compiled=None
            )
            self._compiled[key] = (None, profile)
            return out
        t0 = time.perf_counter()
        compiled = self._fn.lower(*args).compile()
        dt = time.perf_counter() - t0
        profile = self._xprof._record_compile(self, args, dt, compiled=compiled)
        self._compiled[key] = (compiled, profile)
        return compiled(*args)


class Xprof:
    """Compile ledger + recompile event stream for instrumented
    programs.

    ``enabled=False`` (the default everywhere) is free:
    ``instrument`` hands back the caller's function object itself —
    not a wrapper — so the disabled hot path is the uninstrumented
    hot path, byte for byte (pinned by tests).
    """

    MAX_EVENTS = 1024
    MAX_LEDGER = 512

    def __init__(self, *, enabled: bool = False):
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        # Append-only, one entry per COMPILE: two compiles can share a
        # shape signature (the dispatch cache also keys weak_type and
        # sharding), and a keyed ledger would overwrite the first —
        # dropping its compile seconds and making the exported
        # compile_seconds_total counter go backwards. Bounded like the
        # event deque: a recompile storm — the exact pathology this
        # diagnoses — must not grow the process (or every flight-
        # recorder dump / /stats payload that embeds the ledger)
        # without limit, so old entries are evicted while the
        # program_count / compile-seconds counters below stay monotone
        # accumulators that survive eviction.
        self._ledger: deque[ProgramProfile] = deque(maxlen=self.MAX_LEDGER)
        self._program_count = 0
        self._total_compile_s = 0.0
        # Last signature per label, for shape_diff on recompile.
        self._last_sig: dict[str, str] = {}
        # Per-label annotation dicts (see :meth:`annotate`).
        self._notes: dict[str, dict] = {}
        self._events: deque = deque(maxlen=self.MAX_EVENTS)
        self.event_seq = 0

    # ---- instrumentation --------------------------------------------

    def instrument(self, fn: Callable, label: str) -> Callable:
        """Wrap ``fn`` (ideally a jit wrapper) for ledgered compiles;
        identity when disabled."""
        if not self.enabled:
            return fn
        return _Instrumented(self, fn, label)

    def annotate(self, label: str, **fields) -> None:
        """Attach host-known facts to a label's ledger entries.

        XLA's introspection can't see decisions made before lowering —
        the effective Pallas ``block_k`` after divisor fallback, a
        host-resolved bucket width, a dtype chosen by a knob. Callers
        record them here; the fields ride every subsequent (and, for
        robustness, every already-ledgered) compile record under a
        ``notes`` key, so the tuner and humans read one surface. No-op
        when disabled — the free-when-disabled contract holds.
        """
        if not self.enabled:
            return
        with self._lock:
            merged = dict(self._notes.get(label, {}))
            merged.update(fields)
            self._notes[label] = merged
            for p in self._ledger:
                if p.label == label:
                    p.notes.update(fields)

    def _record_compile(
        self, inst: _Instrumented, args: tuple, dt: float, *, compiled
    ) -> ProgramProfile:
        sig = shape_signature(args)
        if compiled is not None:
            flops, bytes_accessed, memory = _introspect(compiled)
            try:
                collectives = parse_hlo_collectives(compiled.as_text())
            except Exception:  # noqa: BLE001
                collectives = {}
        else:
            flops = bytes_accessed = None
            memory, collectives = {}, {}
        with self._lock:
            prev = self._last_sig.get(inst.label)
            profile = ProgramProfile(
                label=inst.label,
                signature=sig,
                compile_time_s=dt,
                flops=flops,
                bytes_accessed=bytes_accessed,
                memory=memory,
                collectives=collectives,
                shape_diff=shape_diff(prev, sig) if prev is not None else None,
                fallback=compiled is None,
                calls=1,
                notes=dict(self._notes.get(inst.label, {})),
            )
            self._ledger.append(profile)
            self._program_count += 1
            self._total_compile_s += dt
            self._last_sig[inst.label] = sig
            self.event_seq += 1
            self._events.append((self.event_seq, profile.record()))
        return profile

    # ---- reading the ledger -----------------------------------------

    def events_after(self, seq: int) -> tuple[int, list[dict]]:
        """Compile events with sequence > ``seq`` → (new cursor,
        events). The attribution/metrics readers each keep their own
        cursor, so neither consumes the other's view."""
        with self._lock:
            out = [dict(ev) for s, ev in self._events if s > seq]
            return self.event_seq, out

    def ledger_records(self) -> list[dict]:
        """JSON-ready ledger (flight-recorder dumps, bench records) —
        the most recent ``MAX_LEDGER`` compiles."""
        with self._lock:
            return [p.record() for p in self._ledger]

    @property
    def program_count(self) -> int:
        with self._lock:
            return self._program_count

    @property
    def total_compile_s(self) -> float:
        with self._lock:
            return self._total_compile_s

    def measured_flops(self, label: str) -> Optional[float]:
        """XLA-counted FLOPs of the label's most recent compile (the
        analytic-estimator cross-check input)."""
        with self._lock:
            for p in reversed(self._ledger):
                if p.label == label and p.flops is not None:
                    return p.flops
        return None

    def label_collectives(self, label: str) -> Optional[dict]:
        """Raw parsed collectives of the label's most recent AOT
        compile (the per-axis attribution input), or None."""
        with self._lock:
            for p in reversed(self._ledger):
                if p.label == label and not p.fallback:
                    return p.collectives
        return None

    def collective_traffic(
        self, label: str, world: int
    ) -> Optional[dict[str, int]]:
        """Ring-model per-replica traffic of the label's most recent
        compile, or None when nothing compiled (or no collectives)."""
        coll = self.label_collectives(label)
        return (
            ring_collective_traffic(coll, world)
            if coll is not None
            else None
        )

    def comm_check(
        self,
        label: str,
        expected_total: int,
        world: int,
        *,
        tolerance: float = 0.05,
        expected_by_axis: Optional[dict] = None,
        slice_size: Optional[int] = None,
    ) -> Optional[dict]:
        """Hand-ledger vs HLO: does ``expected_total`` (e.g. the zero
        strategy's ``zero_comm_bytes`` estimate) match the compiled
        program's ring traffic within ``tolerance``? None until the
        label compiles; otherwise a JSON-ready verdict.

        ``expected_by_axis`` + ``slice_size`` extend the verdict per
        fabric (the hierarchical zero claim): each axis's analytic
        total (``zero_comm_bytes``'s ``by_axis[...]["total"]``) is
        checked against the replica-group-attributed HLO traffic
        (:func:`hlo_axis_traffic`) under the same tolerance, and the
        overall ``within_tolerance`` requires every axis to hold."""
        measured = self.collective_traffic(label, world)
        if measured is None:
            return None
        ratio = (
            measured["total"] / expected_total if expected_total else None
        )
        if expected_total:
            within = ratio is not None and abs(ratio - 1.0) <= tolerance
        else:
            # Expected zero (world 1, or a collective-free strategy):
            # the check passes iff the program is indeed collective-
            # free — a nonzero measurement against a zero estimate is
            # exactly the drift this exists to catch.
            within = measured["total"] == 0
        out = {
            "label": label,
            "expected_comm_bytes": int(expected_total),
            "measured_comm_bytes": measured["total"],
            "measured_by_kind": {
                k: v for k, v in measured.items() if k != "total" and v
            },
            "ratio": round(ratio, 4) if ratio is not None else None,
            "within_tolerance": within,
        }
        if expected_by_axis is not None and slice_size:
            coll = self.label_collectives(label)
            split = hlo_axis_traffic(
                coll or {}, slice_size=slice_size, world=world
            )
            by_axis = {}
            for axis, exp in expected_by_axis.items():
                exp_total = int(
                    exp["total"] if isinstance(exp, dict) else exp
                )
                got = split.get(axis, {}).get("total", 0)
                aratio = got / exp_total if exp_total else None
                # A small ABSOLUTE slack on top of the ratio band: the
                # scalar loss/accuracy/norm reductions (a few 4-byte
                # all-reduces) ride whichever fabric their pmean spans
                # and are not part of the analytic shard-payload model
                # — at real bucket sizes they are noise, but against a
                # small per-axis expectation they would fail the pure
                # ratio test spuriously.
                awithin = (
                    abs(aratio - 1.0) <= tolerance
                    or abs(got - exp_total) <= 64
                    if aratio is not None
                    else got <= 64
                )
                by_axis[axis] = {
                    "expected_comm_bytes": exp_total,
                    "measured_comm_bytes": int(got),
                    "ratio": (
                        round(aratio, 4) if aratio is not None else None
                    ),
                    "within_tolerance": awithin,
                }
                out["within_tolerance"] = (
                    out["within_tolerance"] and awithin
                )
            out["by_axis"] = by_axis
        return out


# ---- device memory: high-water and headroom ---------------------------


def max_device_buffer_bytes(arrays) -> int:
    """Max over local devices of the bytes the given jax.Arrays' live
    shards actually hold there (per-shard accounting over the real
    shardings: replicated arrays count in full on every device,
    sharded arrays 1/N). THE one definition of this convention —
    ``parallel/zero.opt_bytes_per_device`` (the bench's opt-memory
    ratio) and the sampler's live-buffer fallback both call it, so
    the two can never drift. Deleted/donated arrays are skipped."""
    per: dict[Any, int] = {}
    for arr in arrays:
        try:
            shards = arr.addressable_shards
        except Exception:  # noqa: BLE001 — deleted/donated arrays
            continue
        for s in shards:
            n = 1
            for d in s.data.shape:
                n *= int(d)
            per[s.device] = per.get(s.device, 0) + n * arr.dtype.itemsize
    return max(per.values(), default=0)


class DeviceMemorySampler:
    """Per-step HBM high-water/headroom, host-side and sync-free.

    TPU runtimes expose ``device.memory_stats()`` (bytes_in_use /
    peak_bytes_in_use / bytes_limit); backends without it (CPU) fall
    back to live-buffer accounting — per-device bytes of every
    ``jax.live_arrays()`` shard, the ``opt_bytes_per_device``
    convention — with the high-water tracked across samples by this
    object (and no limit, so headroom is honestly absent rather than
    invented). ``enabled=False`` samples nothing and returns ``{}``.
    """

    def __init__(self, *, enabled: bool = False, devices=None):
        self.enabled = bool(enabled)
        self._devices = devices
        self._high_water = 0
        self._source: Optional[str] = None

    def _live_buffer_bytes(self) -> int:
        import jax

        return max_device_buffer_bytes(jax.live_arrays())

    def sample(self) -> dict:
        """One sample → ``{hbm_used_bytes, hbm_high_water_bytes,
        hbm_limit_bytes?, hbm_headroom_frac?, hbm_source}`` (max over
        local devices). ``{}`` when disabled."""
        if not self.enabled:
            return {}
        import jax

        devices = self._devices if self._devices is not None else jax.local_devices()
        used = peak = limit = None
        for d in devices:
            try:
                stats = d.memory_stats()
            except Exception:  # noqa: BLE001 — backend without stats
                stats = None
            if not stats:
                continue
            u = int(stats.get("bytes_in_use", 0))
            p = int(stats.get("peak_bytes_in_use", u))
            lim = stats.get("bytes_limit")
            used = u if used is None else max(used, u)
            peak = p if peak is None else max(peak, p)
            if lim:
                limit = int(lim) if limit is None else max(limit, int(lim))
        if used is not None:
            self._source = "memory_stats"
            self._high_water = max(self._high_water, peak or used)
        else:
            self._source = "live_buffers"
            used = self._live_buffer_bytes()
            self._high_water = max(self._high_water, used)
        out = {
            "hbm_used_bytes": int(used),
            "hbm_high_water_bytes": int(self._high_water),
            "hbm_source": self._source,
        }
        if limit:
            out["hbm_limit_bytes"] = int(limit)
            out["hbm_headroom_frac"] = round(
                max(0.0, 1.0 - self._high_water / limit), 6
            )
        return out

    @property
    def high_water_bytes(self) -> int:
        return self._high_water

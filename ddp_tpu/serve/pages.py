"""Paged-KV page pool + radix prefix index (host-side bookkeeping).

The fixed-lane ``SlotCache`` pays N full prefills and N cache copies
for N requests sharing a system prompt (PAPERS.md #1 names prefix
caching, not per-chip decode, as where TPU serving loses today). The
paged layout breaks that coupling: K/V live in a pool of
``page_size``-token **pages** ([depth, num_pages, page_size, H_kv,
Dh], models/generate.PagedSlotCache) and each decode lane maps pages
through an int32 page table — so two lanes whose prompts share a
prefix can map the SAME pages copy-free, and a completed prompt's
pages stay resident as a cached prefix for future requests.

This module is the engine's allocator + index, all host-side Python
(no JAX): the device only ever sees the page-table int32 arrays the
engine uploads at bind/retire time.

- :class:`PrefixCache` — refcounted page allocator fused with a
  **page-granular radix trie** over token ids. Each trie edge is one
  full page's token tuple, so a node's path from the root spells the
  exact token prefix (and therefore the exact absolute positions)
  whose K/V its page holds — the property that makes reuse sound:
  matching the path guarantees the cached bytes are what a fresh
  prefill of those tokens would have written.
- **Refcounts** count live lane mappings. A page with refcount 0 that
  a trie node owns is *cached* (resident, evictable); one owned by no
  node returns to the free list at unmap. Invariant (pinned by the
  property test): a mapped lane maps its whole path, so a parent's
  refcount never drops below a child's — which is what makes subtree
  eviction safe.
- **LRU eviction**: allocation pressure evicts the least-recently-
  matched cached page *and its whole subtree* (a child prefix is
  unreachable without its parent). Matching touches the full path, so
  ancestors are always at least as fresh as descendants.

Page 0 is the reserved **scratch page**: idle lanes' all-zero page
tables read and write it, warmup chunks land in it, and
out-of-demand pad positions fall into it — it is never allocated,
never indexed, never attended by a live lane (live tables never
contain 0 inside a lane's demand region).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional


def page_demand(
    prompt_len: int,
    max_new_tokens: int,
    page_size: int,
    *,
    total_len: int,
    reserve: int = 0,
) -> int:
    """Pages a lane must own to serve a request → ceil division.

    ``reserve`` is the speculative-decoding γ-1 write reserve (PR 10:
    a verify round writes γ rows per lane, so the last round may
    overshoot the emission budget by up to γ-1 positions) — in paged
    mode the reserve is accounted in PAGES here, not just in the
    scheduler's token ceiling, so a verify-round write can never land
    in an unowned (scratch) page.
    """
    need = min(total_len, prompt_len + max_new_tokens + max(0, reserve))
    return -(-need // page_size)


class _Node:
    """One radix-trie node: owns one page, keyed by its page's token
    tuple under its parent."""

    __slots__ = ("key", "page_id", "parent", "children")

    def __init__(self, key, page_id, parent):
        self.key = key
        self.page_id = page_id
        self.parent = parent
        self.children: dict[tuple, "_Node"] = {}


class PrefixCache:
    """Refcounted page pool + radix prefix index over ``num_pages``
    pages of ``page_size`` tokens (page 0 reserved as scratch).

    The engine calls exactly three things: :meth:`acquire` at lane
    bind (match cached prefix pages + allocate the private remainder,
    evicting LRU cached prefixes under pressure), :meth:`release` at
    retire (publish the prompt's full pages into the index, unmap
    everything), and :meth:`stats` for the gauges. All operations are
    O(pages touched); the trie walk is O(prompt/page_size).
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved "
                f"scratch page), got {num_pages}"
            )
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = num_pages
        self.page_size = page_size
        # Pop from the tail so pages allocate in ascending id order
        # (deterministic tables make the identity tests readable).
        self._free = list(range(num_pages - 1, 0, -1))
        self._refcount: dict[int, int] = {}
        # Evictable set: indexed pages at refcount 0, LRU-ordered
        # (oldest first — OrderedDict move_to_end on touch).
        self._cached: "OrderedDict[int, None]" = OrderedDict()
        self._node_of: dict[int, _Node] = {}
        self._root = _Node(key=None, page_id=0, parent=None)
        # Counters for the gauges (monotone; the engine exposes them).
        self.hit_requests = 0
        self.miss_requests = 0
        self.hit_tokens = 0
        self.prompt_tokens = 0
        self.evicted_pages = 0
        # Pages installed from ANOTHER replica's pool (PrefixCache
        # .adopt — the disaggregation/prefix-tier migration path);
        # 0 on every non-disaggregated engine.
        self.adopted_pages = 0

    # ---- derived state (the /statusz + /metricsz gauges) ------------

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def resident_pages(self) -> int:
        """Pages holding live K/V: mapped by a lane or cached in the
        index (everything but free + scratch)."""
        return self.num_pages - 1 - len(self._free)

    @property
    def cached_pages(self) -> int:
        return len(self._cached)

    @property
    def shared_pages(self) -> int:
        """Pages mapped by two or more lanes at once — the copy-free
        fork count."""
        return sum(1 for rc in self._refcount.values() if rc >= 2)

    @property
    def mapped_pages(self) -> int:
        """Unique pages currently mapped by at least one lane."""
        return len(self._refcount)

    @property
    def mapped_page_refs(self) -> int:
        """Σ refcounts: what the lane-copies baseline would have to
        keep resident — mapped_page_refs / mapped_pages (unique) is
        the effective-slots multiplier the engine exports."""
        return sum(self._refcount.values())

    def hit_rate(self) -> Optional[float]:
        """Token-level prefix-hit rate over all acquires, None before
        any traffic."""
        if not self.prompt_tokens:
            return None
        return self.hit_tokens / self.prompt_tokens

    # ---- internals --------------------------------------------------

    def _chunks(self, tokens) -> list[tuple]:
        ps = self.page_size
        return [
            tuple(tokens[i : i + ps])
            for i in range(0, len(tokens) - ps + 1, ps)
        ]

    def _touch(self, node: _Node) -> None:
        """Refresh LRU recency. The ordering lives ENTIRELY in the
        ``_cached`` OrderedDict (append on unmap, move_to_end here,
        evict from the front) — there is deliberately no per-node
        timestamp to drift out of sync with it."""
        if node.page_id in self._cached:
            self._cached.move_to_end(node.page_id)

    def _map(self, pid: int) -> None:
        self._refcount[pid] = self._refcount.get(pid, 0) + 1
        self._cached.pop(pid, None)  # pinned while mapped

    def _unmap(self, pid: int) -> None:
        rc = self._refcount.get(pid, 0)
        if rc <= 0:
            raise RuntimeError(f"unmap of unmapped page {pid}")
        if rc > 1:
            self._refcount[pid] = rc - 1
            return
        del self._refcount[pid]
        if pid in self._node_of:
            self._cached[pid] = None  # evictable cached prefix
        else:
            self._free.append(pid)

    def _evict_subtree(self, node: _Node) -> int:
        """Free ``node``'s page and every descendant's → pages freed.

        Sound because a lane maps its whole path: refcount(parent) >=
        refcount(child), so an evictable (refcount-0) node's subtree
        is refcount-0 throughout — asserted, never assumed.
        """
        freed = 0
        stack = [node]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            pid = n.page_id
            if self._refcount.get(pid, 0) > 0:  # pragma: no cover
                raise RuntimeError(
                    f"eviction reached mapped page {pid}: the "
                    "path-refcount invariant is broken"
                )
            self._cached.pop(pid, None)
            del self._node_of[pid]
            self._free.append(pid)
            freed += 1
        if node.parent is not None:
            del node.parent.children[node.key]
        self.evicted_pages += freed
        return freed

    def _alloc(self, n: int) -> Optional[list[int]]:
        """Take ``n`` free pages, evicting LRU cached prefixes as
        needed; None (nothing taken) when the pool cannot satisfy.

        The feasibility check comes FIRST: free + cached is exactly
        the attainable maximum (an evictable node's whole subtree is
        itself refcount-0 cached), so an unsatisfiable demand —
        a page-starved bind that will requeue and retry every step —
        must return None without evicting anything. Draining the
        prefix cache for an allocation that cannot succeed would
        collapse the hit rate for all other traffic while the head
        waits.
        """
        if len(self._free) + len(self._cached) < n:
            return None
        while len(self._free) < n:
            oldest = next(iter(self._cached))
            self._evict_subtree(self._node_of[oldest])
        return [self._free.pop() for _ in range(n)]

    # ---- engine surface ---------------------------------------------

    def match(self, tokens, max_pages: int) -> list[int]:
        """Longest cached prefix at page granularity → page ids
        (touches the matched path for LRU)."""
        pids: list[int] = []
        node = self._root
        for key in self._chunks(tokens)[:max_pages]:
            child = node.children.get(key)
            if child is None:
                break
            self._touch(child)
            pids.append(child.page_id)
            node = child
        return pids

    def acquire(
        self, tokens, demand_pages: int
    ) -> Optional[tuple[list[int], int]]:
        """Bind-time entry: → (page ids for the lane's table, matched
        token count), or None when the pool cannot satisfy the demand
        even after evicting every unpinned cached prefix (the caller
        requeues the request — page-based admission backpressure).

        The match is capped at ``(len(tokens) - 1) // page_size``
        pages so at least one prompt token always remains to prefill:
        the request's first output token must be sampled from a real
        forward pass (there is no cached-logits shortcut), the same
        reason vLLM never matches a whole prompt.
        """
        cap = min(max(0, (len(tokens) - 1) // self.page_size),
                  demand_pages)
        matched = self.match(tokens, cap)
        # Map matched pages BEFORE allocating: mapping pins them, so
        # the allocation's LRU eviction can never free the prefix just
        # matched.
        for pid in matched:
            self._map(pid)
        new = self._alloc(demand_pages - len(matched))
        if new is None:
            for pid in matched:  # roll back — nothing acquired
                self._unmap(pid)
            return None
        for pid in new:
            self._map(pid)
        n_hit = len(matched) * self.page_size
        self.prompt_tokens += len(tokens)
        self.hit_tokens += n_hit
        if matched:
            self.hit_requests += 1
        else:
            self.miss_requests += 1
        return matched + new, n_hit

    def adopt(
        self, tokens
    ) -> Optional[tuple[list[int], list[tuple[int, int]]]]:
        """Host an EXTERNALLY-prefilled prefix (disaggregation's
        install path, PR 16): → (page ids spelling the whole prefix
        path, [(page ordinal, page id)] for the pages that are NEW
        here — the K/V bytes the caller must copy into the pool), or
        None when the pool cannot host the missing pages even after
        LRU eviction (the caller skips the install; the request just
        prefills locally).

        Only FULL pages adopt (``len(tokens) // page_size`` — the
        same publish rule as :meth:`release`). Pages already on the
        path are kept (their bytes are identical by the trie-path
        property — same tokens, same positions, same K/V) and only
        touched for LRU; the allocation pins the existing path first,
        so eviction pressure can never free the prefix being extended.
        New pages enter the index CACHED at refcount 0 — exactly the
        state :meth:`release` leaves published pages in — so the next
        local :meth:`acquire` maps them as an ordinary prefix hit.
        Adoption counts toward no hit/miss counters: it is supply,
        not demand.
        """
        keys = self._chunks(tokens)
        node = self._root
        have: list[int] = []
        for key in keys:
            child = node.children.get(key)
            if child is None:
                break
            self._touch(child)
            have.append(child.page_id)
            node = child
        missing = keys[len(have):]
        if not missing:
            return have, []
        for pid in have:  # pin the existing path across the alloc
            self._map(pid)
        new = self._alloc(len(missing))
        for pid in have:
            self._unmap(pid)
        if new is None:
            return None
        pids = list(have)
        fill: list[tuple[int, int]] = []
        for ordinal, (key, pid) in enumerate(
            zip(missing, new), start=len(have)
        ):
            child = _Node(key=key, page_id=pid, parent=node)
            node.children[key] = child
            self._node_of[pid] = child
            self._cached[pid] = None  # published, evictable
            node = child
            pids.append(pid)
            fill.append((ordinal, pid))
        self.adopted_pages += len(fill)
        return pids, fill

    def release(
        self, tokens, page_ids: list[int], prefilled_tokens: int
    ) -> None:
        """Retire-time entry: publish the prompt's fully-written pages
        into the index, then unmap every page the lane held.

        Only FULL pages of PROMPT tokens are publishable — decode
        output is request-private, and a page is only valid once every
        one of its ``page_size`` positions was actually prefilled
        (``prefilled_tokens`` caps mid-prefill evictions). A node that
        already exists keeps its page (two lanes that missed
        concurrently on the same prompt converge on the first
        publisher; the second's duplicate page simply frees at unmap).
        """
        n_pub = min(prefilled_tokens, len(tokens)) // self.page_size
        node = self._root
        for key, pid in zip(self._chunks(tokens)[:n_pub], page_ids):
            child = node.children.get(key)
            if child is None:
                if pid in self._node_of:  # pragma: no cover
                    raise RuntimeError(
                        f"page {pid} already owned by another prefix"
                    )
                child = _Node(key=key, page_id=pid, parent=node)
                node.children[key] = child
                self._node_of[pid] = child
            self._touch(child)
            node = child
        for pid in page_ids:
            self._unmap(pid)

    def stats(self) -> dict:
        hr = self.hit_rate()
        return {
            "page_size": self.page_size,
            "num_pages": self.num_pages,
            "pages_free": self.free_pages,
            "pages_resident": self.resident_pages,
            "pages_cached": self.cached_pages,
            "pages_shared": self.shared_pages,
            "prefix_hits": self.hit_requests,
            "prefix_misses": self.miss_requests,
            "prefix_hit_rate": None if hr is None else round(hr, 4),
            "evicted_pages": self.evicted_pages,
            # Absent until a migration installs pages: the pre-disagg
            # stats surface stays byte-identical (PR-16 convention).
            **(
                {"adopted_pages": self.adopted_pages}
                if self.adopted_pages
                else {}
            ),
        }

    def check_invariants(self) -> None:
        """Allocator soundness (the property test's oracle): free,
        mapped and cached partition the non-scratch pool; no page is
        free while mapped; every cached page has an index node."""
        free = set(self._free)
        mapped = set(self._refcount)
        cached = set(self._cached)
        assert not (free & mapped), f"freed while mapped: {free & mapped}"
        assert not (free & cached), f"freed while cached: {free & cached}"
        assert not (mapped & cached), (
            f"cached while mapped: {mapped & cached}"
        )
        indexed = set(self._node_of)
        assert cached <= indexed, "cached page without an index node"
        assert indexed <= (mapped | cached), (
            "index node owns a freed page: "
            f"{indexed - mapped - cached}"
        )
        accounted = free | mapped | cached
        assert accounted == set(range(1, self.num_pages)), (
            f"page leak: {set(range(1, self.num_pages)) - accounted}"
        )
        assert all(rc > 0 for rc in self._refcount.values())

"""Continuous-batching decode engine over fixed slots.

The TPU serving problem (PAPERS.md #1/#5 regime): requests arrive at
arbitrary times with arbitrary prompt/output lengths, but XLA wants
ONE compiled program per shape. The resolution is the standard
continuous-batching design (Orca/vLLM lineage) restricted to fully
static shapes:

- the engine owns S decode **slots** — lanes of one SlotCache
  (models/generate.py) sized [depth, S, total_len, H_kv, Dh] at
  startup, never reshaped;
- every engine step advances ALL S lanes by one token
  (``slot_decode_step`` — one compiled program, mixed-age batch);
- a finished/evicted slot is **refilled** in place: the queue head is
  prefilled at one fixed padded width (``prefill_slot``) and spliced
  into the freed lane (``write_slot``) while the other lanes keep
  decoding on the next step;
- therefore the engine compiles exactly THREE programs (prefill,
  decode, splice) at warmup, and a varied request mix — staggered
  arrivals, different lengths, evictions — triggers **zero further
  compilation** (pinned by tests/test_serve.py via the jit cache
  counters this class exposes in ``compile_counts``).

Scheduling policy lives in serve/scheduler.py (admission, FIFO,
deadlines); this module is the data plane plus per-request
bookkeeping. Observability flows through utils/metrics.MetricsWriter:
``serve_step`` records (queue depth, slot occupancy, evictions) and
``serve_request`` records (status, TTFT, decode tokens/s) land in the
same JSONL stream the trainer writes.

Sampling: greedy (temperature 0, the correctness-pinned path — token-
identical to models/generate.generate) or host-side temperature
sampling with a per-request numpy PRNG (deliberately NOT the jitted
``jax.random`` path: per-request keys would either recompile per mix
or burn a [S]-wide key tensor for mostly-greedy traffic).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ddp_tpu.models.generate import (
    init_slot_cache,
    prefill_slot,
    slot_decode_step,
    write_slot,
)
from ddp_tpu.models.lm import LMSpec
from ddp_tpu.obs.tracer import Tracer
from ddp_tpu.serve.scheduler import Admission, Request, Scheduler
from ddp_tpu.utils.metrics import MetricsWriter, StatSummary

# Completion statuses.
COMPLETE = "complete"
TIMEOUT_EVICTED = "timeout_evicted"  # deadline hit while decoding
TIMEOUT_QUEUE = "timeout_queue"  # deadline hit while queued


@dataclass
class Completion:
    """One finished request: everything the frontend returns."""

    rid: int
    status: str
    prompt: list[int]
    tokens: list[int]
    ttft: float  # seconds, submit → first token ready
    decode_seconds: float  # first token → finish
    submitted: float
    finished: float

    @property
    def decode_tokens_per_s(self) -> float:
        n = len(self.tokens) - 1  # tokens after the prefill token
        return n / self.decode_seconds if self.decode_seconds > 0 else 0.0


@dataclass
class _Slot:
    """Host-side bookkeeping for one lane."""

    request: Optional[Request] = None
    tokens: list[int] = field(default_factory=list)
    first_token_at: float = 0.0
    rng: Optional[np.random.Generator] = None

    @property
    def free(self) -> bool:
        return self.request is None


class ServeEngine:
    """Fixed-slot continuous-batching engine for one causal LM.

    ``slots`` and ``prefill_len`` fix the static shapes (prefill_len
    defaults to half the position table — prompts longer than it are
    rejected at admission, budget for decode is what remains).
    ``clock`` is injectable for deterministic tests; MetricsWriter
    ``metrics`` may be shared with a trainer's stream or omitted.
    """

    def __init__(
        self,
        spec: LMSpec,
        params: Any,
        *,
        slots: int = 4,
        prefill_len: Optional[int] = None,
        max_queue: int = 64,
        metrics: Optional[MetricsWriter] = None,
        tracer: Optional[Tracer] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        prefill_len = prefill_len or max(1, spec.total_len // 2)
        if not 0 < prefill_len <= spec.total_len - 1:
            raise ValueError(
                f"prefill_len {prefill_len} must leave room to decode "
                f"inside total_len {spec.total_len}"
            )
        self.spec = spec
        self.params = params
        self.num_slots = slots
        self.prefill_len = prefill_len
        self.clock = clock
        self.metrics = metrics or MetricsWriter(None)
        # Span tracing (ddp_tpu.obs): prefill/refill/decode device
        # work lands on the host timeline; disabled by default and
        # pinned free when off. Serving "goodput" here is device-busy
        # over wall since engine start — the idle-poll complement of
        # slot occupancy, published via stats()/statusz.
        self.tracer = tracer or Tracer()
        self._started_at = clock()
        self._productive_s = 0.0
        self.scheduler = Scheduler(
            max_queue=max_queue,
            prefill_len=prefill_len,
            total_len=spec.total_len,
            vocab_size=spec.vocab_size,
            clock=clock,
        )
        self._slots = [_Slot() for _ in range(slots)]
        self._cache = init_slot_cache(spec, slots)
        self._tokens = np.zeros((slots,), np.int32)
        self._completed: dict[int, Completion] = {}
        self._steps = 0
        self.ttft = StatSummary()
        self.decode_rate = StatSummary()
        # The engine's entire compiled surface: three programs, built
        # once here. Slot index / length / positions are traced, so
        # no request mix can grow this set after warmup.
        self._prefill = jax.jit(
            lambda p, prompt, n: prefill_slot(spec, p, prompt, n)
        )
        # The cache argument is DONATED in both cache-threading
        # programs: the engine always overwrites self._cache with the
        # result, and without donation XLA must preserve the input, so
        # every decoded token would re-materialize the full
        # [depth, S, total_len, H_kv, Dh] KV buffer (2× serving HBM +
        # a copy per step). Same reason models/generate.py's scan
        # donates its cache carry.
        self._decode = jax.jit(
            lambda p, cache, toks: slot_decode_step(spec, p, cache, toks),
            donate_argnums=(1,),
        )
        # A fresh lambda (like the two above), NOT jax.jit(write_slot):
        # jit tracing caches are shared per function object, so a bare
        # write_slot wrapper would count OTHER engines' compilations in
        # this engine's compile_counts — the static-shape pin must be
        # per-engine.
        self._splice = jax.jit(
            lambda c, s, k, v, n: write_slot(c, s, k, v, n),
            donate_argnums=(0,),
        )

    # ---- frontend surface ------------------------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        seed: int = 0,
        timeout: Optional[float] = None,
    ) -> Admission:
        """Admission-checked enqueue; rejections carry a reason."""
        adm = self.scheduler.submit(
            prompt,
            max_new_tokens,
            temperature=temperature,
            seed=seed,
            timeout=timeout,
        )
        if not adm.accepted:
            self.metrics.write(
                "serve_reject",
                reason=adm.reason,
                queue_depth=self.scheduler.depth,
            )
        return adm

    def result(self, rid: int) -> Optional[Completion]:
        """The finished record for ``rid``, None while pending."""
        return self._completed.get(rid)

    def pop_result(self, rid: int) -> Optional[Completion]:
        return self._completed.pop(rid, None)

    @property
    def active(self) -> int:
        return sum(1 for s in self._slots if not s.free)

    @property
    def pending(self) -> bool:
        return self.active > 0 or self.scheduler.depth > 0

    def compile_counts(self) -> dict[str, int]:
        """Compiled-program count per engine function (the static-
        shape pin: after warmup these must never grow)."""
        return {
            "prefill": self._prefill._cache_size(),
            "decode": self._decode._cache_size(),
            "splice": self._splice._cache_size(),
        }

    def goodput(self) -> dict:
        """Device-busy seconds over wall seconds since engine start."""
        wall = self.clock() - self._started_at
        return {
            "productive_s": round(self._productive_s, 4),
            "wall_s": round(wall, 4),
            "goodput": (
                round(self._productive_s / wall, 6) if wall > 0 else None
            ),
        }

    def stats(self) -> dict:
        """JSON-ready operational snapshot (the /stats endpoint)."""
        return {
            "slots": self.num_slots,
            "active": self.active,
            "queue_depth": self.scheduler.depth,
            "steps": self._steps,
            "completed": len(self._completed),
            "ttft_s": self.ttft.snapshot(),
            "decode_tokens_per_s": self.decode_rate.snapshot(),
            "compile_counts": self.compile_counts(),
            "goodput": self.goodput(),
        }

    # ---- engine loop ------------------------------------------------

    def step(self) -> int:
        """One engine iteration → number of live tokens produced.

        Order: (1) retire finished / evict expired running requests,
        (2) evict expired queued requests, (3) refill free slots from
        the queue (prefill produces each request's FIRST token), (4)
        one batched decode step over all slots. A slot refilled in (3)
        also decodes in (4) — continuous batching, no drain barrier.
        """
        now = self.clock()
        evictions = 0
        for slot in self._slots:
            req = slot.request
            if req is None:
                continue
            if len(slot.tokens) >= req.max_new_tokens:
                self._finish(slot, COMPLETE)
            elif req.expired(now):
                self._finish(slot, TIMEOUT_EVICTED)
                evictions += 1
        for req in self.scheduler.evict_expired():
            now2 = self.clock()
            self._completed[req.rid] = Completion(
                rid=req.rid, status=TIMEOUT_QUEUE, prompt=req.prompt,
                tokens=[], ttft=now2 - req.submitted, decode_seconds=0.0,
                submitted=req.submitted, finished=now2,
            )
            self._record_request(self._completed[req.rid])
            evictions += 1

        produced = 0
        for i, slot in enumerate(self._slots):
            if not slot.free or self.scheduler.depth == 0:
                continue
            req = self.scheduler.next_request()
            if req is None:
                break
            self._refill(i, slot, req)
            produced += 1

        if self.active:
            w0, t0 = self.clock(), time.perf_counter()
            logits, self._cache = self._decode(
                self.params, self._cache, jnp.asarray(self._tokens)
            )
            logits = np.asarray(logits)  # host sync: decode really done
            self._productive_s += self.clock() - w0
            self.tracer.complete(
                "serve.decode", t0, time.perf_counter() - t0, None
            )
            for i, slot in enumerate(self._slots):
                req = slot.request
                if req is None or len(slot.tokens) >= req.max_new_tokens:
                    # Idle lane, or a request whose budget the prefill
                    # token already filled — it retires next step; the
                    # lane's decode output is discarded.
                    continue
                tok = self._pick(slot, logits[i])
                slot.tokens.append(tok)
                self._tokens[i] = tok
                produced += 1

        self._steps += 1
        self.metrics.write(
            "serve_step",
            step=self._steps,
            queue_depth=self.scheduler.depth,
            active_slots=self.active,
            slot_occupancy=round(self.active / self.num_slots, 4),
            evictions=evictions,
            tokens=produced,
        )
        return produced

    def run(self, *, max_steps: Optional[int] = None) -> list[Completion]:
        """Drive ``step()`` until idle (or ``max_steps``) → completions
        retired during this call, in finish order."""
        before = set(self._completed)
        steps = 0
        # A request whose budget fills on a decode step retires at the
        # START of the next step, so ``pending`` stays true until the
        # retire pass has run — no trailing flush needed.
        while self.pending and (max_steps is None or steps < max_steps):
            self.step()
            steps += 1
        return sorted(
            (c for r, c in self._completed.items() if r not in before),
            key=lambda c: c.finished,
        )

    # ---- internals --------------------------------------------------

    def _refill(self, index: int, slot: _Slot, req: Request) -> None:
        """Prefill ``req`` into lane ``index``; emits the first token."""
        pad = self.prefill_len - len(req.prompt)
        padded = jnp.asarray(
            [req.prompt + [0] * pad], jnp.int32
        )
        traced = self.tracer.enabled
        w0, t0 = self.clock(), time.perf_counter()
        logits, k, v = self._prefill(
            self.params, padded, jnp.int32(len(req.prompt))
        )
        if traced:
            # Only when measuring: the span must cover the device
            # compute, not just the async enqueue — otherwise prefill
            # cost is silently billed to the next decode span. The
            # untraced path stays fully async (the np.asarray in
            # _pick below is its natural sync point).
            jax.block_until_ready(k)
        t1 = time.perf_counter()
        self.tracer.complete(
            "serve.prefill", t0, t1 - t0,
            {"rid": req.rid, "prompt_len": len(req.prompt)}
            if traced
            else None,
        )
        self._cache = self._splice(
            self._cache, jnp.int32(index), k, v, jnp.int32(len(req.prompt))
        )
        if traced:
            jax.block_until_ready(self._cache)
        self._productive_s += self.clock() - w0
        self.tracer.complete(
            "serve.refill", t1, time.perf_counter() - t1,
            {"slot": index} if traced else None,
        )
        slot.request = req
        slot.tokens = []
        slot.rng = (
            np.random.default_rng(req.seed)
            if req.temperature > 0.0
            else None
        )
        tok = self._pick(slot, np.asarray(logits))
        slot.tokens.append(tok)
        self._tokens[index] = tok
        slot.first_token_at = self.clock()
        self.ttft.add(slot.first_token_at - req.submitted)

    def _pick(self, slot: _Slot, logits: np.ndarray) -> int:
        """Greedy argmax, or host-side temperature sampling."""
        req = slot.request
        if req.temperature <= 0.0:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / req.temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(slot.rng.choice(len(p), p=p))

    def _finish(self, slot: _Slot, status: str) -> None:
        req = slot.request
        now = self.clock()
        c = Completion(
            rid=req.rid,
            status=status,
            prompt=req.prompt,
            tokens=list(slot.tokens),
            ttft=slot.first_token_at - req.submitted,
            decode_seconds=now - slot.first_token_at,
            submitted=req.submitted,
            finished=now,
        )
        self._completed[req.rid] = c
        if len(c.tokens) > 1:
            self.decode_rate.add(c.decode_tokens_per_s)
        self._record_request(c)
        slot.request = None
        slot.tokens = []
        slot.rng = None

    def _record_request(self, c: Completion) -> None:
        self.metrics.write(
            "serve_request",
            rid=c.rid,
            status=c.status,
            prompt_len=len(c.prompt),
            new_tokens=len(c.tokens),
            ttft_s=round(c.ttft, 4),
            decode_tokens_per_s=round(c.decode_tokens_per_s, 2),
        )

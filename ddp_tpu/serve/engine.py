"""Continuous-batching decode engine over fixed slots.

The TPU serving problem (PAPERS.md #1/#5 regime): requests arrive at
arbitrary times with arbitrary prompt/output lengths, but XLA wants
ONE compiled program per shape. The resolution is the standard
continuous-batching design (Orca/vLLM lineage) restricted to fully
static shapes:

- the engine owns S decode **slots** — lanes of one SlotCache
  (models/generate.py) sized [depth, S, total_len, H_kv, Dh] at
  startup, never reshaped;
- every engine step advances ALL S lanes by one token AND samples
  each lane's next token on device (``slot_decode_sample_step`` — one
  compiled program, mixed-age batch, fused sampling);
- prompts are ingested by **chunked prefill** (Sarathi-style):
  ``prefill_chunk`` writes one power-of-two-bucketed chunk straight
  into the freed lane of the donated cache, co-scheduled with decode
  steps under a per-step token budget (serve/scheduler.plan_chunks),
  so running lanes never stall behind a long prompt;
- therefore the engine compiles a BOUNDED program set — one decode
  program plus one chunk program per bucket width — enumerable at
  ``warmup()``, after which a varied request mix (staggered arrivals,
  different lengths, evictions) triggers **zero further compilation**
  (pinned by tests/test_serve.py via ``compile_counts``).

The decode loop is **device-resident**: the only steady-state
device→host transfer is the [S] int32 token vector of the PREVIOUS
step, fetched after the current step's work has been dispatched
(dispatch step i+1, then retire step i's tokens while the device
computes) — no full-logits round-trip, no per-slot Python sampling
(both pinned by tests). The cache is donated through every program
(train/fast.py's convention) so XLA keeps one KV buffer; the token
vector deliberately is NOT donated — the host still owes a read of
the previous step's values.

Scheduling policy lives in serve/scheduler.py (admission, FIFO,
deadlines, chunk planning); this module is the data plane plus
per-request bookkeeping. Observability flows through
utils/metrics.MetricsWriter: ``serve_step`` records (queue depth,
slot occupancy, evictions, chunk tokens, dispatch/retire split) and
``serve_request`` records (status, TTFT, decode tokens/s) land in the
same JSONL stream the trainer writes; span tracing emits
``serve.prefill_chunk`` / ``serve.decode`` / ``serve.sample``.

Sampling: greedy (temperature 0, the correctness-pinned path — token-
identical to models/generate.generate) or seeded temperature/top-p
sampling fused into the jitted step via per-slot ``jax.random`` keys
(ALSO token-identical to a seeded ``generate()`` — same fold_in
stream, pinned by tests). ``top_k`` stays generate-only: its k is a
compiled shape, so per-request values would recompile per mix.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

# Aliased: ``prefill_chunk`` is also an engine CONFIG name (the chunk
# width __init__ parameter), which would shadow the function inside
# closures defined there.
from ddp_tpu.models.generate import (
    init_paged_slot_cache,
    init_slot_cache,
)
from ddp_tpu.models.generate import prefill_chunk as _prefill_chunk
from ddp_tpu.models.generate import (
    slot_decode_sample_step as _decode_sample,
)
from ddp_tpu.models.generate import slot_decode_step as _decode_step
from ddp_tpu.models.generate import slot_verify_step as _verify_step
from ddp_tpu.models.lm import LMSpec
from ddp_tpu.obs.tracer import Tracer
from ddp_tpu.serve.pages import PrefixCache, page_demand
from ddp_tpu.serve.scheduler import (
    Admission,
    Request,
    Scheduler,
    next_pow2,
    prev_pow2,
)
from ddp_tpu.utils.metrics import MetricsWriter, StatSummary

# Completion statuses.
COMPLETE = "complete"
TIMEOUT_EVICTED = "timeout_evicted"  # deadline hit while decoding
TIMEOUT_QUEUE = "timeout_queue"  # deadline hit while queued
REJECTED_TOO_LONG = "rejected_too_long"  # slipped past the front door


@dataclass
class Completion:
    """One finished request: everything the frontend returns.

    ``ttft`` is None for requests that never produced a token (queue
    timeouts, mid-prefill evictions, refill-time rejections) — they
    must not pollute the TTFT summaries with queue-wait times.
    """

    rid: int
    status: str
    prompt: list[int]
    tokens: list[int]
    ttft: Optional[float]  # seconds, submit → first token observed
    decode_seconds: float  # first token → finish
    submitted: float
    finished: float
    # Speculative decoding only: fraction of draft proposals the
    # target accepted over this request's verify rounds (None on the
    # non-speculative path, or before any round ran).
    spec_acceptance: Optional[float] = None
    # Seconds queued before a lane bound the request (None for
    # requests evicted from the queue — they never bound).
    queue_s: Optional[float] = None
    # Request-trace digest (obs/reqtrace.py): trace id + queue/
    # prefill/decode split + spec stats. None with tracing off.
    trace: Optional[dict] = None
    # Paged-KV prefix reuse only (PR 12): prompt tokens served from
    # cached prefix pages — zero prefill compute paid for them. None
    # on fixed-lane engines; 0 = paged but missed.
    prefix_hit_tokens: Optional[int] = None

    @property
    def decode_tokens_per_s(self) -> float:
        n = len(self.tokens) - 1  # tokens after the prefill token
        return n / self.decode_seconds if self.decode_seconds > 0 else 0.0

    @property
    def tpot_s(self) -> Optional[float]:
        """Time per output token (decode only) — the per-request SLI
        behind the tpot_p50 objective; None before a second token."""
        n = len(self.tokens) - 1
        if n <= 0 or self.decode_seconds <= 0:
            return None
        return self.decode_seconds / n


@dataclass
class _Slot:
    """Host-side bookkeeping for one lane."""

    # Lane index, fixed at engine construction. Identity, not a
    # field-equality lookup: two freshly-reset slots compare equal
    # under the generated __eq__, so list.index() would be wrong.
    index: int = -1
    request: Optional[Request] = None
    tokens: list[int] = field(default_factory=list)
    # Tokens SCHEDULED on device for this request, including ones whose
    # values the host has not fetched yet (len(tokens) lags by the
    # in-flight step). Retirement decisions use this — counts are
    # host-known at dispatch time, values are not.
    emitted: int = 0
    prefill_pos: int = 0  # prompt tokens ingested so far
    first_token_at: Optional[float] = None  # None = no token observed
    queue_s: Optional[float] = None  # submit → lane bind wait
    # Speculative-decoding tallies for this occupancy (host-side —
    # the verify round's matched counts are fetched anyway).
    spec_drafted: int = 0
    spec_accepted: int = 0
    # Paged mode only: the page ids this lane's table maps (prefix +
    # private, in position order) and how many leading prompt tokens
    # came from cached prefix pages.
    pages: list[int] = field(default_factory=list)
    matched_tokens: int = 0

    @property
    def free(self) -> bool:
        return self.request is None

    @property
    def prefilling(self) -> bool:
        return (
            self.request is not None
            and self.prefill_pos < len(self.request.prompt)
        )

    @property
    def decoding(self) -> bool:
        return (
            self.request is not None
            and self.prefill_pos >= len(self.request.prompt)
        )


def drain_eta_s(
    retire_times: list[float], depth: int
) -> Optional[float]:
    """Seconds until ``depth`` queued requests drain at the measured
    retirement rate, from a window of retire clock times.

    The backpressure Retry-After derivation (pure — unit-testable
    with synthetic clocks): (count-1) retirements over the window's
    span give requests/second; depth over that rate is the ETA. None
    when the window can't support a rate (fewer than two retires, or
    a same-instant burst) — callers fall back to a static hint. An
    empty queue still returns a positive beat (one retirement
    period): the 429 raced a retire, and "retry immediately" is how
    thundering herds start.
    """
    if len(retire_times) < 2:
        return None
    span = retire_times[-1] - retire_times[0]
    if span <= 0:
        return None
    rate = (len(retire_times) - 1) / span
    return max(1, depth) / rate


def resolve_engine_knobs(
    spec: LMSpec,
    *,
    slots: int = 4,
    prefill_len: Optional[int] = None,
    prefill_chunk: Optional[int] = None,
    min_bucket: Optional[int] = None,
    step_token_budget: Optional[int] = None,
    decode_attn: str = "auto",
    kv_dtype: str = "fp32",
    page_size: int = 0,
    kv_pages: Optional[int] = None,
    spec_tokens: int = 0,
    draft_spec: Optional[LMSpec] = None,
    has_draft_params: bool = False,
) -> dict:
    """Validate + resolve the engine's knob surface — the SINGLE rule
    set for what configurations are constructible.

    ``ServeEngine.__init__`` consumes this verbatim, and the autotuner
    (``ddp_tpu.tune``) uses it as the validity predicate for proposed
    configs: a candidate is proposable iff this returns — so the tuner
    can never propose a config the CLI would reject, by construction
    rather than by a parallel re-implementation of the rules. Raises
    the same ``ValueError`` messages the engine always raised; returns
    the resolved values (defaults filled, pow2 snapping and caps
    applied) the engine assigns.
    """
    if slots < 1:
        raise ValueError(f"slots must be >= 1, got {slots}")
    prefill_len = prefill_len or max(1, spec.total_len // 2)
    if not 0 < prefill_len <= spec.total_len - 1:
        raise ValueError(
            f"prefill_len {prefill_len} must leave room to decode "
            f"inside total_len {spec.total_len}"
        )
    # Decode-attention impl (ops/decode.py): resolved ONCE, like
    # best_attention — the flash-decode Pallas kernel on TPU, the
    # bit-identical jnp reference elsewhere; "flash" forces the
    # kernel (interpret mode off-TPU: how CPU tests pin token
    # identity).
    if decode_attn not in ("auto", "flash", "reference"):
        raise ValueError(
            f"decode_attn must be auto|flash|reference, got "
            f"{decode_attn!r}"
        )
    if decode_attn == "auto":
        decode_attn = (
            "flash"
            if jax.devices()[0].platform == "tpu"
            else "reference"
        )
    if kv_dtype not in ("fp32", "int8"):
        raise ValueError(
            f"kv_dtype must be fp32|int8, got {kv_dtype!r}"
        )
    if kv_pages is not None and not page_size:
        raise ValueError(
            "--kv_pages needs --page_size (the page pool only "
            "exists in paged mode)"
        )
    paged = bool(page_size)
    page_size = int(page_size)
    lane_pages = resolved_kv_pages = None
    if paged:
        if page_size < 1 or (page_size & (page_size - 1)):
            raise ValueError(
                f"--page_size must be a power of two, got "
                f"{page_size}"
            )
        if spec.total_len % page_size:
            raise ValueError(
                f"--page_size {page_size} must divide the model's "
                f"total_len {spec.total_len}: a partial tail page "
                "would break the page-granular tail-chunk "
                "invariant (every chunk write maps through whole "
                "pages)"
            )
        lane_pages = spec.total_len // page_size
        resolved_kv_pages = int(
            kv_pages
            if kv_pages is not None
            # Capacity-neutral default: the pool holds exactly the
            # fixed-lane layout's lines (+ the scratch page), so
            # any sharing is pure headroom.
            else slots * lane_pages + 1
        )
        if resolved_kv_pages < lane_pages + 1:
            raise ValueError(
                f"--kv_pages {resolved_kv_pages} cannot hold one "
                f"full-context lane: needs >= total_len/"
                f"--page_size + 1 scratch = {lane_pages + 1}"
                " (a maximal request could never bind — permanent "
                "queue head starvation)"
            )
    if spec_tokens:
        if spec_tokens < 1:
            raise ValueError(
                f"spec_tokens must be >= 1, got {spec_tokens}"
            )
        if draft_spec is None or not has_draft_params:
            raise ValueError(
                "speculative decoding needs draft_spec AND "
                "draft_params alongside spec_tokens"
            )
        if draft_spec.vocab_size != spec.vocab_size:
            raise ValueError(
                f"draft vocab {draft_spec.vocab_size} != target "
                f"vocab {spec.vocab_size}"
            )
        if draft_spec.total_len != spec.total_len:
            raise ValueError(
                f"draft total_len {draft_spec.total_len} != target "
                f"total_len {spec.total_len} (the caches track the "
                "same positions)"
            )
        if spec_tokens >= spec.total_len - prefill_len:
            raise ValueError(
                f"spec_tokens {spec_tokens} leaves no decode room "
                f"past prefill_len {prefill_len} in total_len "
                f"{spec.total_len}"
            )
    spec_tokens = int(spec_tokens)
    # Admission context ceiling: the verify round's K-1 reserve
    # comes off the budget check, never the cache geometry.
    ctx_len = spec.total_len - max(0, spec_tokens - 1)
    # Decode-path tokens dispatched per running lane per step: 1
    # plain, K under speculation (the verify round processes K
    # positions per lane — plan_chunks accounts them all).
    tokens_per_decode = max(1, spec_tokens)
    chunk = next_pow2(
        prefill_chunk
        if prefill_chunk
        else min(next_pow2(prefill_len), 64)
    )
    # A chunk's write region [start, start + width) must fit the
    # cache at start = 0 — cap at the largest pow2 <= total_len.
    chunk = min(chunk, prev_pow2(spec.total_len))
    # The smallest bucket must fit the cache at ANY admissible
    # start (max start = prefill_len - 1, so the space floor is
    # total_len - prefill_len + 1 >= 2): a wider bucket's pad
    # overhang would make dynamic_update_slice clamp the write
    # start and silently shift the chunk over live cache lines.
    min_bucket = min(
        chunk,
        next_pow2(min_bucket) if min_bucket else min(8, chunk),
        prev_pow2(spec.total_len - prefill_len + 1),
    )
    step_token_budget = (
        step_token_budget
        if step_token_budget
        else chunk + slots * tokens_per_decode
    )
    if step_token_budget < min_bucket + slots * tokens_per_decode:
        # Below this floor the prefill head can starve forever
        # while lanes decode (the budget never fits even the
        # smallest bucket after decode tokens are accounted).
        raise ValueError(
            f"step_token_budget {step_token_budget} cannot "
            f"sustain prefill progress: needs >= min_bucket "
            f"({min_bucket}) + slots ({slots}) x decode tokens "
            f"per lane ({tokens_per_decode})"
        )
    return {
        "slots": slots,
        "prefill_len": prefill_len,
        "decode_attn": decode_attn,
        "kv_dtype": kv_dtype,
        "paged": paged,
        "page_size": page_size,
        "lane_pages": lane_pages,
        "kv_pages": resolved_kv_pages,
        "spec_tokens": spec_tokens,
        "ctx_len": ctx_len,
        "tokens_per_decode": tokens_per_decode,
        "chunk": chunk,
        "min_bucket": min_bucket,
        "step_token_budget": step_token_budget,
    }


class ServeEngine:
    """Fixed-slot continuous-batching engine for one causal LM.

    ``slots`` fixes the decode batch shape; ``prefill_len`` is the
    admission ceiling for prompts (default half the position table).
    ``prefill_chunk`` (power of two, default min(pow2(prefill_len),
    64)) is the full chunk width prompts are ingested at;
    ``min_bucket`` floors the power-of-two bucket of the final partial
    chunk; ``step_token_budget`` bounds chunk-plus-decode tokens
    dispatched per step (default chunk + slots — one full-width chunk
    can ride along with a full decode batch). ``clock`` is injectable
    for deterministic tests; MetricsWriter ``metrics`` may be shared
    with a trainer's stream or omitted.

    ``page_size`` > 0 (power of two dividing ``spec.total_len``)
    switches the KV cache to the PAGED layout (PR 12): K/V live in a
    pool of ``kv_pages`` pages (default slots · total_len/page_size +
    1 — capacity-neutral vs fixed lanes, plus the scratch page) and
    each lane maps pages through an int32 table, so prompts sharing a
    prefix prefill it ONCE and fork the pages copy-free
    (serve/pages.PrefixCache: radix index, refcounts, LRU eviction of
    cached prefixes). Admission then accounts in free pages instead
    of lanes × ctx_len; outputs stay token-identical to the
    fixed-lane engine (pinned by tests/test_paged.py). Speculative
    decoding composes: the γ-1 write reserve is accounted in pages,
    while the DRAFT cache stays fixed-lane (it is lane-private —
    nothing to share); a prefix hit skips TARGET prefill for the
    matched tokens, which can only lower draft acceptance there,
    never correctness (verify guarantees the stream).
    """

    def __init__(
        self,
        spec: LMSpec,
        params: Any,
        *,
        slots: int = 4,
        prefill_len: Optional[int] = None,
        prefill_chunk: Optional[int] = None,
        min_bucket: Optional[int] = None,
        step_token_budget: Optional[int] = None,
        max_queue: int = 64,
        metrics: Optional[MetricsWriter] = None,
        tracer: Optional[Tracer] = None,
        clock: Callable[[], float] = time.monotonic,
        sanitize: bool = False,
        xprof=None,
        decode_attn: str = "auto",
        kv_dtype: str = "fp32",
        page_size: int = 0,
        kv_pages: Optional[int] = None,
        draft_spec: Optional[LMSpec] = None,
        draft_params: Any = None,
        spec_tokens: int = 0,
        reqtrace: bool = False,
        reqtrace_keep: int = 512,
        trace_seed: Optional[int] = None,
        slo=None,
        recorder=None,
        model_version: Optional[str] = None,
    ):
        # The whole knob surface validates + resolves through the
        # module-level resolver — the same rule set the autotuner's
        # validity predicates call, so tuner and CLI can never
        # disagree about what constructs.
        knobs = resolve_engine_knobs(
            spec,
            slots=slots,
            prefill_len=prefill_len,
            prefill_chunk=prefill_chunk,
            min_bucket=min_bucket,
            step_token_budget=step_token_budget,
            decode_attn=decode_attn,
            kv_dtype=kv_dtype,
            page_size=page_size,
            kv_pages=kv_pages,
            spec_tokens=spec_tokens,
            draft_spec=draft_spec,
            has_draft_params=draft_params is not None,
        )
        prefill_len = knobs["prefill_len"]
        self.decode_attn = knobs["decode_attn"]
        self.kv_dtype = knobs["kv_dtype"]
        # Paged KV + radix prefix reuse (PR 12, serve/pages.py):
        # --page_size > 0 flips the cache to the page-pool layout
        # (PagedSlotCache) and admission to free-page accounting.
        # 0 (the default) is the fixed-lane control — byte-identical
        # transfer shapes, compile counts and /metricsz exposition to
        # the pre-paging engine.
        self.paged = knobs["paged"]
        self.page_size = knobs["page_size"]
        if self.paged:
            self._lane_pages = knobs["lane_pages"]
            self.kv_pages = knobs["kv_pages"]
        # Speculative decoding: a draft LM proposes spec_tokens greedy
        # continuations per lane; the target verifies them in ONE
        # batched step (models/generate.slot_verify_step). The verify
        # round writes K = spec_tokens rows per lane, so admission
        # reserves K-1 cache lines (a lane one round short of budget
        # may overshoot its context by up to K-2 positions — reserved
        # rather than clamp-shifted over live lines).
        self.spec_tokens = knobs["spec_tokens"]
        self.draft_spec = draft_spec
        self.draft_params = draft_params
        ctx_len = knobs["ctx_len"]
        tokens_per_decode = knobs["tokens_per_decode"]
        chunk = knobs["chunk"]
        min_bucket = knobs["min_bucket"]
        self.spec = spec
        self.params = params
        # Model lifecycle (serve/lifecycle.py): the serving version
        # label (None keeps every surface byte-identical to the
        # pre-lifecycle engine), hot-swap counters, and the admission
        # pause that drains lanes to the swap barrier WITHOUT dropping
        # queued or newly-submitted work.
        self.model_version = model_version
        self.reloads_total = 0
        self.rollbacks_total = 0
        self._admission_paused = False
        self.num_slots = slots
        self.prefill_len = prefill_len
        self.prefill_chunk = chunk
        self.min_bucket = min_bucket
        self._ctx_len = ctx_len
        self._tokens_per_decode = tokens_per_decode
        self.step_token_budget = knobs["step_token_budget"]
        self.clock = clock
        self.metrics = metrics or MetricsWriter(None)
        # Span tracing (ddp_tpu.obs): chunk/decode device work plus the
        # sampled-token retirement land on the host timeline; disabled
        # by default and pinned free when off. When ENABLED, dispatches
        # block until ready so spans cover device compute — measuring
        # mode trades the pipeline overlap for span fidelity.
        self.tracer = tracer or Tracer()
        # Runtime sanitizer (--sanitize, runtime/sanitize.py): the
        # transfer guard arms around the steady-state DECODE dispatch
        # in step(), proving it does zero implicit host transfer. The
        # engine's two deliberate transfers — the chunk-argument
        # upload and the one-step-behind [S] int32 fetch — execute
        # OUTSIDE the guarded region (no allow() windows here, unlike
        # the trainer). Disabled = a free nullcontext, like the
        # tracer.
        from ddp_tpu.runtime.sanitize import Sanitizer

        self._sanitizer = Sanitizer(sanitize)
        self._started_at = clock()
        self._productive_s = 0.0
        # Per-request 64-bit trace-id space: deterministic when the
        # caller seeds it (tests), collision-free across replicas when
        # left to entropy (scripts/serve.py default) — merged fleet
        # traces must keep requests from different engines apart.
        import os as _os

        if trace_seed is None:
            trace_seed = int.from_bytes(_os.urandom(8), "little")
        self.scheduler = Scheduler(
            max_queue=max_queue,
            prefill_len=prefill_len,
            total_len=ctx_len,
            vocab_size=spec.vocab_size,
            chunk=chunk,
            min_bucket=min_bucket,
            token_budget=self.step_token_budget,
            trace_seed=trace_seed,
            clock=clock,
        )
        # Request-level distributed tracing (obs/reqtrace.py): OFF by
        # default and pinned free when off — every recording site
        # below guards on one `is not None` check, so a disabled
        # engine allocates no per-request trace state at all. Enabled,
        # events are stamped only at points the engine already touches
        # the host (the PR-3 transfer invariant holds under
        # --sanitize, re-pinned by tests/test_reqtrace.py).
        from ddp_tpu.obs.reqtrace import RequestTracer

        self._reqtrace = (
            RequestTracer(keep=reqtrace_keep) if reqtrace else None
        )
        # Fleet trace adoption accounting (ISSUE 19): valid inbound
        # contexts adopted vs malformed ones orphaned (counted, never
        # fatal); router-staged hop seconds parked per rid until the
        # completion's serve_request record picks them up.
        self.trace_propagated = 0
        self.trace_orphaned = 0
        self._request_hops: dict[int, dict] = {}
        # SLO engine (obs/slo.py): observes every retired request;
        # breach transitions land in the metrics stream AND the flight
        # recorder (the PR-4 post-mortem ring) before any caller hook.
        from ddp_tpu.obs.recorder import FlightRecorder, build_info

        self._recorder = recorder if recorder is not None else (
            FlightRecorder(None)
        )
        self._slo = slo
        self._user_breach_cb = None
        if slo is not None:
            prev = slo.on_breach
            # Re-attachment (a fresh engine over the same SLOEngine,
            # e.g. a restart loop): adopt the ORIGINAL caller hook,
            # not the dead engine's interceptor — chaining through it
            # would duplicate breach records into a retired engine's
            # metrics/flight streams.
            if (
                getattr(prev, "__func__", None)
                is ServeEngine._on_slo_breach
            ):
                prev = prev.__self__._user_breach_cb
            self._user_breach_cb = prev
            slo.on_breach = self._on_slo_breach
        self._build_info = build_info()
        # {min_bucket · 2^i} ∪ {chunk}: the whole compiled-width set.
        self.buckets = self.scheduler.bucket_list()
        self._slots = [_Slot(index=i) for i in range(slots)]
        cache_dtype = jnp.int8 if kv_dtype == "int8" else jnp.float32
        if self.paged:
            self._cache = init_paged_slot_cache(
                spec, slots,
                num_pages=self.kv_pages, page_size=self.page_size,
                dtype=cache_dtype,
            )
            self._prefix = PrefixCache(self.kv_pages, self.page_size)
            # Host mirror of the device page table: mutated at
            # bind/retire, uploaded (one [S, lane_pages] int32 array)
            # before the next dispatch — the steady-state decode loop
            # still transfers nothing but the [S] token vector.
            self._table_np = np.zeros(
                (slots, self._lane_pages), np.int32
            )
            self._table_dirty = False
            # Admission stalls where the FIFO head's page demand
            # outran the pool (requeued, retried next step).
            self.page_starved_binds = 0
        else:
            self._cache = init_slot_cache(
                spec, slots, dtype=cache_dtype,
            )
        # Device-resident token vector: output of the last decode (or
        # chunk splice), input to the next — the decode loop never
        # routes tokens through the host. NOT donated anywhere: the
        # host still owes an async read of the previous step's values.
        self._toks = jnp.zeros((slots,), jnp.int32)
        # Per-slot sampling state, ALSO device-resident: the chunk
        # program installs a request's (seed, temperature, top_p) at
        # its lane and the decode program advances the fold_in step
        # counters — the steady-state loop uploads NOTHING per step.
        # Seeds are int32 to hit exactly generate()'s
        # jax.random.key(seed) path.
        self._seeds = jnp.zeros((slots,), jnp.int32)
        self._sample_steps = jnp.zeros((slots,), jnp.int32)
        self._temps = jnp.zeros((slots,), jnp.float32)
        self._top_ps = jnp.ones((slots,), jnp.float32)
        # Device values dispatched but not yet read back:
        # ("first", scalar, slot) | ("decode", [S] array, lanes).
        self._pending: list[tuple[str, Any, Any]] = []
        self._completed: dict[int, Completion] = {}
        self._steps = 0
        self.ttft = StatSummary()
        self.decode_rate = StatSummary()
        self.step_latency = StatSummary()
        # User-facing latency SLIs, always on (two float appends per
        # request): queue wait (submit → lane bind) and TPOT (decode
        # seconds per output token) — what the SLO engine evaluates
        # and the fleet aggregator merges.
        self.queue_wait = StatSummary()
        self.tpot = StatSummary()
        # Monotone token counter (the aggregator's tokens/s source —
        # per-request rate summaries are not additive across a fleet).
        self.tokens_emitted_total = 0
        # Recent retirement clock times (bounded): the queue-drain-rate
        # window behind ``queue_drain_eta_s`` — what a backpressure
        # 429's Retry-After is derived from, so a rejected client (or
        # the fleet router) backs off for as long as the queue will
        # actually take to drain instead of hammering.
        self._retire_times: deque = deque(maxlen=32)
        # Monotone aggregate counters (the /metricsz exposition needs
        # totals, not just the JSONL event stream): admission rejects
        # by reason, finished requests by status.
        self.reject_counts: dict[str, int] = {}
        self.status_counts: dict[str, int] = {}
        # The engine's entire compiled surface: ONE decode program
        # (sampling fused) plus per bucket width one FIRST-chunk
        # program (self-contained causal attention — short prompts pay
        # bucket-sized compute, the monolithic-prefill cost) and one
        # CONTINUATION program (banded attention against the full
        # lane) — slot index / start / length / final / sampling
        # config are all traced, so no request mix can grow the set
        # past 2·len(buckets) + 1 after warmup(). Fresh lambdas (not
        # bare function objects): jit tracing caches are shared per
        # function object, and the static-shape pin must be
        # per-engine.
        def _chunk_fn(lane_attend, chunk_spec):
            return jax.jit(
                lambda p, c, t, se, sp, tm, tp, s, ch, st, ln, fi, sd,
                rtm, rtp: _prefill_chunk(
                    chunk_spec, p, c, t, se, sp, tm, tp, s, ch, st, ln,
                    fi, sd, rtm, rtp, lane_attend=lane_attend,
                ),
                donate_argnums=(1,),
            )

        # Compiled-program introspection (obs/xprof.py): when a live
        # Xprof is passed, the engine's whole program set dispatches
        # through its compile ledger — warmup() then enumerates every
        # program WITH its compile time, XLA FLOPs, and memory
        # breakdown, and /metricsz gains compile + HBM gauges. The
        # wrapper preserves _cache_size(), so the static-shape pins
        # (compile_counts frozen after warmup) hold either way. None =
        # uninstrumented, byte-identical to the pre-xprof engine.
        from ddp_tpu.obs.xprof import DeviceMemorySampler, Xprof

        self._xprof = xprof if xprof is not None else Xprof(enabled=False)
        self._hbm = DeviceMemorySampler(enabled=self._xprof.enabled)
        self._chunk_first = self._xprof.instrument(
            _chunk_fn(False, spec), "serve.prefill_first"
        )
        self._chunk_cont = self._xprof.instrument(
            _chunk_fn(True, spec), "serve.prefill_chunk"
        )
        impl = self.decode_attn
        self._decode = self._xprof.instrument(
            jax.jit(
                lambda p, c, t, sd, st, tm, tp: _decode_sample(
                    spec, p, c, t, sd, st, tm, tp, attn_impl=impl
                ),
                donate_argnums=(1,),
            ),
            # The label names the program actually built: recompile
            # culprits and /metricsz compile gauges distinguish the
            # kernel path from the jnp path.
            "serve.flash_decode" if impl == "flash" else "serve.decode",
        )
        if impl == "flash":
            # The kernel snaps block_k to the largest divisor of the
            # lane length (ops/decode.pick_block_k) — a host-side
            # decision XLA introspection can't see. Ledger it so the
            # tuner and humans read the EFFECTIVE block, not the
            # requested default. Paged lanes gather [pages ·
            # page_size] keys and block on the page itself.
            from ddp_tpu.ops.decode import pick_block_k

            requested = self.page_size if self.paged else 128
            lane_len = spec.total_len
            self._xprof.annotate(
                "serve.flash_decode",
                block_k_requested=requested,
                block_k=pick_block_k(lane_len, requested),
            )
        if self.spec_tokens:
            dspec = draft_spec
            # Draft-side machinery: its OWN cache (the draft tracks
            # the same token history at its own width) plus dummy
            # per-slot sampling state for the chunk signature — the
            # draft always proposes greedily, so none of it is read.
            self._draft_cache = init_slot_cache(dspec, slots)
            self._d_toks = jnp.zeros((slots,), jnp.int32)
            self._d_seeds = jnp.zeros((slots,), jnp.int32)
            self._d_steps = jnp.zeros((slots,), jnp.int32)
            self._d_temps = jnp.zeros((slots,), jnp.float32)
            self._d_top_ps = jnp.ones((slots,), jnp.float32)
            # Device-resident sync flags: the first draft step of a
            # round adopts the TARGET cache's per-lane positions (the
            # draft advanced spec_tokens last round, the target only
            # as far as acceptance went). Prebuilt so the sanitized
            # hot loop uploads nothing.
            self._sync_pos = jnp.asarray(True)
            self._keep_pos = jnp.asarray(False)

            def _draft_propose(p, c, t, pos, sync):
                c = c._replace(pos=jnp.where(sync, pos, c.pos))
                logits, c = _decode_step(
                    dspec, p, c, t, attn_impl=impl
                )
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), c

            self._draft_chunk_first = self._xprof.instrument(
                _chunk_fn(False, dspec), "serve.draft_prefill_first"
            )
            self._draft_chunk_cont = self._xprof.instrument(
                _chunk_fn(True, dspec), "serve.draft_prefill_chunk"
            )
            self._draft_decode = self._xprof.instrument(
                jax.jit(_draft_propose, donate_argnums=(1,)),
                "serve.draft_decode",
            )
            self._verify = self._xprof.instrument(
                jax.jit(
                    lambda p, c, t, dr, sd, st, tm, tp: _verify_step(
                        spec, p, c, t, dr, sd, st, tm, tp
                    ),
                    donate_argnums=(1,),
                ),
                "serve.spec_verify",
            )
        # Engine-lifetime speculative tallies (the /stats + bench
        # acceptance-rate source); zero-cost when speculation is off.
        self.spec_drafted_total = 0
        self.spec_accepted_total = 0
        self.accept_rate = StatSummary()

    # ---- frontend surface ------------------------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        top_p: float = 1.0,
        seed: int = 0,
        timeout: Optional[float] = None,
        trace: Optional[str] = None,
        hops: Optional[dict] = None,
        model: Optional[str] = None,
    ) -> Admission:
        """Admission-checked enqueue; rejections carry a reason.

        ``trace`` is an inbound fleet trace-context line (the router's
        ``00-<trace>-<span>-<parent>``): a VALID one is adopted — the
        request's trace id becomes the router's, its parent span the
        router attempt's — and counted ``trace_propagated``; a
        present-but-malformed one is counted ``trace_orphaned`` and
        the engine mints locally, exactly as if nothing arrived (a
        peer's garbage must never reject a request). ``hops`` is the
        router's staging hop seconds (queue/handoff/migrate), stamped
        onto this request's ``serve_request`` record so one record
        answers "which hop paid".
        """
        from ddp_tpu.obs.reqtrace import parse_trace_context

        adopted = None
        if trace is not None:
            adopted = parse_trace_context(trace)
            if adopted is None:
                self.trace_orphaned += 1
            else:
                self.trace_propagated += 1
        adm = self.scheduler.submit(
            prompt,
            max_new_tokens,
            temperature=temperature,
            top_p=top_p,
            seed=seed,
            timeout=timeout,
            trace_id=adopted[0] if adopted else None,
            model=model,
        )
        if not adm.accepted:
            self.reject_counts[adm.reason] = (
                self.reject_counts.get(adm.reason, 0) + 1
            )
            self.metrics.write(
                "serve_reject",
                reason=adm.reason,
                queue_depth=self.scheduler.depth,
            )
            return adm
        if hops:
            self._request_hops[adm.request.rid] = dict(hops)
        if self._reqtrace is not None:
            # The admit event: the request's 64-bit trace id exists
            # from this point on (assigned by the scheduler — or
            # adopted from the router), and the submit call is already
            # a host-side touch point.
            self._reqtrace.admit(
                adm.request.rid,
                adm.request.trace_id,
                parent=f"{adopted[1]:016x}" if adopted else None,
            )
        return adm

    def result(self, rid: int) -> Optional[Completion]:
        """The finished record for ``rid``, None while pending."""
        return self._completed.get(rid)

    def pop_result(self, rid: int) -> Optional[Completion]:
        return self._completed.pop(rid, None)

    @property
    def active(self) -> int:
        return sum(1 for s in self._slots if not s.free)

    @property
    def pending(self) -> bool:
        # Admission-paused (hot-swap barrier): queued work is not
        # steppable — only running lanes keep the loop hot, so a
        # paused engine with an empty batch idles instead of spinning
        # empty steps (and their serve_step records) while the swap's
        # host-side load runs.
        if self._admission_paused:
            return self.active > 0
        return self.active > 0 or self.scheduler.depth > 0

    def compile_counts(self) -> dict[str, int]:
        """Compiled-program count per engine function (the static-
        shape pin: after ``warmup()`` these must never grow;
        prefill_first and prefill_chunk are each bounded by
        ``len(self.buckets)``). Speculative engines add the draft
        chunk programs (same bucket bound), one draft-decode and one
        verify program."""
        counts = {
            "prefill_first": self._chunk_first._cache_size(),
            "prefill_chunk": self._chunk_cont._cache_size(),
            "decode": self._decode._cache_size(),
        }
        if self.spec_tokens:
            counts.update(
                draft_prefill_first=self._draft_chunk_first._cache_size(),
                draft_prefill_chunk=self._draft_chunk_cont._cache_size(),
                draft_decode=self._draft_decode._cache_size(),
                spec_verify=self._verify._cache_size(),
            )
        return counts

    def compile_budget(self) -> int:
        """The engine's whole-program-set ceiling: 2 chunk programs
        per bucket + 1 decode, doubled-chunks + draft-decode + verify
        when speculating — asserted by ``bench.py serve_decode`` and
        the static-shape tests."""
        base = 2 * len(self.buckets) + 1
        if self.spec_tokens:
            base += 2 * len(self.buckets) + 2
        return base

    def warmup(self) -> dict[str, int]:
        """Eagerly compile the engine's whole program set → counts.

        Per bucket width one first-chunk and one continuation-chunk
        program, plus the decode program — after this, steady state
        compiles NOTHING (the zero-recompilation pin's baseline, and
        a serving process's first-request latency is a decode step,
        not an XLA compile). Must run on an idle engine: warmup
        chunks write garbage K/V into lane 0, which the refill
        invariant (every line is overwritten before it becomes
        attendable) makes harmless only while no request owns a lane.
        """
        if self.active:
            raise RuntimeError("warmup() requires an idle engine")
        zero = jnp.int32(0)
        for fn in (self._chunk_first, self._chunk_cont):
            for w in self.buckets:
                (self._cache, self._toks, self._seeds,
                 self._sample_steps, self._temps, self._top_ps,
                 _) = fn(
                    self.params, self._cache, self._toks, self._seeds,
                    self._sample_steps, self._temps, self._top_ps,
                    zero, jnp.zeros((w,), jnp.int32), zero,
                    jnp.int32(w), jnp.asarray(False), zero,
                    jnp.float32(0.0), jnp.float32(1.0),
                )
        self._toks, self._cache, self._sample_steps = self._decode(
            self.params, self._cache, self._toks, self._seeds,
            self._sample_steps, self._temps, self._top_ps,
        )
        if self.spec_tokens:
            for fn in (self._draft_chunk_first, self._draft_chunk_cont):
                for w in self.buckets:
                    (self._draft_cache, self._d_toks, self._d_seeds,
                     self._d_steps, self._d_temps, self._d_top_ps,
                     _) = fn(
                        self.draft_params, self._draft_cache,
                        self._d_toks, self._d_seeds, self._d_steps,
                        self._d_temps, self._d_top_ps,
                        zero, jnp.zeros((w,), jnp.int32), zero,
                        jnp.int32(w), jnp.asarray(False), zero,
                        jnp.float32(0.0), jnp.float32(1.0),
                    )
            _, self._draft_cache = self._draft_decode(
                self.draft_params, self._draft_cache, self._toks,
                self._cache.pos, self._sync_pos,
            )
            (self._toks, self._cache, self._sample_steps, _t, _m
             ) = self._verify(
                self.params, self._cache, self._toks,
                jnp.zeros((self.num_slots, self.spec_tokens), jnp.int32),
                self._seeds, self._sample_steps, self._temps,
                self._top_ps,
            )
        jax.block_until_ready(self._toks)
        return self.compile_counts()

    # ---- model lifecycle (serve/lifecycle.py) -----------------------

    def pause_admission(self) -> None:
        """Stop BINDING queued requests to lanes (step() skips the
        admit phase) while running lanes decode to completion — the
        drain-to-a-barrier half of a hot-swap. Unlike the server's
        drain (503s new work to a replacement process), paused
        admission keeps accepting submissions into the queue: nothing
        is dropped across a swap, requests just wait it out."""
        self._admission_paused = True

    def resume_admission(self) -> None:
        self._admission_paused = False

    def install_params(
        self,
        params: Any,
        *,
        model_version: Optional[str] = None,
        invalidate_prefix: bool = False,
    ) -> None:
        """Atomically swap the serving weights in place.

        Requires a drained engine (``active == 0`` — pause admission
        and let the lanes retire first). The compiled program set is
        untouched: params are a per-dispatch argument (only the cache
        is donated), so a same-shaped tree swaps with ZERO
        recompilation — which is also why the tree must match the
        serving one exactly (structure, shapes, dtypes); a skew here
        raises before any state changes and the caller rolls back.
        The old leaves are released by reference drop, never
        ``.delete()``d — callers legitimately install the same tree
        they are serving (the token-identity drill) or hold the old
        tree for rollback.

        ``invalidate_prefix`` flushes the radix prefix index and page
        table (paged engines): cached K/V was computed under the OLD
        weights, so any version change must drop it; a same-version
        reinstall keeps the cache (and token identity) intact.
        """
        if self.active:
            raise RuntimeError(
                "install_params() requires a drained engine "
                f"({self.active} lanes still bound — pause admission "
                "and wait for retirement)"
            )
        old_leaves, old_def = jax.tree.flatten(self.params)
        new_leaves, new_def = jax.tree.flatten(params)
        if old_def != new_def:
            raise ValueError(
                "spec_skew: incoming parameter tree structure differs "
                "from the serving tree"
            )
        for old, new in zip(old_leaves, new_leaves):
            if (
                tuple(old.shape) != tuple(new.shape)
                or old.dtype != new.dtype
            ):
                raise ValueError(
                    f"spec_skew: leaf {tuple(new.shape)}/{new.dtype} "
                    f"!= serving {tuple(old.shape)}/{old.dtype}"
                )
        self.params = params
        if model_version is not None:
            self.model_version = model_version
        self.reloads_total += 1
        if invalidate_prefix and self.paged:
            # No lane owns pages at the barrier, so every mapped page
            # belongs to the (now stale) prefix index: rebuild it and
            # zero the host table — exactly the startup state, with
            # the pool's garbage bytes unreferenced until re-written.
            self._prefix = PrefixCache(self.kv_pages, self.page_size)
            self._table_np[:] = 0
            self._table_dirty = True

    def cache_bytes_per_slot(self) -> int:
        """KV-cache HBM per decode lane, scales included — the number
        int8 quantization halves (better: int8 rows + one fp32 scale
        per head per position vs fp32 rows), and the per-chip ``slots``
        capacity story in ``bench.py serve_decode``."""
        leaves = [self._cache.k, self._cache.v]
        if self._cache.quantized():
            leaves += [self._cache.k_scale, self._cache.v_scale]
        return sum(int(x.nbytes) for x in leaves) // self.num_slots

    def page_stats(self) -> Optional[dict]:
        """Paged-mode pool/index snapshot (None on fixed-lane engines
        — the /metricsz absent-key gate). ``effective_slots_multiplier``
        is the reuse win: pages the lane-copies baseline would keep
        resident (Σ per-lane mappings) over the UNIQUE mapped pages —
        1.0 with no sharing, > 1 when prefix pages are forked."""
        if not self.paged:
            return None
        refs = self._prefix.mapped_page_refs
        uniq = self._prefix.mapped_pages
        return {
            **self._prefix.stats(),
            "lane_pages": self._lane_pages,
            "pages_mapped": uniq,
            "page_starved_binds": self.page_starved_binds,
            "effective_slots_multiplier": (
                round(refs / uniq, 3) if uniq else None
            ),
        }

    # ---- disaggregated serving: page export/install (PR 16) ---------

    def export_prefix(self, tokens, trace=None) -> Optional[bytes]:
        """Ship a cached prefix's raw pages (serve/disagg.py wire
        format): the longest indexed prefix of ``tokens``, at page
        granularity, K/V bytes (plus per-page scales on int8 pools)
        gathered from the pool → one self-validating binary payload.
        None on fixed-lane engines or when no full page of the prompt
        is cached (the /pages/export 404).

        The gather is a host read OUTSIDE the decode loop (migration
        is a control-plane event, like the bind-time table upload) —
        the steady-state transfer invariant is untouched.
        """
        if not self.paged:
            return None
        from ddp_tpu.serve.disagg import encode_pages

        pids = self._prefix.match(
            list(tokens), len(tokens) // self.page_size
        )
        if not pids:
            return None
        idx = jnp.asarray(pids, jnp.int32)
        covered = list(tokens)[: len(pids) * self.page_size]
        quant = self._cache.quantized()
        return encode_pages(
            covered,
            np.asarray(self._cache.k[:, idx]),
            np.asarray(self._cache.v[:, idx]),
            page_size=self.page_size,
            k_scale=(
                np.asarray(self._cache.k_scale[:, idx]) if quant
                else None
            ),
            v_scale=(
                np.asarray(self._cache.v_scale[:, idx]) if quant
                else None
            ),
            table_row=pids,
            positions=len(covered),
            # Fleet trace context threaded by the router: rides the
            # DPKV header so the install side of the migration sees
            # the same trace id (absent-key byte-identical when off).
            trace=trace,
            # Serving identity: lets the install side refuse pages
            # computed under a different model mid-/reloadz (absent on
            # version-less engines — pre-lifecycle wire bytes).
            model_version=self.model_version,
        )

    def install_prefix(self, frame) -> Optional[dict]:
        """Host another replica's prefilled pages (the POST /pages
        implementation): validate the frame against THIS pool's
        geometry, adopt the token path into the radix index
        (serve/pages.PrefixCache.adopt), and copy only the pages the
        index did not already hold into the device pool. → install
        summary dict, or None when the pool cannot host the missing
        pages (the caller degrades to a local prefill — never a torn
        page set).

        Raises serve/disagg.PageWireError(shape_mismatch) when the
        frame's geometry or dtype disagrees with this engine — a
        fleet mixing engine configs must fail loudly, not dequantize
        garbage — and PageWireError(model_skew) when both sides carry
        a lifecycle version and they differ: during a one-at-a-time
        /reloadz roll the fleet briefly serves two model versions, and
        KV prefilled under the other one must not be adopted here. Installed pages enter the index CACHED, so the next
        local admission maps them as an ordinary prefix hit — the
        decode stream is then the same continuation-program replay a
        local hit takes, which is what makes migrated streams
        token-identical to a hybrid replica (pinned by
        tests/test_disagg.py).
        """
        from ddp_tpu.serve.disagg import (
            MODEL_SKEW,
            SHAPE_MISMATCH,
            PageWireError,
        )

        if not self.paged:
            raise PageWireError(
                SHAPE_MISMATCH, "this engine is not paged (--page_size)"
            )
        if (
            frame.model_version is not None
            and self.model_version is not None
            and frame.model_version != self.model_version
        ):
            raise PageWireError(
                MODEL_SKEW,
                f"frame from {frame.model_version}, this replica "
                f"serves {self.model_version}",
            )
        quant = self._cache.quantized()
        depth, _, ps, h_kv, d_head = self._cache.k.shape
        want_dtype = "int8" if quant else "fp32"
        if frame.page_size != ps:
            raise PageWireError(
                SHAPE_MISMATCH,
                f"frame page_size {frame.page_size} != pool {ps}",
            )
        if frame.dtype != want_dtype:
            raise PageWireError(
                SHAPE_MISMATCH,
                f"frame dtype {frame.dtype} != pool {want_dtype}",
            )
        if frame.k.shape[0] != depth or frame.k.shape[3:] != (
            h_kv, d_head,
        ):
            raise PageWireError(
                SHAPE_MISMATCH,
                f"frame kv {frame.k.shape} != pool "
                f"[{depth}, ·, {ps}, {h_kv}, {d_head}]",
            )
        got = self._prefix.adopt(frame.tokens)
        if got is None:
            return None
        pids, fill = got
        cache = self._cache
        for ordinal, pid in fill:
            # One eager dynamic-index scatter per page: the page id is
            # a traced scalar, so every install reuses ONE compiled
            # update per array — migrations never grow the program
            # set (the bounded-compile pin holds).
            i = jnp.int32(pid)
            cache = cache._replace(
                k=cache.k.at[:, i].set(jnp.asarray(frame.k[:, ordinal])),
                v=cache.v.at[:, i].set(jnp.asarray(frame.v[:, ordinal])),
            )
            if quant:
                cache = cache._replace(
                    k_scale=cache.k_scale.at[:, i].set(
                        jnp.asarray(frame.k_scale[:, ordinal])
                    ),
                    v_scale=cache.v_scale.at[:, i].set(
                        jnp.asarray(frame.v_scale[:, ordinal])
                    ),
                )
        self._cache = cache
        return {
            "pages": len(pids),
            "copied_pages": len(fill),
            "tokens": len(pids) * self.page_size,
        }

    def spec_acceptance_rate(self) -> Optional[float]:
        """Lifetime draft-acceptance fraction, None before any verify
        round (or when speculation is off)."""
        if not self.spec_drafted_total:
            return None
        return self.spec_accepted_total / self.spec_drafted_total

    def queue_drain_eta_s(self) -> Optional[float]:
        """Estimated seconds until the CURRENT queue drains, from the
        recent retirement rate (``drain_eta_s`` over the bounded
        retire-time window). None before two retirements exist — the
        caller falls back to a static Retry-After then. This is what a
        backpressure (queue_full) rejection advertises: "come back
        when a seat should be free", not a constant."""
        return drain_eta_s(
            list(self._retire_times), self.scheduler.depth
        )

    def goodput(self) -> dict:
        """Device-busy seconds over wall seconds since engine start."""
        wall = self.clock() - self._started_at
        return {
            "productive_s": round(self._productive_s, 4),
            "wall_s": round(wall, 4),
            "goodput": (
                round(self._productive_s / wall, 6) if wall > 0 else None
            ),
        }

    def stats(
        self, *, include_ledger: bool = False,
        include_states: bool = False,
    ) -> dict:
        """JSON-ready operational snapshot (the /stats endpoint).

        ``include_ledger`` embeds the full per-executable compile
        ledger; the default keeps the snapshot scalar-cheap — the
        /metricsz renderer only reads the gauge fields, and a
        Prometheus scrape must not pay a per-profile dict copy (which
        grows with the ledger) for three gauges. ``include_states``
        embeds the latency summaries' full mergeable StatSummary
        states (reservoir included) — what /statusz serves so the
        fleet aggregator (obs/aggregate.py) can merge EXACTLY; off by
        default for the same scrape-cost reason.
        """
        return {
            "slots": self.num_slots,
            "active": self.active,
            "queue_depth": self.scheduler.depth,
            "steps": self._steps,
            "completed": len(self._completed),
            "tokens_total": self.tokens_emitted_total,
            "ttft_s": self.ttft.snapshot(),
            "tpot_s": self.tpot.snapshot(ndigits=6),
            "queue_s": self.queue_wait.snapshot(ndigits=6),
            "decode_tokens_per_s": self.decode_rate.snapshot(),
            "step_latency_s": self.step_latency.snapshot(ndigits=6),
            "rejects": dict(self.reject_counts),
            "requests_by_status": dict(self.status_counts),
            "build_info": dict(self._build_info),
            **(
                {
                    "summary_states": {
                        "ttft_s": self.ttft.to_state(),
                        "tpot_s": self.tpot.to_state(),
                        "queue_s": self.queue_wait.to_state(),
                        "decode_tokens_per_s":
                            self.decode_rate.to_state(),
                    }
                }
                if include_states
                else {}
            ),
            # Paged KV + prefix index (PR 12): absent on fixed-lane
            # engines, so the default /metricsz exposition stays
            # byte-identical to the pre-paging engine's.
            **(
                {"paged": self.page_stats()} if self.paged else {}
            ),
            # Model lifecycle (serve/lifecycle.py): absent until a
            # version label is set or a swap/rollback has happened —
            # a pre-lifecycle engine's stats stay byte-identical.
            **(
                {
                    "lifecycle": {
                        **(
                            {"model_version": self.model_version}
                            if self.model_version is not None
                            else {}
                        ),
                        "reloads_total": self.reloads_total,
                        "rollbacks_total": self.rollbacks_total,
                    }
                }
                if self.model_version is not None
                or self.reloads_total
                or self.rollbacks_total
                else {}
            ),
            # SLO + request-trace state render only when configured:
            # with both off the /metricsz exposition stays
            # byte-identical to the pre-SLO engine's (the PR-2/PR-9
            # disabled-pin convention; pinned by tests/test_slo.py).
            **(
                {"slo": self._slo.state()}
                if self._slo is not None
                else {}
            ),
            **(
                {
                    "reqtrace": {
                        "live": self._reqtrace.live_count,
                        "retained": self._reqtrace.retired_count,
                        # Fleet adoption counters render only once a
                        # trace context has actually arrived, so a
                        # classic single-process engine's stats stay
                        # byte-identical.
                        **(
                            {
                                "propagated": self.trace_propagated,
                                "orphaned": self.trace_orphaned,
                            }
                            if self.trace_propagated
                            or self.trace_orphaned
                            else {}
                        ),
                    }
                }
                if self._reqtrace is not None
                else {}
            ),
            "compile_counts": self.compile_counts(),
            "prefill": {
                "chunk": self.prefill_chunk,
                "min_bucket": self.min_bucket,
                "buckets": list(self.buckets),
                "step_token_budget": self.step_token_budget,
            },
            "decode_path": {
                "attn_impl": self.decode_attn,
                "kv_dtype": self.kv_dtype,
                "cache_bytes_per_slot": self.cache_bytes_per_slot(),
                "spec_tokens": self.spec_tokens,
                **(
                    {
                        "spec_acceptance": self.spec_acceptance_rate(),
                        "spec_drafted_total": self.spec_drafted_total,
                        "spec_accepted_total": self.spec_accepted_total,
                        # Per-request distribution (the lifetime ratio
                        # above hides stragglers: one cold request in
                        # a warm fleet shows up here).
                        "spec_acceptance_per_request":
                            self.accept_rate.snapshot(),
                    }
                    if self.spec_tokens
                    else {}
                ),
            },
            "goodput": self.goodput(),
            # Compiled-program introspection, only when instrumented:
            # an xprof-less engine's stats (and its /metricsz
            # rendering) stay byte-identical.
            **(
                {
                    "xprof": {
                        "programs": self._xprof.program_count,
                        "compile_s_total": round(
                            self._xprof.total_compile_s, 4
                        ),
                        **(
                            {"ledger": self._xprof.ledger_records()}
                            if include_ledger
                            else {}
                        ),
                        "hbm": self._hbm.sample(),
                    }
                }
                if self._xprof.enabled
                else {}
            ),
        }

    # ---- engine loop ------------------------------------------------

    def step(self) -> int:
        """One engine iteration → number of tokens scheduled.

        Order: (1) retire finished / evict expired requests (draining
        any in-flight token values they are owed), (2) evict expired
        queued requests, (3) admit queue heads into free slots, (4)
        dispatch prefill chunks within the step token budget — a slot
        whose FINAL chunk lands this step joins the decode batch
        immediately (continuous batching, no drain barrier), (5)
        dispatch one fused decode+sample step over all slots, (6)
        retire the PREVIOUS step's [S] int32 token vector — the only
        steady-state device→host transfer — while the device computes
        what was just dispatched.
        """
        now = self.clock()
        t_step = time.perf_counter()
        traced = self.tracer.enabled
        evictions = 0
        for slot in self._slots:
            req = slot.request
            if req is None:
                continue
            if slot.emitted >= req.max_new_tokens:
                self._drain()  # the completion needs its token values
                self._finish(slot, COMPLETE)
            elif req.expired(now):
                self._drain()
                self._finish(slot, TIMEOUT_EVICTED)
                evictions += 1
        for req in self.scheduler.evict_expired():
            now2 = self.clock()
            c = Completion(
                rid=req.rid, status=TIMEOUT_QUEUE, prompt=req.prompt,
                tokens=[], ttft=None, decode_seconds=0.0,
                submitted=req.submitted, finished=now2,
            )
            self._completed[req.rid] = c
            self._retire_trace(c)
            self._record_request(c)
            evictions += 1

        for slot in self._slots:
            # Admission pause (hot-swap barrier): running lanes keep
            # decoding above; queue heads stay queued until the swap
            # commits or rolls back.
            if self._admission_paused:
                break
            if not slot.free or self.scheduler.depth == 0:
                continue
            req = self.scheduler.next_request()
            if req is None:
                break
            if self._admit_to_slot(slot, req) == "starved":
                # Page-starved bind: _admit_to_slot put the FIFO head
                # back at the queue front — stop admitting (later
                # requests must not overtake it) and retry after
                # retirements free pages.
                break

        # Paged mode: page-table mutations (binds above, retires at
        # the top of this step) upload ONCE here, before any dispatch
        # — one [S, lane_pages] int32 host→device copy per mutating
        # step, nothing on the steady-state path.
        if self.paged and self._table_dirty:
            self._cache = self._cache._replace(
                table=jnp.asarray(self._table_np)
            )
            self._table_dirty = False

        # Everything below is device dispatch + the one-step-lagged
        # retirement; anything fetched in (6) was dispatched LAST step
        # and has been computing since.
        prev_pending = self._pending
        self._pending = []
        self._step_spec = (0, 0)  # (drafted, accepted) this step
        produced = 0
        w0 = self.clock()
        t_dispatch = time.perf_counter()
        device_work = False

        prefilling = [
            (i, s.prefill_pos, len(s.request.prompt) - s.prefill_pos)
            for i, s in enumerate(self._slots)
            if s.prefilling
        ]
        # plan_chunks' FIFO contract is ADMISSION order, not slot-index
        # order: under a tight budget the head gets full width and
        # followers shrink/defer, so a newer request refilled into a
        # lower-index lane must not starve an older one's prefill.
        prefilling.sort(key=lambda t: self._slots[t[0]].request.rid)
        decode_lanes = [i for i, s in enumerate(self._slots) if s.decoding]
        chunk_tokens = 0
        # Budget accounting: a decoding lane costs tokens_per_decode
        # budget tokens this step — 1 on the plain path, γ under
        # speculation (the verify program runs γ positions per lane).
        for i, width in self.scheduler.plan_chunks(
            prefilling, len(decode_lanes) * self._tokens_per_decode
        ):
            slot = self._slots[i]
            req = slot.request
            start = slot.prefill_pos
            live = min(width, len(req.prompt) - start)
            final = start + live == len(req.prompt)
            buf = np.zeros((width,), np.int32)
            buf[:live] = req.prompt[start : start + live]
            # First chunk: self-contained causal attention (the chunk
            # IS its own causal prefix at start == 0) — short prompts
            # never pay a total_len-wide lane read. Continuations
            # attend the full lane under the banded q_offset mask.
            fn = self._chunk_first if start == 0 else self._chunk_cont
            t0 = time.perf_counter()
            slot_i, tok_buf = jnp.int32(i), jnp.asarray(buf)
            start_t, live_t = jnp.int32(start), jnp.int32(live)
            final_t = jnp.asarray(final)
            (self._cache, self._toks, self._seeds, self._sample_steps,
             self._temps, self._top_ps, first) = fn(
                self.params, self._cache, self._toks, self._seeds,
                self._sample_steps, self._temps, self._top_ps,
                slot_i, tok_buf, start_t, live_t, final_t,
                # Exact int32 seed (admission range-checks it): any
                # masking here would break token-identity with
                # generate(seed=...) for negative seeds.
                jnp.int32(req.seed),
                jnp.float32(req.temperature), jnp.float32(req.top_p),
            )
            if self.spec_tokens:
                # The draft cache tracks the same token history: the
                # same chunk ingests into its lane (never final — the
                # request's first token is the TARGET's draw; the
                # draft's dummy sampling state is never read).
                dfn = (
                    self._draft_chunk_first
                    if start == 0
                    else self._draft_chunk_cont
                )
                (self._draft_cache, self._d_toks, self._d_seeds,
                 self._d_steps, self._d_temps, self._d_top_ps, _) = dfn(
                    self.draft_params, self._draft_cache, self._d_toks,
                    self._d_seeds, self._d_steps, self._d_temps,
                    self._d_top_ps,
                    slot_i, tok_buf, start_t, live_t, self._keep_pos,
                    jnp.int32(req.seed),
                    jnp.float32(req.temperature),
                    jnp.float32(req.top_p),
                )
            device_work = True
            slot.prefill_pos = start + live
            chunk_tokens += live
            if traced:
                jax.block_until_ready(self._toks)
            chunk_dur = time.perf_counter() - t0
            self.tracer.complete(
                "serve.prefill_chunk", t0, chunk_dur,
                {"rid": req.rid, "slot": i, "start": start,
                 "width": width, "final": final}
                if traced
                else None,
            )
            if self._reqtrace is not None:
                tr = self._reqtrace.get(req.rid)
                if tr is not None:
                    tr.prefill_chunk(
                        t0, chunk_dur, start=start, bucket=width,
                        tokens=live, final=final,
                    )
            if final:
                slot.emitted = 1
                produced += 1
                self._pending.append(("first", first, i))
                decode_lanes.append(i)

        emit_lanes = [
            i
            for i in decode_lanes
            if self._slots[i].emitted
            < self._slots[i].request.max_new_tokens
        ]
        # Dispatch only when some lane will actually emit: a step whose
        # every decoding lane already filled its budget (all retiring
        # next step) would compute a full [S, total_len] decode and
        # throw the entire output away.
        if emit_lanes and self.spec_tokens:
            produced += self._spec_round(emit_lanes, traced)
            device_work = True
        elif emit_lanes:
            t0 = time.perf_counter()
            # --sanitize: every steady-state decode input is already
            # device-resident, so the guard proves this dispatch does
            # ZERO implicit host transfer — the PR-3 invariant,
            # enforced instead of assumed. (Chunk dispatch above
            # legitimately uploads prompt content; the retire below
            # legitimately fetches [S] int32 — both deliberate.)
            with self._sanitizer.guard():
                self._toks, self._cache, self._sample_steps = self._decode(
                    self.params, self._cache, self._toks, self._seeds,
                    self._sample_steps, self._temps, self._top_ps,
                )
            device_work = True
            if traced:
                jax.block_until_ready(self._toks)
            self.tracer.complete(
                "serve.decode", t0, time.perf_counter() - t0,
                {"lanes": len(decode_lanes)} if traced else None,
            )
            for i in emit_lanes:
                self._slots[i].emitted += 1
                if self._reqtrace is not None:
                    # Aggregate decode accounting: one counter bump
                    # per lane per step, folded into ONE req.decode
                    # span at retire — never an event per token.
                    tr = self._reqtrace.get(self._slots[i].request.rid)
                    if tr is not None:
                        tr.decode_step(t0)
            self._pending.append(("decode", self._toks, emit_lanes))
            produced += len(emit_lanes)

        dispatch_s = time.perf_counter() - t_dispatch
        t_retire = time.perf_counter()
        drained = self._drain(prev_pending)
        retire_s = time.perf_counter() - t_retire
        if device_work or drained:
            self._productive_s += self.clock() - w0

        self._steps += 1
        self.tokens_emitted_total += produced
        self.step_latency.add(time.perf_counter() - t_step)
        # Speculative rounds report their per-step acceptance in the
        # serve_step stream (the ISSUE-10 contract); non-speculative
        # engines keep the record schema byte-identical.
        spec_fields = {}
        if self.spec_tokens:
            drafted, accepted = self._step_spec
            spec_fields = dict(
                spec_drafted=drafted,
                spec_accepted=accepted,
                spec_acceptance=(
                    round(accepted / drafted, 4) if drafted else None
                ),
            )
        if self.paged:
            # Paged gauges ride the step stream (health_report's
            # page/prefix triage line keys on their presence);
            # fixed-lane engines keep the serve_step schema
            # byte-identical.
            p = self._prefix
            spec_fields.update(
                pages_free=p.free_pages,
                pages_resident=p.resident_pages,
                pages_shared=p.shared_pages,
                prefix_hit_rate=(
                    round(p.hit_rate(), 4)
                    if p.hit_rate() is not None else None
                ),
            )
        self.metrics.write(
            "serve_step",
            step=self._steps,
            queue_depth=self.scheduler.depth,
            active_slots=self.active,
            slot_occupancy=round(self.active / self.num_slots, 4),
            evictions=evictions,
            tokens=produced,
            prefill_chunk_tokens=chunk_tokens,
            dispatch_s=round(dispatch_s, 6),
            retire_s=round(retire_s, 6),
            **spec_fields,
        )
        return produced

    def run(self, *, max_steps: Optional[int] = None) -> list[Completion]:
        """Drive ``step()`` until idle (or ``max_steps``) → completions
        retired during this call, in finish order."""
        before = set(self._completed)
        steps = 0
        # A request whose budget fills on a decode step retires at the
        # START of the next step, so ``pending`` stays true until the
        # retire pass has run — no trailing flush needed.
        while self.pending and (max_steps is None or steps < max_steps):
            self.step()
            steps += 1
        return sorted(
            (c for r, c in self._completed.items() if r not in before),
            key=lambda c: c.finished,
        )

    # ---- internals --------------------------------------------------

    def _spec_round(self, emit_lanes: list[int], traced: bool) -> int:
        """One speculative round: γ draft proposals + one batched
        verify → tokens emitted (1..γ per lane).

        The draft model proposes γ greedy tokens per lane (its first
        step adopts the TARGET's per-lane positions — acceptance may
        have advanced the target less than the draft last round);
        ``slot_verify_step`` scores all of them in ONE target-model
        step and emits each lane's accepted prefix plus the target's
        correction token. The verify outputs' emit counts are
        data-dependent, so the host reads them at the END of the same
        step (two small int32 arrays — [S, γ] target tokens and [S]
        match counts, still never logits): spec mode trades the
        one-step retirement lag for up to γ tokens per big-model
        step. Runs fully under the --sanitize transfer guard up to
        that deliberate fetch.
        """
        gamma = self.spec_tokens
        # First-token scalars from THIS step's final chunks must land
        # before the verify tokens (slot.tokens is in stream order).
        self._drain()
        t0 = time.perf_counter()
        with self._sanitizer.guard():
            t = self._toks
            pos0 = self._cache.pos
            drafts = []
            for j in range(gamma):
                t, self._draft_cache = self._draft_decode(
                    self.draft_params, self._draft_cache, t, pos0,
                    self._sync_pos if j == 0 else self._keep_pos,
                )
                drafts.append(t)
            (self._toks, self._cache, self._sample_steps, target,
             matched) = self._verify(
                self.params, self._cache, self._toks,
                jnp.stack(drafts, axis=1),
                self._seeds, self._sample_steps, self._temps,
                self._top_ps,
            )
        t_np = np.asarray(target)  # [S, γ] int32
        m_np = np.asarray(matched)  # [S] int32
        round_dur = time.perf_counter() - t0
        produced = 0
        drafted = accepted = 0
        for i in emit_lanes:
            slot = self._slots[i]
            m = int(m_np[i])
            n = min(
                m + 1, gamma,
                slot.request.max_new_tokens - slot.emitted,
            )
            slot.tokens.extend(int(x) for x in t_np[i, :n])
            slot.emitted += n
            produced += n
            drafted += gamma
            accepted += m
            slot.spec_drafted += gamma
            slot.spec_accepted += m
            if self._reqtrace is not None:
                tr = self._reqtrace.get(slot.request.rid)
                if tr is not None:
                    tr.spec_round(
                        t0, round_dur, drafted=gamma, accepted=m,
                        emitted=n,
                    )
        self.spec_drafted_total += drafted
        self.spec_accepted_total += accepted
        self._step_spec = (drafted, accepted)
        self.tracer.complete(
            "serve.spec_verify", t0, round_dur,
            {"lanes": len(emit_lanes), "drafted": drafted,
             "accepted": accepted}
            if traced
            else None,
        )
        return produced

    def _admit_to_slot(self, slot: _Slot, req: Request) -> str:
        """Bind a popped request to a lane → "bound" | "rejected" |
        "starved".

        "rejected": the belt to admission's braces — a prompt that
        cannot be served (longer than the admission ceiling, or
        leaving no room to decode) but slipped past the front door —
        a mutated scheduler config, a future code path — completes
        as REJECTED_TOO_LONG here rather than surfacing as a cryptic
        shape error from the middle of a jitted program. "starved"
        (paged only): the page pool could not satisfy the request's
        demand — it went back to the queue FRONT and the caller must
        stop admitting this step.
        """
        if len(req.prompt) > min(self.prefill_len, self.spec.total_len - 1):
            now = self.clock()
            c = Completion(
                rid=req.rid, status=REJECTED_TOO_LONG, prompt=req.prompt,
                tokens=[], ttft=None, decode_seconds=0.0,
                submitted=req.submitted, finished=now,
            )
            self._completed[req.rid] = c
            self._retire_trace(c)
            self._record_request(c)
            return "rejected"
        matched = 0
        pids: list[int] = []
        if self.paged:
            # Page-based admission (the lanes×ctx_len ceiling's
            # replacement): the lane must own every page it could
            # write — prompt + decode budget + the speculative γ-1
            # write reserve, in PAGES (serve/pages.page_demand), so a
            # verify round can never scatter into an unowned page.
            got = self._prefix.acquire(
                req.prompt,
                page_demand(
                    len(req.prompt), req.max_new_tokens,
                    self.page_size,
                    total_len=self.spec.total_len,
                    reserve=self.spec_tokens - 1
                    if self.spec_tokens else 0,
                ),
            )
            if got is None:
                # Pool exhausted even after LRU eviction: requeue at
                # the FRONT (FIFO order intact) and let the caller
                # stop admitting until retirements free pages.
                self.page_starved_binds += 1
                self.scheduler.push_front(req)
                return "starved"
            pids, matched = got
        slot.request = req
        slot.tokens = []
        slot.emitted = 0
        slot.prefill_pos = matched
        slot.first_token_at = None
        slot.spec_drafted = 0
        slot.spec_accepted = 0
        slot.pages = pids
        slot.matched_tokens = matched
        if self.paged:
            self._table_np[slot.index] = 0
            self._table_np[slot.index, : len(pids)] = pids
            self._table_dirty = True
            # Position FLOOR at the matched-prefix length, applied on
            # device before any dispatch: idle-shape decode steps
            # write garbage at each lane's pos between chunks, and
            # pos >= matched at all times is exactly the invariant
            # that keeps those writes out of SHARED prefix pages
            # (private pages tolerate them — every line is rewritten
            # by its covering chunk or decode step before it becomes
            # attendable, the PR-3 invariant). A hit also skips the
            # matched pages' prefill outright: the first tail chunk
            # starts at ``matched`` through the continuation program.
            self._cache = self._cache._replace(
                pos=self._cache.pos.at[jnp.asarray(slot.index)].set(
                    jnp.int32(matched)
                )
            )
        # Queue wait closes here: the SLI behind queue_s_p99 and the
        # req.queue span (the bind is already a host-side touch point).
        slot.queue_s = max(0.0, self.clock() - req.submitted)
        self.queue_wait.add(slot.queue_s)
        if self._reqtrace is not None:
            tr = self._reqtrace.get(req.rid)
            if tr is not None:
                tr.bind(self._reqtrace.clock())
        # Sampling config reaches the device with the request's first
        # chunk (prefill_chunk installs it at the lane) — nothing to
        # upload here.
        return "bound"

    def _drain(self, items: Optional[list] = None) -> int:
        """Fetch dispatched-but-unread token values → tokens appended.

        The steady-state host sync: each item is either the previous
        decode step's [S] int32 vector or a final chunk's first-token
        scalar — never logits. Called with the previous step's items
        after this step's dispatches (the fetch overlaps the device
        computing the new work), and with everything outstanding when
        a retirement needs its values now.
        """
        if items is None:
            items = self._pending
            self._pending = []
        if not items:
            return 0
        traced = self.tracer.enabled
        t0 = time.perf_counter()
        appended = 0
        for kind, arr, meta in items:
            vals = np.asarray(arr)
            if kind == "first":
                slot = self._slots[meta]
                slot.tokens.append(int(vals))
                appended += 1
                slot.first_token_at = self.clock()
                if slot.request is not None:
                    self.ttft.add(
                        slot.first_token_at - slot.request.submitted
                    )
            else:
                for i in meta:
                    self._slots[i].tokens.append(int(vals[i]))
                    appended += 1
        self.tracer.complete(
            "serve.sample", t0, time.perf_counter() - t0,
            {"tokens": appended} if traced else None,
        )
        return appended

    def _finish(self, slot: _Slot, status: str) -> None:
        req = slot.request
        now = self.clock()
        first = slot.first_token_at  # None = evicted before any token
        c = Completion(
            rid=req.rid,
            status=status,
            prompt=req.prompt,
            tokens=list(slot.tokens),
            ttft=(first - req.submitted) if first is not None else None,
            decode_seconds=(now - first) if first is not None else 0.0,
            submitted=req.submitted,
            finished=now,
            # Per-completion acceptance (ISSUE-10): fraction of this
            # request's draft proposals the target accepted. None when
            # no verify round ran for it (spec off, or the request
            # finished on its prefill token alone).
            spec_acceptance=(
                round(slot.spec_accepted / slot.spec_drafted, 4)
                if slot.spec_drafted
                else None
            ),
            queue_s=slot.queue_s,
            prefix_hit_tokens=(
                slot.matched_tokens if self.paged else None
            ),
        )
        self._completed[req.rid] = c
        self._retire_times.append(now)
        if len(c.tokens) > 1:
            self.decode_rate.add(c.decode_tokens_per_s)
        if c.tpot_s is not None:
            self.tpot.add(c.tpot_s)
        if c.spec_acceptance is not None:
            self.accept_rate.add(c.spec_acceptance)
        self._retire_trace(c)
        self._record_request(c)
        if self.paged and slot.pages:
            # Publish the prompt's fully-prefilled pages into the
            # radix index (decode output stays private; prefill_pos
            # caps mid-prefill evictions) and unmap everything the
            # lane held — published pages go LRU-cached at refcount
            # 0, the rest return to the free list. The lane's table
            # row zeroes (→ the scratch page) so the idle-shape
            # decode can never write into freed/reallocated pages.
            self._prefix.release(
                req.prompt, slot.pages, slot.prefill_pos
            )
            self._table_np[slot.index] = 0
            self._table_dirty = True
        slot.request = None
        slot.tokens = []
        slot.emitted = 0
        slot.prefill_pos = 0
        slot.first_token_at = None
        slot.queue_s = None
        slot.spec_drafted = 0
        slot.spec_accepted = 0
        slot.pages = []
        slot.matched_tokens = 0

    def _retire_trace(self, c: Completion) -> None:
        """Close the request's trace (if tracing) and hang the digest
        on the completion — called from every retirement path."""
        if self._reqtrace is None:
            return
        t = self._reqtrace.retire(c.rid, c.status, tracer=self.tracer)
        if t is not None:
            c.trace = t.summary()

    def _on_slo_breach(self, state: dict) -> None:
        """SLO alert transition (obs/slo.py multi-window burn): one
        record into the metrics stream AND the flight recorder ring
        (the PR-4 post-mortem artifact) per False→True transition,
        then the caller's hook if any."""
        fields = dict(
            objective=state["name"],
            target=state["target"],
            current=state["current"],
            burn_rate_fast=state["burn_rate_fast"],
            burn_rate_slow=state["burn_rate_slow"],
            window_n=state["window_n"],
        )
        self.metrics.write("slo_breach", **fields)
        self._recorder.record("slo_breach", **fields)
        if self._user_breach_cb is not None:
            self._user_breach_cb(state)

    def request_timeline(self, key) -> Optional[dict]:
        """One request's full event timeline by rid or hex trace id —
        the /requestz payload. None when unknown (or tracing off)."""
        if self._reqtrace is None:
            return None
        t = self._reqtrace.lookup(key)
        if t is None:
            return None
        doc = t.timeline()
        doc["live"] = t.retire_t is None
        return doc

    def emit_request_spans(self) -> int:
        """Retroactively emit retired request traces into the span
        tracer (→ count). The bench path: its timed window runs with
        the tracer's measuring mode off (span fidelity would destroy
        the dispatch/retire overlap being measured) and exports the
        request spans afterwards — stamps were recorded live, so the
        exported timeline is the measured one."""
        if self._reqtrace is None:
            return 0
        return self._reqtrace.emit_all(self.tracer)

    def _record_request(self, c: Completion) -> None:
        self.status_counts[c.status] = self.status_counts.get(c.status, 0) + 1
        fields = dict(
            rid=c.rid,
            status=c.status,
            prompt_len=len(c.prompt),
            new_tokens=len(c.tokens),
            decode_tokens_per_s=round(c.decode_tokens_per_s, 2),
        )
        # Requests that never produced a token carry no ttft_s at all:
        # downstream aggregation must see only real first-token
        # latencies, not queue-timeout wait times.
        if c.ttft is not None:
            fields["ttft_s"] = round(c.ttft, 4)
        # Same absent-vs-null contract for acceptance: only requests
        # that actually ran verify rounds carry the field.
        if c.spec_acceptance is not None:
            fields["spec_acceptance"] = c.spec_acceptance
        # The user-facing SLIs (absent when the request never bound a
        # lane / never decoded — aggregation sees only real values).
        if c.queue_s is not None:
            fields["queue_s"] = round(c.queue_s, 6)
        if c.tpot_s is not None:
            fields["tpot_s"] = round(c.tpot_s, 6)
        # Cross-plane correlation key: present only when request
        # tracing is on (the serve_request schema is otherwise
        # byte-compatible with the pre-reqtrace stream).
        if c.trace is not None:
            fields["trace_id"] = c.trace["trace_id"]
        # Prefix-reuse accounting, paged engines only (the bench's
        # TTFT hit-vs-miss split reads this).
        if c.prefix_hit_tokens is not None:
            fields["prefix_hit_tokens"] = c.prefix_hit_tokens
        # Which model served this request — only engines with a
        # version label carry it (pre-lifecycle streams unchanged);
        # stamped at retirement, so a request that straddled a swap is
        # attributed to the version that finished it.
        if self.model_version is not None:
            fields["model_version"] = self.model_version
        # Per-hop seconds (ISSUE 19): only requests the router staged
        # with a fleet trace carry the key — the router's queue/
        # handoff/migrate seconds joined with this engine's own
        # queue/decode split, so ONE record attributes the whole TTFT.
        router_hops = self._request_hops.pop(c.rid, None)
        if router_hops is not None:
            hops = dict(router_hops)
            if c.trace is not None:
                for k in ("queue_s", "prefill_s", "decode_s"):
                    if c.trace.get(k) is not None:
                        hops[f"engine_{k}"] = c.trace[k]
            fields["hops"] = hops
        self.metrics.write("serve_request", **fields)
        # Feed the SLO engine from the same retirement: the SLIs are
        # host floats already in hand, and availability counts every
        # SERVICE-terminal status (a timeout IS an unavailability
        # event). Client-class rejections are excluded — a burst of
        # over-long prompts must not burn the availability budget and
        # page the operator while valid traffic is served perfectly
        # (admission rejects never reach this path either).
        if self._slo is not None and c.status != REJECTED_TOO_LONG:
            self._slo.observe(
                ttft_s=c.ttft,
                tpot_s=c.tpot_s,
                queue_s=c.queue_s,
                ok=c.status == COMPLETE,
            )

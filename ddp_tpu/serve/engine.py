"""Continuous-batching decode engine over fixed slots.

The TPU serving problem (PAPERS.md #1/#5 regime): requests arrive at
arbitrary times with arbitrary prompt/output lengths, but XLA wants
ONE compiled program per shape. The resolution is the standard
continuous-batching design (Orca/vLLM lineage) restricted to fully
static shapes:

- the engine owns S decode **slots** — lanes of one SlotCache
  (models/generate.py) sized [depth, S, total_len, H_kv, Dh] at
  startup, never reshaped;
- every engine step advances ALL S lanes by one token AND samples
  each lane's next token on device (``slot_decode_sample_step`` — one
  compiled program, mixed-age batch, fused sampling);
- prompts are ingested by **chunked prefill** (Sarathi-style):
  ``prefill_chunk`` writes one power-of-two-bucketed chunk straight
  into the freed lane of the donated cache, co-scheduled with decode
  steps under a per-step token budget (serve/scheduler.plan_chunks),
  so running lanes never stall behind a long prompt;
- therefore the engine compiles a BOUNDED program set — one decode
  program plus one chunk program per bucket width — enumerable at
  ``warmup()``, after which a varied request mix (staggered arrivals,
  different lengths, evictions) triggers **zero further compilation**
  (pinned by tests/test_serve.py via ``compile_counts``).

The decode loop is **device-resident**: the only steady-state
device→host transfer is the [S] int32 token vector of the PREVIOUS
step, fetched after the current step's work has been dispatched
(dispatch step i+1, then retire step i's tokens while the device
computes) — no full-logits round-trip, no per-slot Python sampling
(both pinned by tests). The cache is donated through every program
(train/fast.py's convention) so XLA keeps one KV buffer; the token
vector deliberately is NOT donated — the host still owes a read of
the previous step's values.

Scheduling policy lives in serve/scheduler.py (admission, FIFO,
deadlines, chunk planning); this module is the data plane plus
per-request bookkeeping. Observability flows through
utils/metrics.MetricsWriter: ``serve_step`` records (queue depth,
slot occupancy, evictions, chunk tokens, dispatch/retire split) and
``serve_request`` records (status, TTFT, decode tokens/s) land in the
same JSONL stream the trainer writes; span tracing emits
``serve.prefill_chunk`` / ``serve.decode`` / ``serve.sample``.

Sampling: greedy (temperature 0, the correctness-pinned path — token-
identical to models/generate.generate) or seeded temperature/top-p
sampling fused into the jitted step via per-slot ``jax.random`` keys
(ALSO token-identical to a seeded ``generate()`` — same fold_in
stream, pinned by tests). ``top_k`` stays generate-only: its k is a
compiled shape, so per-request values would recompile per mix.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

# Aliased: ``prefill_chunk`` is also an engine CONFIG name (the chunk
# width __init__ parameter), which would shadow the function inside
# closures defined there.
from ddp_tpu.models.generate import init_slot_cache
from ddp_tpu.models.generate import prefill_chunk as _prefill_chunk
from ddp_tpu.models.generate import (
    slot_decode_sample_step as _decode_sample,
)
from ddp_tpu.models.lm import LMSpec
from ddp_tpu.obs.tracer import Tracer
from ddp_tpu.serve.scheduler import (
    Admission,
    Request,
    Scheduler,
    next_pow2,
    prev_pow2,
)
from ddp_tpu.utils.metrics import MetricsWriter, StatSummary

# Completion statuses.
COMPLETE = "complete"
TIMEOUT_EVICTED = "timeout_evicted"  # deadline hit while decoding
TIMEOUT_QUEUE = "timeout_queue"  # deadline hit while queued
REJECTED_TOO_LONG = "rejected_too_long"  # slipped past the front door


@dataclass
class Completion:
    """One finished request: everything the frontend returns.

    ``ttft`` is None for requests that never produced a token (queue
    timeouts, mid-prefill evictions, refill-time rejections) — they
    must not pollute the TTFT summaries with queue-wait times.
    """

    rid: int
    status: str
    prompt: list[int]
    tokens: list[int]
    ttft: Optional[float]  # seconds, submit → first token observed
    decode_seconds: float  # first token → finish
    submitted: float
    finished: float

    @property
    def decode_tokens_per_s(self) -> float:
        n = len(self.tokens) - 1  # tokens after the prefill token
        return n / self.decode_seconds if self.decode_seconds > 0 else 0.0


@dataclass
class _Slot:
    """Host-side bookkeeping for one lane."""

    request: Optional[Request] = None
    tokens: list[int] = field(default_factory=list)
    # Tokens SCHEDULED on device for this request, including ones whose
    # values the host has not fetched yet (len(tokens) lags by the
    # in-flight step). Retirement decisions use this — counts are
    # host-known at dispatch time, values are not.
    emitted: int = 0
    prefill_pos: int = 0  # prompt tokens ingested so far
    first_token_at: Optional[float] = None  # None = no token observed

    @property
    def free(self) -> bool:
        return self.request is None

    @property
    def prefilling(self) -> bool:
        return (
            self.request is not None
            and self.prefill_pos < len(self.request.prompt)
        )

    @property
    def decoding(self) -> bool:
        return (
            self.request is not None
            and self.prefill_pos >= len(self.request.prompt)
        )


class ServeEngine:
    """Fixed-slot continuous-batching engine for one causal LM.

    ``slots`` fixes the decode batch shape; ``prefill_len`` is the
    admission ceiling for prompts (default half the position table).
    ``prefill_chunk`` (power of two, default min(pow2(prefill_len),
    64)) is the full chunk width prompts are ingested at;
    ``min_bucket`` floors the power-of-two bucket of the final partial
    chunk; ``step_token_budget`` bounds chunk-plus-decode tokens
    dispatched per step (default chunk + slots — one full-width chunk
    can ride along with a full decode batch). ``clock`` is injectable
    for deterministic tests; MetricsWriter ``metrics`` may be shared
    with a trainer's stream or omitted.
    """

    def __init__(
        self,
        spec: LMSpec,
        params: Any,
        *,
        slots: int = 4,
        prefill_len: Optional[int] = None,
        prefill_chunk: Optional[int] = None,
        min_bucket: Optional[int] = None,
        step_token_budget: Optional[int] = None,
        max_queue: int = 64,
        metrics: Optional[MetricsWriter] = None,
        tracer: Optional[Tracer] = None,
        clock: Callable[[], float] = time.monotonic,
        sanitize: bool = False,
        xprof=None,
    ):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        prefill_len = prefill_len or max(1, spec.total_len // 2)
        if not 0 < prefill_len <= spec.total_len - 1:
            raise ValueError(
                f"prefill_len {prefill_len} must leave room to decode "
                f"inside total_len {spec.total_len}"
            )
        chunk = next_pow2(
            prefill_chunk
            if prefill_chunk
            else min(next_pow2(prefill_len), 64)
        )
        # A chunk's write region [start, start + width) must fit the
        # cache at start = 0 — cap at the largest pow2 <= total_len.
        chunk = min(chunk, prev_pow2(spec.total_len))
        # The smallest bucket must fit the cache at ANY admissible
        # start (max start = prefill_len - 1, so the space floor is
        # total_len - prefill_len + 1 >= 2): a wider bucket's pad
        # overhang would make dynamic_update_slice clamp the write
        # start and silently shift the chunk over live cache lines.
        min_bucket = min(
            chunk,
            next_pow2(min_bucket) if min_bucket else min(8, chunk),
            prev_pow2(spec.total_len - prefill_len + 1),
        )
        self.spec = spec
        self.params = params
        self.num_slots = slots
        self.prefill_len = prefill_len
        self.prefill_chunk = chunk
        self.min_bucket = min_bucket
        self.step_token_budget = (
            step_token_budget
            if step_token_budget
            else chunk + slots
        )
        if self.step_token_budget < min_bucket + slots:
            # Below this floor the prefill head can starve forever
            # while lanes decode (the budget never fits even the
            # smallest bucket after decode tokens are accounted).
            raise ValueError(
                f"step_token_budget {self.step_token_budget} cannot "
                f"sustain prefill progress: needs >= min_bucket "
                f"({min_bucket}) + slots ({slots})"
            )
        self.clock = clock
        self.metrics = metrics or MetricsWriter(None)
        # Span tracing (ddp_tpu.obs): chunk/decode device work plus the
        # sampled-token retirement land on the host timeline; disabled
        # by default and pinned free when off. When ENABLED, dispatches
        # block until ready so spans cover device compute — measuring
        # mode trades the pipeline overlap for span fidelity.
        self.tracer = tracer or Tracer()
        # Runtime sanitizer (--sanitize, runtime/sanitize.py): the
        # transfer guard arms around the steady-state DECODE dispatch
        # in step(), proving it does zero implicit host transfer. The
        # engine's two deliberate transfers — the chunk-argument
        # upload and the one-step-behind [S] int32 fetch — execute
        # OUTSIDE the guarded region (no allow() windows here, unlike
        # the trainer). Disabled = a free nullcontext, like the
        # tracer.
        from ddp_tpu.runtime.sanitize import Sanitizer

        self._sanitizer = Sanitizer(sanitize)
        self._started_at = clock()
        self._productive_s = 0.0
        self.scheduler = Scheduler(
            max_queue=max_queue,
            prefill_len=prefill_len,
            total_len=spec.total_len,
            vocab_size=spec.vocab_size,
            chunk=chunk,
            min_bucket=min_bucket,
            token_budget=self.step_token_budget,
            clock=clock,
        )
        # {min_bucket · 2^i} ∪ {chunk}: the whole compiled-width set.
        self.buckets = self.scheduler.bucket_list()
        self._slots = [_Slot() for _ in range(slots)]
        self._cache = init_slot_cache(spec, slots)
        # Device-resident token vector: output of the last decode (or
        # chunk splice), input to the next — the decode loop never
        # routes tokens through the host. NOT donated anywhere: the
        # host still owes an async read of the previous step's values.
        self._toks = jnp.zeros((slots,), jnp.int32)
        # Per-slot sampling state, ALSO device-resident: the chunk
        # program installs a request's (seed, temperature, top_p) at
        # its lane and the decode program advances the fold_in step
        # counters — the steady-state loop uploads NOTHING per step.
        # Seeds are int32 to hit exactly generate()'s
        # jax.random.key(seed) path.
        self._seeds = jnp.zeros((slots,), jnp.int32)
        self._sample_steps = jnp.zeros((slots,), jnp.int32)
        self._temps = jnp.zeros((slots,), jnp.float32)
        self._top_ps = jnp.ones((slots,), jnp.float32)
        # Device values dispatched but not yet read back:
        # ("first", scalar, slot) | ("decode", [S] array, lanes).
        self._pending: list[tuple[str, Any, Any]] = []
        self._completed: dict[int, Completion] = {}
        self._steps = 0
        self.ttft = StatSummary()
        self.decode_rate = StatSummary()
        self.step_latency = StatSummary()
        # Monotone aggregate counters (the /metricsz exposition needs
        # totals, not just the JSONL event stream): admission rejects
        # by reason, finished requests by status.
        self.reject_counts: dict[str, int] = {}
        self.status_counts: dict[str, int] = {}
        # The engine's entire compiled surface: ONE decode program
        # (sampling fused) plus per bucket width one FIRST-chunk
        # program (self-contained causal attention — short prompts pay
        # bucket-sized compute, the monolithic-prefill cost) and one
        # CONTINUATION program (banded attention against the full
        # lane) — slot index / start / length / final / sampling
        # config are all traced, so no request mix can grow the set
        # past 2·len(buckets) + 1 after warmup(). Fresh lambdas (not
        # bare function objects): jit tracing caches are shared per
        # function object, and the static-shape pin must be
        # per-engine.
        def _chunk_fn(lane_attend):
            return jax.jit(
                lambda p, c, t, se, sp, tm, tp, s, ch, st, ln, fi, sd,
                rtm, rtp: _prefill_chunk(
                    spec, p, c, t, se, sp, tm, tp, s, ch, st, ln, fi,
                    sd, rtm, rtp, lane_attend=lane_attend,
                ),
                donate_argnums=(1,),
            )

        # Compiled-program introspection (obs/xprof.py): when a live
        # Xprof is passed, the engine's whole program set dispatches
        # through its compile ledger — warmup() then enumerates every
        # program WITH its compile time, XLA FLOPs, and memory
        # breakdown, and /metricsz gains compile + HBM gauges. The
        # wrapper preserves _cache_size(), so the static-shape pins
        # (compile_counts frozen after warmup) hold either way. None =
        # uninstrumented, byte-identical to the pre-xprof engine.
        from ddp_tpu.obs.xprof import DeviceMemorySampler, Xprof

        self._xprof = xprof if xprof is not None else Xprof(enabled=False)
        self._hbm = DeviceMemorySampler(enabled=self._xprof.enabled)
        self._chunk_first = self._xprof.instrument(
            _chunk_fn(False), "serve.prefill_first"
        )
        self._chunk_cont = self._xprof.instrument(
            _chunk_fn(True), "serve.prefill_chunk"
        )
        self._decode = self._xprof.instrument(
            jax.jit(
                lambda p, c, t, sd, st, tm, tp: _decode_sample(
                    spec, p, c, t, sd, st, tm, tp
                ),
                donate_argnums=(1,),
            ),
            "serve.decode",
        )

    # ---- frontend surface ------------------------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        top_p: float = 1.0,
        seed: int = 0,
        timeout: Optional[float] = None,
    ) -> Admission:
        """Admission-checked enqueue; rejections carry a reason."""
        adm = self.scheduler.submit(
            prompt,
            max_new_tokens,
            temperature=temperature,
            top_p=top_p,
            seed=seed,
            timeout=timeout,
        )
        if not adm.accepted:
            self.reject_counts[adm.reason] = (
                self.reject_counts.get(adm.reason, 0) + 1
            )
            self.metrics.write(
                "serve_reject",
                reason=adm.reason,
                queue_depth=self.scheduler.depth,
            )
        return adm

    def result(self, rid: int) -> Optional[Completion]:
        """The finished record for ``rid``, None while pending."""
        return self._completed.get(rid)

    def pop_result(self, rid: int) -> Optional[Completion]:
        return self._completed.pop(rid, None)

    @property
    def active(self) -> int:
        return sum(1 for s in self._slots if not s.free)

    @property
    def pending(self) -> bool:
        return self.active > 0 or self.scheduler.depth > 0

    def compile_counts(self) -> dict[str, int]:
        """Compiled-program count per engine function (the static-
        shape pin: after ``warmup()`` these must never grow;
        prefill_first and prefill_chunk are each bounded by
        ``len(self.buckets)``)."""
        return {
            "prefill_first": self._chunk_first._cache_size(),
            "prefill_chunk": self._chunk_cont._cache_size(),
            "decode": self._decode._cache_size(),
        }

    def warmup(self) -> dict[str, int]:
        """Eagerly compile the engine's whole program set → counts.

        Per bucket width one first-chunk and one continuation-chunk
        program, plus the decode program — after this, steady state
        compiles NOTHING (the zero-recompilation pin's baseline, and
        a serving process's first-request latency is a decode step,
        not an XLA compile). Must run on an idle engine: warmup
        chunks write garbage K/V into lane 0, which the refill
        invariant (every line is overwritten before it becomes
        attendable) makes harmless only while no request owns a lane.
        """
        if self.active:
            raise RuntimeError("warmup() requires an idle engine")
        zero = jnp.int32(0)
        for fn in (self._chunk_first, self._chunk_cont):
            for w in self.buckets:
                (self._cache, self._toks, self._seeds,
                 self._sample_steps, self._temps, self._top_ps,
                 _) = fn(
                    self.params, self._cache, self._toks, self._seeds,
                    self._sample_steps, self._temps, self._top_ps,
                    zero, jnp.zeros((w,), jnp.int32), zero,
                    jnp.int32(w), jnp.asarray(False), zero,
                    jnp.float32(0.0), jnp.float32(1.0),
                )
        self._toks, self._cache, self._sample_steps = self._decode(
            self.params, self._cache, self._toks, self._seeds,
            self._sample_steps, self._temps, self._top_ps,
        )
        jax.block_until_ready(self._toks)
        return self.compile_counts()

    def goodput(self) -> dict:
        """Device-busy seconds over wall seconds since engine start."""
        wall = self.clock() - self._started_at
        return {
            "productive_s": round(self._productive_s, 4),
            "wall_s": round(wall, 4),
            "goodput": (
                round(self._productive_s / wall, 6) if wall > 0 else None
            ),
        }

    def stats(self, *, include_ledger: bool = False) -> dict:
        """JSON-ready operational snapshot (the /stats endpoint).

        ``include_ledger`` embeds the full per-executable compile
        ledger; the default keeps the snapshot scalar-cheap — the
        /metricsz renderer only reads the gauge fields, and a
        Prometheus scrape must not pay a per-profile dict copy (which
        grows with the ledger) for three gauges.
        """
        return {
            "slots": self.num_slots,
            "active": self.active,
            "queue_depth": self.scheduler.depth,
            "steps": self._steps,
            "completed": len(self._completed),
            "ttft_s": self.ttft.snapshot(),
            "decode_tokens_per_s": self.decode_rate.snapshot(),
            "step_latency_s": self.step_latency.snapshot(ndigits=6),
            "rejects": dict(self.reject_counts),
            "requests_by_status": dict(self.status_counts),
            "compile_counts": self.compile_counts(),
            "prefill": {
                "chunk": self.prefill_chunk,
                "min_bucket": self.min_bucket,
                "buckets": list(self.buckets),
                "step_token_budget": self.step_token_budget,
            },
            "goodput": self.goodput(),
            # Compiled-program introspection, only when instrumented:
            # an xprof-less engine's stats (and its /metricsz
            # rendering) stay byte-identical.
            **(
                {
                    "xprof": {
                        "programs": self._xprof.program_count,
                        "compile_s_total": round(
                            self._xprof.total_compile_s, 4
                        ),
                        **(
                            {"ledger": self._xprof.ledger_records()}
                            if include_ledger
                            else {}
                        ),
                        "hbm": self._hbm.sample(),
                    }
                }
                if self._xprof.enabled
                else {}
            ),
        }

    # ---- engine loop ------------------------------------------------

    def step(self) -> int:
        """One engine iteration → number of tokens scheduled.

        Order: (1) retire finished / evict expired requests (draining
        any in-flight token values they are owed), (2) evict expired
        queued requests, (3) admit queue heads into free slots, (4)
        dispatch prefill chunks within the step token budget — a slot
        whose FINAL chunk lands this step joins the decode batch
        immediately (continuous batching, no drain barrier), (5)
        dispatch one fused decode+sample step over all slots, (6)
        retire the PREVIOUS step's [S] int32 token vector — the only
        steady-state device→host transfer — while the device computes
        what was just dispatched.
        """
        now = self.clock()
        t_step = time.perf_counter()
        traced = self.tracer.enabled
        evictions = 0
        for slot in self._slots:
            req = slot.request
            if req is None:
                continue
            if slot.emitted >= req.max_new_tokens:
                self._drain()  # the completion needs its token values
                self._finish(slot, COMPLETE)
            elif req.expired(now):
                self._drain()
                self._finish(slot, TIMEOUT_EVICTED)
                evictions += 1
        for req in self.scheduler.evict_expired():
            now2 = self.clock()
            self._completed[req.rid] = Completion(
                rid=req.rid, status=TIMEOUT_QUEUE, prompt=req.prompt,
                tokens=[], ttft=None, decode_seconds=0.0,
                submitted=req.submitted, finished=now2,
            )
            self._record_request(self._completed[req.rid])
            evictions += 1

        for slot in self._slots:
            if not slot.free or self.scheduler.depth == 0:
                continue
            req = self.scheduler.next_request()
            if req is None:
                break
            self._admit_to_slot(slot, req)

        # Everything below is device dispatch + the one-step-lagged
        # retirement; anything fetched in (6) was dispatched LAST step
        # and has been computing since.
        prev_pending = self._pending
        self._pending = []
        produced = 0
        w0 = self.clock()
        t_dispatch = time.perf_counter()
        device_work = False

        prefilling = [
            (i, s.prefill_pos, len(s.request.prompt) - s.prefill_pos)
            for i, s in enumerate(self._slots)
            if s.prefilling
        ]
        # plan_chunks' FIFO contract is ADMISSION order, not slot-index
        # order: under a tight budget the head gets full width and
        # followers shrink/defer, so a newer request refilled into a
        # lower-index lane must not starve an older one's prefill.
        prefilling.sort(key=lambda t: self._slots[t[0]].request.rid)
        decode_lanes = [i for i, s in enumerate(self._slots) if s.decoding]
        chunk_tokens = 0
        for i, width in self.scheduler.plan_chunks(
            prefilling, len(decode_lanes)
        ):
            slot = self._slots[i]
            req = slot.request
            start = slot.prefill_pos
            live = min(width, len(req.prompt) - start)
            final = start + live == len(req.prompt)
            buf = np.zeros((width,), np.int32)
            buf[:live] = req.prompt[start : start + live]
            # First chunk: self-contained causal attention (the chunk
            # IS its own causal prefix at start == 0) — short prompts
            # never pay a total_len-wide lane read. Continuations
            # attend the full lane under the banded q_offset mask.
            fn = self._chunk_first if start == 0 else self._chunk_cont
            t0 = time.perf_counter()
            (self._cache, self._toks, self._seeds, self._sample_steps,
             self._temps, self._top_ps, first) = fn(
                self.params, self._cache, self._toks, self._seeds,
                self._sample_steps, self._temps, self._top_ps,
                jnp.int32(i), jnp.asarray(buf), jnp.int32(start),
                jnp.int32(live), jnp.asarray(final),
                # Exact int32 seed (admission range-checks it): any
                # masking here would break token-identity with
                # generate(seed=...) for negative seeds.
                jnp.int32(req.seed),
                jnp.float32(req.temperature), jnp.float32(req.top_p),
            )
            device_work = True
            slot.prefill_pos = start + live
            chunk_tokens += live
            if traced:
                jax.block_until_ready(self._toks)
            self.tracer.complete(
                "serve.prefill_chunk", t0, time.perf_counter() - t0,
                {"rid": req.rid, "slot": i, "start": start,
                 "width": width, "final": final}
                if traced
                else None,
            )
            if final:
                slot.emitted = 1
                produced += 1
                self._pending.append(("first", first, i))
                decode_lanes.append(i)

        emit_lanes = [
            i
            for i in decode_lanes
            if self._slots[i].emitted
            < self._slots[i].request.max_new_tokens
        ]
        # Dispatch only when some lane will actually emit: a step whose
        # every decoding lane already filled its budget (all retiring
        # next step) would compute a full [S, total_len] decode and
        # throw the entire output away.
        if emit_lanes:
            t0 = time.perf_counter()
            # --sanitize: every steady-state decode input is already
            # device-resident, so the guard proves this dispatch does
            # ZERO implicit host transfer — the PR-3 invariant,
            # enforced instead of assumed. (Chunk dispatch above
            # legitimately uploads prompt content; the retire below
            # legitimately fetches [S] int32 — both deliberate.)
            with self._sanitizer.guard():
                self._toks, self._cache, self._sample_steps = self._decode(
                    self.params, self._cache, self._toks, self._seeds,
                    self._sample_steps, self._temps, self._top_ps,
                )
            device_work = True
            if traced:
                jax.block_until_ready(self._toks)
            self.tracer.complete(
                "serve.decode", t0, time.perf_counter() - t0,
                {"lanes": len(decode_lanes)} if traced else None,
            )
            for i in emit_lanes:
                self._slots[i].emitted += 1
            self._pending.append(("decode", self._toks, emit_lanes))
            produced += len(emit_lanes)

        dispatch_s = time.perf_counter() - t_dispatch
        t_retire = time.perf_counter()
        drained = self._drain(prev_pending)
        retire_s = time.perf_counter() - t_retire
        if device_work or drained:
            self._productive_s += self.clock() - w0

        self._steps += 1
        self.step_latency.add(time.perf_counter() - t_step)
        self.metrics.write(
            "serve_step",
            step=self._steps,
            queue_depth=self.scheduler.depth,
            active_slots=self.active,
            slot_occupancy=round(self.active / self.num_slots, 4),
            evictions=evictions,
            tokens=produced,
            prefill_chunk_tokens=chunk_tokens,
            dispatch_s=round(dispatch_s, 6),
            retire_s=round(retire_s, 6),
        )
        return produced

    def run(self, *, max_steps: Optional[int] = None) -> list[Completion]:
        """Drive ``step()`` until idle (or ``max_steps``) → completions
        retired during this call, in finish order."""
        before = set(self._completed)
        steps = 0
        # A request whose budget fills on a decode step retires at the
        # START of the next step, so ``pending`` stays true until the
        # retire pass has run — no trailing flush needed.
        while self.pending and (max_steps is None or steps < max_steps):
            self.step()
            steps += 1
        return sorted(
            (c for r, c in self._completed.items() if r not in before),
            key=lambda c: c.finished,
        )

    # ---- internals --------------------------------------------------

    def _admit_to_slot(self, slot: _Slot, req: Request) -> bool:
        """Bind a popped request to a lane; False = rejected instead.

        The belt to admission's braces: a prompt that cannot be served
        (longer than the admission ceiling, or leaving no room to
        decode) but slipped past the front door — a mutated scheduler
        config, a future code path — completes as REJECTED_TOO_LONG
        here rather than surfacing as a cryptic shape error from the
        middle of a jitted program.
        """
        if len(req.prompt) > min(self.prefill_len, self.spec.total_len - 1):
            now = self.clock()
            self._completed[req.rid] = Completion(
                rid=req.rid, status=REJECTED_TOO_LONG, prompt=req.prompt,
                tokens=[], ttft=None, decode_seconds=0.0,
                submitted=req.submitted, finished=now,
            )
            self._record_request(self._completed[req.rid])
            return False
        slot.request = req
        slot.tokens = []
        slot.emitted = 0
        slot.prefill_pos = 0
        slot.first_token_at = None
        # Sampling config reaches the device with the request's first
        # chunk (prefill_chunk installs it at the lane) — nothing to
        # upload here.
        return True

    def _drain(self, items: Optional[list] = None) -> int:
        """Fetch dispatched-but-unread token values → tokens appended.

        The steady-state host sync: each item is either the previous
        decode step's [S] int32 vector or a final chunk's first-token
        scalar — never logits. Called with the previous step's items
        after this step's dispatches (the fetch overlaps the device
        computing the new work), and with everything outstanding when
        a retirement needs its values now.
        """
        if items is None:
            items = self._pending
            self._pending = []
        if not items:
            return 0
        traced = self.tracer.enabled
        t0 = time.perf_counter()
        appended = 0
        for kind, arr, meta in items:
            vals = np.asarray(arr)
            if kind == "first":
                slot = self._slots[meta]
                slot.tokens.append(int(vals))
                appended += 1
                slot.first_token_at = self.clock()
                if slot.request is not None:
                    self.ttft.add(
                        slot.first_token_at - slot.request.submitted
                    )
            else:
                for i in meta:
                    self._slots[i].tokens.append(int(vals[i]))
                    appended += 1
        self.tracer.complete(
            "serve.sample", t0, time.perf_counter() - t0,
            {"tokens": appended} if traced else None,
        )
        return appended

    def _finish(self, slot: _Slot, status: str) -> None:
        req = slot.request
        now = self.clock()
        first = slot.first_token_at  # None = evicted before any token
        c = Completion(
            rid=req.rid,
            status=status,
            prompt=req.prompt,
            tokens=list(slot.tokens),
            ttft=(first - req.submitted) if first is not None else None,
            decode_seconds=(now - first) if first is not None else 0.0,
            submitted=req.submitted,
            finished=now,
        )
        self._completed[req.rid] = c
        if len(c.tokens) > 1:
            self.decode_rate.add(c.decode_tokens_per_s)
        self._record_request(c)
        slot.request = None
        slot.tokens = []
        slot.emitted = 0
        slot.prefill_pos = 0
        slot.first_token_at = None

    def _record_request(self, c: Completion) -> None:
        self.status_counts[c.status] = self.status_counts.get(c.status, 0) + 1
        fields = dict(
            rid=c.rid,
            status=c.status,
            prompt_len=len(c.prompt),
            new_tokens=len(c.tokens),
            decode_tokens_per_s=round(c.decode_tokens_per_s, 2),
        )
        # Requests that never produced a token carry no ttft_s at all:
        # downstream aggregation must see only real first-token
        # latencies, not queue-timeout wait times.
        if c.ttft is not None:
            fields["ttft_s"] = round(c.ttft, 4)
        self.metrics.write("serve_request", **fields)

"""Fleet serving: supervised replicas behind a health-gated router.

One ``ServeEngine`` is one replica; the ROADMAP's "millions of users"
need N of them behind a front door where a replica dying is a
NON-EVENT. This module composes the robustness pieces the repo
already drills one at a time — per-worker restart-with-backoff
(runtime/launch.py), graceful drain + ``/healthz`` (serve/server.py),
deterministic fault injection (runtime/chaos.py), and the fleet
telemetry view (obs/aggregate.py) — into that front door:

- ``ReplicaManager`` spawns N replica PROCESSES (each a full
  ``scripts/serve.py`` engine on its own port, restoring the same
  checkpoint), supervises them with exit classification + capped
  exponential backoff up to ``max_restarts`` per replica, and keeps
  every replica's state (``starting`` / ``healthy`` / ``draining`` /
  ``dead``) current from process liveness plus a ``/healthz`` poll
  loop. Fleet chaos (``kill:replica<R>@request<N>`` /
  ``stall:replica<R>@request<N>:<S>s``) fires from here, keyed on the
  router's global dispatch counter.
- ``Router`` dispatches requests with least-loaded selection plus
  PREFIX AFFINITY (a stable hash of the prompt's leading page-aligned
  tokens names a preferred replica, so the paged radix cache stays
  warm per replica; spill to least-loaded when the preferred replica
  is saturated) and wraps every dispatch in the robustness envelope:
  per-request deadline propagation, bounded retry with jittered
  exponential backoff on connection-level failures, optional
  tail-latency hedging (first completion wins, the loser is
  cancelled), and a per-replica circuit breaker (consecutive-failure
  threshold → open; half-open probe via ``/healthz``; close on
  success). A REFUSED connection trips the breaker immediately —
  nothing is listening — while a TIMEOUT only counts toward the
  threshold (maybe-overloaded; obs/aggregate.classify_unreachable is
  the shared classifier). In-flight requests on a replica that dies
  are REPLAYED to a survivor (safe: a request is stateless
  prompt+params until completion) and the replay is surfaced in the
  response's ``router`` digest, never silently duplicated — the
  router returns exactly one response per request, stamped with a
  fleet-level trace id.
- ``FleetServer`` is the HTTP front door: ``POST /generate`` through
  the router, fleet ``/healthz``/``/statusz``/``/metricsz`` (statusz
  delegates to obs/aggregate.py, scraped live from member replicas —
  the view PR 11 built "for the router" now feeds it), and
  ``POST /rollz`` for a rolling restart: drain → wait → restart →
  re-admit, one replica at a time, zero dropped requests.

Pure host-side stdlib — no JAX anywhere in this module; the device
work lives in the replica processes.
"""

from __future__ import annotations

import collections
import dataclasses
import http.client
import itertools
import json
import logging
import os
import queue as _queue
import random
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Optional, Sequence
from urllib.parse import urlsplit

from ddp_tpu.obs.aggregate import classify_unreachable
from ddp_tpu.obs.reqtrace import (
    HOP_BREAKER_WAIT,
    HOP_CAT,
    HOP_DISPATCH,
    HOP_HANDOFF,
    HOP_HEDGE,
    HOP_MIGRATE,
    HOP_MIGRATE_EXPORT,
    HOP_MIGRATE_INSTALL,
    HOP_RETRY,
    derive_span_id,
    derive_trace_id,
    encode_trace_context,
    format_trace_id,
    splitmix64,
)
from ddp_tpu.runtime.chaos import (
    ChaosEvent,
    fleet_events,
    reload_events,
)
from ddp_tpu.runtime.launch import classify_exit, free_port
from ddp_tpu.utils.metrics import StatSummary

# Disaggregated-serving roles (PR 16; docs/SERVING.md): which traffic
# the ROUTER sends a replica. "hybrid" is the classic co-located
# engine and the default — a roleless fleet behaves exactly as before.
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_HYBRID = "hybrid"
ROLES = (ROLE_PREFILL, ROLE_DECODE, ROLE_HYBRID)

logger = logging.getLogger("ddp_tpu")

# Replica lifecycle states (the router dispatches to HEALTHY only).
STARTING = "starting"  # process up, /healthz not yet answering ok
HEALTHY = "healthy"
DRAINING = "draining"  # finishing lanes, admitting nothing new
DEAD = "dead"  # process down (restart pending or budget exhausted)
STOPPED = "stopped"  # deliberately stopped (fleet shutdown)


# ---------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------


class CircuitBreaker:
    """Per-replica dispatch gate: closed → open → half-open → closed.

    CLOSED passes traffic; ``threshold`` consecutive failures (or one
    ``trip()`` — a refused connection) opens it. OPEN sheds all user
    traffic for ``cooldown_s``, then ``probe_due()`` moves to
    HALF_OPEN, where the next ``/healthz`` probe decides: success
    closes, failure re-opens (fresh cooldown). User traffic never
    probes — the manager's poll loop does, so a sick replica stops
    timing out user requests the moment it trips. ``clock`` is
    injectable (tests drive the cooldown explicitly).
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(
        self,
        threshold: int = 3,
        cooldown_s: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if threshold < 1:
            raise ValueError(f"breaker threshold must be >= 1: {threshold}")
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        # Dispatch handler threads and the manager's poll thread both
        # touch this breaker; its own lock keeps the count/state
        # transitions atomic without entangling the router/manager
        # locks.
        self._mu = threading.Lock()
        self.state = self.CLOSED
        self.failures = 0  # consecutive, reset by any success
        self.opens_total = 0
        self._opened_at: Optional[float] = None

    def allow_traffic(self) -> bool:
        """User dispatch passes only while CLOSED (half-open is probed
        by /healthz, not by user requests)."""
        return self.state == self.CLOSED

    def probe_due(self) -> bool:
        """True when the breaker wants a /healthz probe: OPEN past its
        cooldown (transitions to HALF_OPEN) or already HALF_OPEN (a
        lost probe must not wedge the breaker open forever)."""
        with self._mu:
            if (
                self.state == self.OPEN
                and self._clock() - self._opened_at >= self.cooldown_s
            ):
                self.state = self.HALF_OPEN
                return True
            return self.state == self.HALF_OPEN

    def record_success(self) -> None:
        """Any successful exchange closes and resets the count."""
        with self._mu:
            self.state = self.CLOSED
            self.failures = 0

    def record_failure(self) -> None:
        """One more consecutive failure; opens at ``threshold`` (or
        instantly from HALF_OPEN — the probe failed)."""
        with self._mu:
            self.failures += 1
            if self.state == self.HALF_OPEN or (
                self.state == self.CLOSED
                and self.failures >= self.threshold
            ):
                self._open()

    def trip(self) -> None:
        """Immediate open: a REFUSED connection means nothing is
        listening — eject now rather than letting ``threshold`` user
        requests time out first."""
        with self._mu:
            if self.state != self.OPEN:
                self._open()

    def _open(self) -> None:
        self.state = self.OPEN
        self._opened_at = self._clock()
        self.opens_total += 1
        self.failures = 0

    def snapshot(self) -> dict:
        return {
            "state": self.state,
            "failures": self.failures,
            "opens_total": self.opens_total,
        }


# ---------------------------------------------------------------------
# Transport (injectable: tests drive a fake, no sockets)
# ---------------------------------------------------------------------


class ReplicaUnreachable(Exception):
    """A dispatch/probe that never produced an HTTP response.

    ``kind`` is obs/aggregate.classify_unreachable's verdict
    (``timeout`` / ``refused`` / ``unreachable``); ``sent`` is True
    when the request had been handed to the replica before the
    failure — the REPLAY case (the replica may have started work;
    the retry is a replay, and the router says so); ``cancelled``
    marks a hedging loser whose socket the router closed on purpose.
    """

    def __init__(self, kind: str, *, sent: bool, cancelled: bool = False):
        super().__init__(kind)
        self.kind = kind
        self.sent = sent
        self.cancelled = cancelled


class _HttpCall:
    """One cancellable POST: ``run()`` blocks to a response,
    ``cancel()`` (from another thread) aborts the socket — how a
    hedging loser dies."""

    def __init__(self, url: str, path: str, body: dict, timeout: float):
        sp = urlsplit(url)
        self._conn = http.client.HTTPConnection(
            sp.hostname, sp.port, timeout=max(0.05, timeout)
        )
        self._path = path
        self._body = body
        self.cancelled = False

    def run(self) -> tuple[int, dict]:
        sent = False
        try:
            data = json.dumps(self._body).encode()
            self._conn.request(
                "POST", self._path, body=data,
                headers={"Content-Type": "application/json"},
            )
            sent = True
            resp = self._conn.getresponse()
            payload = json.loads(resp.read() or b"{}")
            return resp.status, payload
        except (OSError, http.client.HTTPException, ValueError) as e:
            raise ReplicaUnreachable(
                classify_unreachable(e), sent=sent,
                cancelled=self.cancelled,
            ) from e
        finally:
            self._conn.close()

    def cancel(self) -> None:
        self.cancelled = True
        try:
            self._conn.close()
        except OSError:
            pass


class HttpTransport:
    """The router's only I/O, behind two methods so tests can fake it:
    ``start`` returns a cancellable call handle, ``get_json`` is the
    probe/scrape path."""

    def start(
        self, url: str, path: str, body: dict, timeout: float
    ) -> _HttpCall:
        return _HttpCall(url, path, body, timeout)

    def get_json(self, url: str, path: str, timeout: float) -> dict:
        import urllib.request

        try:
            with urllib.request.urlopen(
                url.rstrip("/") + path, timeout=max(0.05, timeout)
            ) as r:
                return json.loads(r.read().decode())
        except (OSError, ValueError) as e:
            raise ReplicaUnreachable(
                classify_unreachable(e), sent=True
            ) from e

    # ---- the /pages transfer plane (PR 16) --------------------------

    def fetch_pages(
        self,
        url: str,
        prompt_tokens: Sequence[int],
        timeout: float,
        *,
        trace: Optional[str] = None,
    ) -> tuple[int, bytes]:
        """POST /pages/export on ``url`` → (status, raw body): the
        owner's longest cached prefix of the prompt as one binary
        page frame (200), or its JSON error body (404 prefix_not_
        found etc.) — the caller only forwards 200 bodies. ``trace``
        (the router's hop context line) rides the request body and is
        embedded in the exported DPKV header, so the frame itself
        names the migration that moved it."""
        sp = urlsplit(url)
        conn = http.client.HTTPConnection(
            sp.hostname, sp.port, timeout=max(0.05, timeout)
        )
        try:
            conn.request(
                "POST", "/pages/export",
                body=json.dumps(
                    {
                        "prompt_tokens": list(prompt_tokens),
                        **({"trace": trace} if trace is not None else {}),
                    }
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            return resp.status, resp.read()
        except (OSError, http.client.HTTPException) as e:
            raise ReplicaUnreachable(
                classify_unreachable(e), sent=True
            ) from e
        finally:
            conn.close()

    def push_pages(
        self, url: str, frame: bytes, timeout: float
    ) -> tuple[int, dict]:
        """POST /pages on ``url`` with one binary page frame →
        (status, JSON payload). The receiver validates before
        installing (serve/disagg.py), so a torn transfer surfaces as
        its 400 reason here, never as garbage pages there."""
        sp = urlsplit(url)
        conn = http.client.HTTPConnection(
            sp.hostname, sp.port, timeout=max(0.05, timeout)
        )
        try:
            conn.request(
                "POST", "/pages", body=frame,
                headers={"Content-Type": "application/octet-stream"},
            )
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read() or b"{}")
        except (OSError, http.client.HTTPException, ValueError) as e:
            raise ReplicaUnreachable(
                classify_unreachable(e), sent=True
            ) from e
        finally:
            conn.close()


# ---------------------------------------------------------------------
# Replica view
# ---------------------------------------------------------------------


class Replica:
    """One supervised serving process + the router's live view of it.

    The manager owns ``proc``/``state``/``restarts``; the router owns
    ``inflight`` (its local dispatch count) and the breaker; the poll
    loop refreshes ``slots``/``active``/``queue_depth`` from
    ``/healthz``. A bare Replica (no proc) is how unit tests and
    in-process routers use this class.
    """

    def __init__(
        self,
        index: int,
        url: Optional[str] = None,
        *,
        role: str = ROLE_HYBRID,
    ):
        if role not in ROLES:
            raise ValueError(
                f"replica role must be one of {ROLES}, got {role!r}"
            )
        self.index = int(index)
        self.url = url
        self.role = role
        self.state = STARTING if url is None else HEALTHY
        self.breaker = CircuitBreaker()
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.restarts = 0
        self.expected_exit = False  # drain/rolling-restart SIGTERM
        self.respawn_at: Optional[float] = None
        self.last_exit: Optional[str] = None
        self.inflight = 0
        self.slots: Optional[int] = None
        self.active = 0
        self.queue_depth = 0
        self.started_at: Optional[float] = None
        self.refused_probes = 0  # consecutive, on a non-STARTING replica
        # Model-lifecycle view (refreshed from /healthz): the serving
        # model version plus any registered named models. None/() on
        # pre-lifecycle replicas — and the gate the router uses so a
        # ``model=`` request never lands on a replica that does not
        # (yet) serve that model.
        self.model_version: Optional[str] = None
        self.models: tuple[str, ...] = ()

    @property
    def load(self) -> int:
        """Least-loaded ordering key: router-local in-flight plus the
        last-probed engine queue depth."""
        return self.inflight + self.queue_depth

    def snapshot(self) -> dict:
        return {
            "index": self.index,
            "url": self.url,
            "state": self.state,
            # Role rides only on disaggregated fleets: a roleless
            # fleet's snapshots stay byte-identical to PR 13's.
            **(
                {"role": self.role} if self.role != ROLE_HYBRID else {}
            ),
            "inflight": self.inflight,
            "queue_depth": self.queue_depth,
            "restarts": self.restarts,
            "breaker": self.breaker.snapshot(),
            **(
                {"last_exit": self.last_exit} if self.last_exit else {}
            ),
            # Lifecycle keys ride only once a versioned model is
            # advertised — versionless snapshots stay byte-identical.
            **(
                {"model_version": self.model_version}
                if self.model_version is not None
                else {}
            ),
            **(
                {"models": list(self.models)} if self.models else {}
            ),
        }


# ---------------------------------------------------------------------
# Prefix affinity
# ---------------------------------------------------------------------


def affinity_key(prompt: Sequence[int], page: int) -> int:
    """Stable 64-bit hash of the prompt's leading PAGE-ALIGNED tokens.

    Page-aligned so it keys exactly the pages the replica's radix
    index can serve: two prompts sharing a prefix through the same
    page boundary hash identically and land on the same replica,
    keeping that replica's prefix cache warm (PR 12). Prompts shorter
    than one page return 0 — no affinity, pure least-loaded. The
    chained splitmix64 fold is order-sensitive and cheap.
    """
    if page <= 0:
        return 0
    n = (len(prompt) // page) * page
    if n <= 0:
        return 0
    h = 0
    for t in prompt[:n]:
        h = splitmix64(h ^ (int(t) & 0xFFFFFFFFFFFFFFFF))
    return h or 1


def retry_backoff_s(
    attempt: int, base: float, cap: float, rng: random.Random
) -> float:
    """Full-jitter exponential backoff: U(0, min(cap, base·2^attempt)).

    Jitter decorrelates a retry herd (every client that saw the same
    failure would otherwise retry in lockstep); the cap bounds the
    worst sleep; the pure form (seeded rng in) is what tests pin.
    """
    return rng.uniform(0.0, min(cap, base * (2.0 ** max(0, attempt))))


# ---------------------------------------------------------------------
# Router
# ---------------------------------------------------------------------


@dataclasses.dataclass
class RouterConfig:
    """The robustness-envelope knobs (docs/SERVING.md table)."""

    retry_max: int = 3  # re-dispatch budget per request
    retry_backoff_s: float = 0.05  # jittered-exponential base
    retry_backoff_cap_s: float = 1.0
    hedge_after_s: Optional[float] = None  # None = hedging off
    affinity_page: int = 16  # 0 = least-loaded only
    affinity: bool = True  # False = random dispatch (the control)
    saturation_depth: int = 4  # spill when inflight >= slots + this
    default_deadline_s: float = 120.0  # requests without a timeout
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 2.0
    trace_seed: int = 0  # fleet-level trace-id space
    # ---- disaggregated serving (PR 16; all default-off) -------------
    # Two-stage dispatch: prompts whose page-aligned length reaches
    # ``prefill_cutoff_tokens`` run chunked prefill to completion on a
    # role=prefill replica, the prefilled pages migrate over POST
    # /pages, and the decode runs on a decode-capable replica. Any
    # stage failing just degrades to a normal dispatch (the decode
    # replica prefills locally — replay from the prompt, never a torn
    # page set).
    disagg: bool = False
    prefill_cutoff_tokens: int = 64
    # Fleet-global prefix tier: the affinity hash made AUTHORITATIVE —
    # a directory (leading-page hash → last replica to serve it) lets
    # the router pull a prefix's pages from their owner to wherever
    # the request actually lands, so churned/restarted replicas re-warm
    # from the fleet instead of re-prefilling.
    directory: bool = False
    # Per-migration transport budget (export + install are two HTTP
    # round-trips of raw KV bytes).
    migration_timeout_s: float = 10.0


class Router:
    """Health-gated dispatch over a fixed replica list.

    One response per request, always: retries, replays and hedges are
    internal — the caller sees a single (status, payload) stamped
    with a ``router`` digest (fleet rid + trace id, serving replica,
    attempts, replays, hedge outcome) so nothing recovers silently.
    """

    def __init__(
        self,
        replicas: Sequence[Replica],
        config: Optional[RouterConfig] = None,
        *,
        transport: Optional[HttpTransport] = None,
        clock: Callable[[], float] = time.monotonic,
        rng: Optional[random.Random] = None,
        on_dispatch: Optional[Callable[[int], None]] = None,
        tracer=None,
    ):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas = list(replicas)
        self.config = config or RouterConfig()
        self.transport = transport or HttpTransport()
        self._clock = clock
        # Fleet tracing (obs/tracer.Tracer or None): when enabled the
        # router stamps a trace-context line into every hop it makes
        # (dispatch, prefill handoff, /pages migration) and emits its
        # own cat="hop" spans on the request's trace id — the fleet
        # half of the causal timeline the replicas' reqtrace emits the
        # engine half of. None (the default) keeps every dispatch body
        # and record byte-identical to the untraced router.
        self.tracer = tracer
        self._rng = rng or random.Random(self.config.trace_seed)
        # Chaos hook: called with the global dispatch ordinal BEFORE
        # the attempt goes out — `kill:replica<R>@request<N>` fires
        # between admission and dispatch, the worst moment.
        self._on_dispatch = on_dispatch
        self._lock = threading.Lock()
        self._rid = itertools.count(1)
        for r in self.replicas:
            r.breaker = CircuitBreaker(
                self.config.breaker_threshold,
                self.config.breaker_cooldown_s,
                clock=clock,
            )
        self.dispatched_total = 0
        self.completed_total = 0
        self.retries_total = 0
        self.replays_total = 0
        self.hedges_total = 0
        self.hedge_wins_total = 0
        self.no_replica_total = 0
        self.deadline_exceeded_total = 0
        # ---- disaggregation state (PR 16) ---------------------------
        # Role-aware dispatch engages when ANY replica carries a
        # non-hybrid role (a prefill-only replica must never serve
        # end-user /generate traffic, disagg flag or not).
        self._role_aware = self.config.disagg or any(
            r.role != ROLE_HYBRID for r in self.replicas
        )
        # The fleet-global prefix directory: leading-page affinity
        # hash → index of the replica that last SERVED that prefix
        # (authoritative owner of its pages). Survives replica
        # restarts by construction — it lives here, not in them.
        self._prefix_dir: dict[int, int] = {}
        self.prefill_handoffs_total = 0
        self.migrations_total = 0
        self.migration_failures_total = 0
        self.pages_migrated_total = 0
        self.directory_pulls_total = 0
        self.directory_pull_hits_total = 0
        self.migration_seconds = StatSummary()
        # ---- fleet tracing state (PR 19) ----------------------------
        # propagated = the serving replica echoed our trace id back
        # (it adopted the context); orphaned = it completed without
        # the echo (old replica, or it judged our context malformed).
        self.trace_propagated_total = 0
        self.trace_orphaned_total = 0
        # Per-hop-kind latency summaries ("dispatch", "migrate", ...)
        # for /metricsz, and the bounded /requestz ring of recent
        # per-request hop chains (trace id hex → digest + hop list).
        self.hop_seconds: dict[str, StatSummary] = {}
        self._recent: collections.OrderedDict = collections.OrderedDict()

    REQUESTZ_RING = 512  # recent requests kept for /requestz?id=

    @property
    def _tracing(self) -> bool:
        return self.tracer is not None and self.tracer.enabled

    # ---- selection ---------------------------------------------------

    def _capable(self, r: Replica, need: Optional[str]) -> bool:
        """Can ``r`` take traffic of class ``need``? Hybrids take
        everything; None (a roleless fleet) disables the filter."""
        return (
            need is None
            or r.role == ROLE_HYBRID
            or r.role == need
        )

    def _serves_model(self, r: Replica, model: Optional[str]) -> bool:
        """Model gating (lifecycle PR): a ``model=`` request only goes
        to a replica that advertises it — a registered named model, or
        the serving model VERSION itself, which is how a mid-roll
        fleet keeps version-pinned requests off not-yet-swapped
        replicas. None (every pre-lifecycle request) disables the
        filter."""
        return (
            model is None
            or model in r.models
            or model == r.model_version
        )

    def _eligible(
        self,
        exclude: set[int],
        need: Optional[str] = None,
        model: Optional[str] = None,
    ) -> list[Replica]:
        return [
            r
            for r in self.replicas
            if r.state == HEALTHY
            and r.breaker.allow_traffic()
            and r.index not in exclude
            and self._capable(r, need)
            and self._serves_model(r, model)
        ]

    def _saturated(self, r: Replica) -> bool:
        slots = r.slots if r.slots else 1
        return r.inflight >= slots + self.config.saturation_depth

    def _select(
        self,
        prompt: Sequence[int],
        exclude: set[int],
        need: Optional[str] = None,
        model: Optional[str] = None,
    ) -> Optional[Replica]:
        """Affinity-preferred, least-loaded otherwise. Call under the
        lock. The preferred index is ``key % len(replicas)`` over the
        FIXED replica list, so it survives restarts (replica N's
        replacement inherits N's affinity and re-warms the same
        prefixes). On a role-aware fleet, /generate selection defaults
        to decode-capable replicas (decode | hybrid) — prefill-tier
        replicas only ever see the router's own prefill-stage
        dispatches."""
        if need is None and self._role_aware:
            need = ROLE_DECODE
        elig = self._eligible(exclude, need, model)
        if not elig:
            return None
        if not self.config.affinity:
            return self._rng.choice(elig)
        key = affinity_key(prompt, self.config.affinity_page)
        if key:
            pref = self.replicas[key % len(self.replicas)]
            if (
                pref.index not in exclude
                and pref in elig
                and not self._saturated(pref)
            ):
                return pref
        return min(elig, key=lambda r: (r.load, r.index))

    # ---- dispatch ----------------------------------------------------

    def dispatch(self, body: dict) -> tuple[int, dict]:
        """POST /generate through the robustness envelope →
        (http_status, payload-with-router-digest)."""
        prompt = body.get("prompt_tokens") or []
        # Multi-model routing label: selection only considers replicas
        # that advertise this model (name or version); the replica's
        # own /generate handler re-validates it.
        model = body.get("model")
        if not isinstance(model, str):
            model = None
        try:
            timeout = (
                float(body["timeout"]) if body.get("timeout") is not None
                else None
            )
        except (TypeError, ValueError):
            timeout = None
        # `is not None`, not truthiness: a client's explicit
        # timeout=0 means "an already-expired deadline" (immediate
        # 504), never "use the 120s default".
        deadline = self._clock() + (
            timeout
            if timeout is not None
            else self.config.default_deadline_s
        )
        with self._lock:
            frid = next(self._rid)
            self.dispatched_total += 1
            ordinal = self.dispatched_total
            hook = self._on_dispatch
        trace_id = derive_trace_id(self.config.trace_seed, frid)
        if hook is not None:
            # Outside the lock: the chaos hook may SIGKILL a replica,
            # and the poll loop needs the lock to mark it dead.
            hook(ordinal)
        digest = {
            "rid": frid,
            "trace_id": format_trace_id(trace_id),
            "replica": None,
            "attempts": 0,
            "replays": 0,
            "hedged": False,
            "hedge_won": False,
        }
        tctx = self._tctx(trace_id, digest["trace_id"])
        # Disaggregated staging (PR 16), OUTSIDE the retry loop and
        # best-effort by design: a long prompt prefills on the prefill
        # tier and its pages migrate to a decode replica; a prefix the
        # directory locates on another replica is pulled to where this
        # request will land. Every failure inside degrades to the
        # plain dispatch below — the decode replica then prefills from
        # the prompt itself (the replay-from-prompt guarantee; a
        # mid-migration death never leaves a torn page set because the
        # receiver installs atomically or not at all).
        dir_key = (
            affinity_key(prompt, self.config.affinity_page)
            if self.config.directory
            else 0
        )
        if self._role_aware or self.config.directory:
            self._stage_pages(prompt, body, deadline, dir_key, tctx)
        exclude: set[int] = set()  # failed THIS request
        backoff_i = 0
        idle_rounds = 0  # rounds with NO eligible replica at all
        hard_failure = False  # any connection-level failure seen
        # Max measured Retry-After across 429s this request saw: when
        # the WHOLE fleet is merely full (no hard failures), the
        # client gets backpressure-with-a-hint, not a fake 502.
        saturated_retry_after: Optional[float] = None
        while True:
            remaining = deadline - self._clock()
            if remaining <= 0:
                return self._finish(
                    504, {"error": "deadline_exceeded"}, digest, tctx,
                )
            with self._lock:
                first = self._select(prompt, exclude, model=model)
            if first is None:
                # Idle rounds spend the same budget as failed
                # attempts: a fleet of open breakers must converge on
                # a 503, not spin the backoff loop forever.
                idle_rounds += 1
                if (
                    digest["attempts"] + idle_rounds
                    > self.config.retry_max
                ):
                    with self._lock:
                        self.no_replica_total += 1
                    # Pure backpressure (every exclusion came from a
                    # 429, no hard failure) names itself and carries
                    # the drain hint, whichever exit path exhausts
                    # the budget first.
                    saturated = (
                        saturated_retry_after is not None
                        and not hard_failure
                    )
                    return self._finish(
                        503,
                        {
                            "error": (
                                "fleet_saturated"
                                if saturated
                                else "no_replica_available"
                            ),
                            **(
                                {
                                    "retry_after_s": round(
                                        saturated_retry_after, 2
                                    )
                                }
                                if saturated_retry_after is not None
                                else {}
                            ),
                        },
                        digest,
                        tctx,
                    )
                # Every currently-eligible replica failed this
                # request: forget the exclusions after a backoff beat
                # — one of them (or a restart) may have recovered.
                exclude = set()
                t0p = time.perf_counter()
                self._backoff(backoff_i, remaining)
                if tctx is not None:
                    # The stall is itself a hop: a request that spent
                    # 300ms waiting out open breakers should show that
                    # wait on its timeline, not an unexplained gap.
                    self._hop(
                        tctx, HOP_BREAKER_WAIT, t0p,
                        time.perf_counter() - t0p,
                        {"round": idle_rounds},
                    )
                backoff_i += 1
                continue
            digest["attempts"] += 1
            winner, status, payload, hedged, hedge_won, failures = (
                self._race(
                    first, prompt, body, deadline, exclude, tctx,
                    model=model,
                )
            )
            if hedged:
                digest["hedged"] = True
                with self._lock:
                    self.hedges_total += 1
                    if hedge_won:
                        self.hedge_wins_total += 1
                if hedge_won:
                    digest["hedge_won"] = True
            for rep, err in failures:
                hard_failure = True
                self._note_failure(rep, err)
                exclude.add(rep.index)
                if err.sent:
                    # The request had reached a replica that then died
                    # mid-flight: the next attempt is a REPLAY (safe —
                    # stateless until completion — but never silent).
                    digest["replays"] += 1
                    with self._lock:
                        self.replays_total += 1
            if winner is not None:
                if status == 429:
                    ra = payload.get("retry_after_s")
                    if isinstance(ra, (int, float)):
                        saturated_retry_after = max(
                            saturated_retry_after or 0.0, float(ra)
                        )
                handled = self._handle_response(
                    winner, status, payload, digest, exclude, tctx
                )
                if handled is not None:
                    if dir_key and handled[0] == 200:
                        # The directory learns from COMPLETIONS: the
                        # serving replica now holds the prompt's
                        # published pages (release() indexed them at
                        # retire) and becomes the prefix's owner.
                        with self._lock:
                            self._prefix_dir[dir_key] = winner.index
                    return handled
            if digest["attempts"] > self.config.retry_max:
                if saturated_retry_after is not None and not hard_failure:
                    # Every attempt was answered — the fleet is FULL,
                    # not broken. Backpressure with the largest
                    # measured drain ETA, never a fake 502 (the
                    # docs/SERVING.md contract: 503 only when no
                    # replica can take the request).
                    return self._finish(
                        503,
                        {
                            "error": "fleet_saturated",
                            "retry_after_s": round(
                                saturated_retry_after, 2
                            ),
                        },
                        digest,
                        tctx,
                    )
                return self._finish(
                    502,
                    {
                        "error": "upstream_failed",
                        "detail": (
                            f"{failures[-1][1].kind}"
                            if failures
                            else "retries exhausted"
                        ),
                    },
                    digest,
                    tctx,
                )
            with self._lock:
                self.retries_total += 1
            if tctx is not None:
                self._hop_instant(
                    tctx, HOP_RETRY, {"attempt": digest["attempts"]}
                )
            self._backoff(backoff_i, deadline - self._clock())
            backoff_i += 1

    # ---- disaggregated staging (PR 16) -------------------------------

    def _stage_pages(
        self,
        prompt: Sequence[int],
        body: dict,
        deadline: float,
        dir_key: int,
        tctx: Optional[dict] = None,
    ) -> None:
        """Best-effort page placement BEFORE the dispatch race: the
        prefill-tier handoff for long prompts, then (when that did not
        already move the pages) the prefix-directory pull. Never
        raises, never blocks past the migration budget; on any failure
        the plain dispatch below simply prefills locally."""
        if not prompt:
            return
        with self._lock:
            target = self._select(prompt, set())
        if target is None:
            return
        staged = False
        if self.config.disagg:
            from ddp_tpu.serve.scheduler import classify_prompt

            cls = classify_prompt(
                len(prompt),
                self.config.affinity_page,
                cutoff_tokens=self.config.prefill_cutoff_tokens,
            )
            if cls == ROLE_PREFILL:
                with self._lock:
                    tier = [
                        r
                        for r in self._eligible(set(), ROLE_PREFILL)
                        if r.role == ROLE_PREFILL
                    ]
                    src = (
                        min(tier, key=lambda r: (r.load, r.index))
                        if tier
                        else None
                    )
                if src is not None and src.index != target.index:
                    staged = self._prefill_handoff(
                        src, target, prompt, body, deadline, tctx
                    )
        if staged or not dir_key:
            return
        with self._lock:
            owner_i = self._prefix_dir.get(dir_key)
            owner = (
                self.replicas[owner_i]
                if owner_i is not None
                and owner_i != target.index
                else None
            )
            pull = (
                owner is not None
                and owner.state == HEALTHY
                and owner.breaker.allow_traffic()
            )
        if pull:
            with self._lock:
                self.directory_pulls_total += 1
            if self._migrate(owner, target, prompt, deadline, tctx):
                with self._lock:
                    self.directory_pull_hits_total += 1

    def _prefill_handoff(
        self,
        src: Replica,
        target: Replica,
        prompt: Sequence[int],
        body: dict,
        deadline: float,
        tctx: Optional[dict] = None,
    ) -> bool:
        """Stage one: run the prompt to prefill completion on the
        prefill tier (max_new_tokens=1 — the chunk programs ingest the
        whole prompt; the single sampled token is discarded), which
        publishes its full pages into ``src``'s radix index at retire;
        stage two migrates them to ``target``. One attempt, budget
        bounded — the robust RETRY path is the plain dispatch this
        degrades into, not a second handoff."""
        remaining = deadline - self._clock()
        if remaining <= 0.05:
            return False
        b = dict(body)
        b["max_new_tokens"] = 1
        b["timeout"] = round(remaining, 3)
        span16 = None
        if tctx is not None:
            # The prefill replica adopts this hop's context, so its
            # own admit→chunks→retire timeline hangs off the SAME
            # trace id as the decode that follows it.
            span, line = self._span_ctx(tctx)
            span16 = f"{span:016x}"
            b["trace"] = line
        t0p = time.perf_counter()
        call = self.transport.start(
            src.url, "/generate", b, remaining + 2.0
        )
        with self._lock:
            src.inflight += 1
        try:
            status, _payload = call.run()
        except ReplicaUnreachable as e:
            self._note_failure(src, e)
            return False
        finally:
            with self._lock:
                src.inflight -= 1
        if status != 200:
            return False
        src.breaker.record_success()
        with self._lock:
            self.prefill_handoffs_total += 1
        if tctx is not None:
            dur = time.perf_counter() - t0p
            tctx["hops"]["handoff_s"] = round(dur, 6)
            self._hop(
                tctx, HOP_HANDOFF, t0p, dur,
                {
                    "src": src.index,
                    "dst": target.index,
                    "span": span16,
                    "tokens": len(prompt),
                },
            )
        return self._migrate(src, target, prompt, deadline, tctx)

    def _migrate(
        self,
        src: Replica,
        dst: Replica,
        prompt: Sequence[int],
        deadline: float,
        tctx: Optional[dict] = None,
    ) -> bool:
        """Move the prompt's cached prefix pages ``src`` → ``dst``
        (export, then push; two HTTP round-trips of raw KV bytes) →
        True on an installed prefix. Counts pages and latency; all
        failures (owner lost the prefix, pool full, transport death)
        are just a False — the request replays from the prompt."""
        t0 = self._clock()
        budget = min(
            self.config.migration_timeout_s, deadline - t0
        )
        if budget <= 0.05:
            return False
        mctx = None
        if tctx is not None:
            _span, mctx = self._span_ctx(tctx)
        t0p = time.perf_counter()
        status = 0  # stage marker: != 200 until the export succeeded
        try:
            ex0 = time.perf_counter()
            # Positional call when untraced: injected fake transports
            # predate the ``trace`` kwarg and must keep working.
            status, raw = (
                self.transport.fetch_pages(
                    src.url, prompt, budget, trace=mctx
                )
                if mctx is not None
                else self.transport.fetch_pages(src.url, prompt, budget)
            )
            ex1 = time.perf_counter()
            if status != 200:
                with self._lock:
                    self.migration_failures_total += 1
                return False
            in0 = time.perf_counter()
            status, payload = self.transport.push_pages(
                dst.url, raw, budget
            )
            in1 = time.perf_counter()
        except ReplicaUnreachable as e:
            self._note_failure(src if status != 200 else dst, e)
            with self._lock:
                self.migration_failures_total += 1
            return False
        if status != 200:
            with self._lock:
                self.migration_failures_total += 1
            return False
        copied = int(payload.get("copied_pages", 0))
        with self._lock:
            self.migrations_total += 1
            self.pages_migrated_total += copied
            self.migration_seconds.add(self._clock() - t0)
        if tctx is not None:
            self._hop(
                tctx, HOP_MIGRATE_EXPORT, ex0, ex1 - ex0,
                {"src": src.index, "bytes": len(raw)},
            )
            self._hop(
                tctx, HOP_MIGRATE_INSTALL, in0, in1 - in0,
                {"dst": dst.index, "pages": copied},
            )
            dur = time.perf_counter() - t0p
            tctx["hops"]["migrate_s"] = round(dur, 6)
            self._hop(
                tctx, HOP_MIGRATE, t0p, dur,
                {
                    "src": src.index,
                    "dst": dst.index,
                    "bytes": len(raw),
                    "pages": copied,
                },
            )
        return True

    def _handle_response(
        self,
        rep: Replica,
        status: int,
        payload: dict,
        digest: dict,
        exclude: set[int],
        tctx: Optional[dict] = None,
    ) -> Optional[tuple[int, dict]]:
        """An HTTP response arrived: deliver it, or turn replica-local
        backpressure/drain into a routed retry. Returns None to keep
        retrying."""
        # The dispatch span closes here, where finality is decided:
        # winner=True marks THE attempt whose response the client got
        # (a 200 — the fleet-timeline validator requires exactly one),
        # everything re-routed below closes as a loser with its status.
        last = tctx.pop("last", None) if tctx is not None else None

        def _span(won: bool) -> None:
            if last is None:
                return
            dur = last["t1"] - last["t0"]
            if won:
                tctx["hops"]["dispatch_s"] = round(dur, 6)
            self._hop(
                tctx, HOP_DISPATCH, last["t0"], dur,
                {
                    "attempt": last["attempt"],
                    "replica": last["replica"],
                    "span": last["span"],
                    "winner": won,
                    "status": status,
                },
            )

        if status == 500:
            # engine failed: the process answers HTTP but cannot
            # serve. Count toward the breaker and re-route.
            _span(False)
            rep.breaker.record_failure()
            exclude.add(rep.index)
            return None
        rep.breaker.record_success()
        if status == 503 and payload.get("error") == "draining":
            # The replica started draining between our poll and this
            # dispatch: update the router's view and re-route — drain
            # is honored fleet-wide, not surfaced to the client.
            _span(False)
            with self._lock:
                rep.state = DRAINING
            exclude.add(rep.index)
            return None
        if status == 429:
            # Backpressure with a measured Retry-After: this replica
            # is full, another may not be — retry elsewhere now, only
            # backing off when everyone is full (the retry loop's
            # no-eligible path).
            _span(False)
            exclude.add(rep.index)
            return None
        _span(status == 200)
        if tctx is not None and status == 200:
            # The replica echoes our trace id back iff it adopted the
            # context we sent — the propagation health signal.
            with self._lock:
                if payload.get("trace_id") == digest["trace_id"]:
                    self.trace_propagated_total += 1
                else:
                    self.trace_orphaned_total += 1
        digest["replica"] = rep.index
        with self._lock:
            self.completed_total += 1
        return self._finish(status, payload, digest, tctx)

    def _finish(
        self,
        status: int,
        payload: dict,
        digest: dict,
        tctx: Optional[dict] = None,
    ) -> tuple[int, dict]:
        if status == 504:
            with self._lock:
                self.deadline_exceeded_total += 1
        if tctx is not None:
            # Per-hop seconds on the digest (and so on the response's
            # ``router`` block), and the /requestz ring entry — every
            # exit path lands here, success or not.
            if tctx["hops"]:
                digest["hops"] = dict(tctx["hops"])
            self._store_recent(tctx, digest)
        payload = dict(payload)
        payload["router"] = digest
        return status, payload

    def _backoff(self, attempt: int, remaining: float) -> None:
        if remaining <= 0:
            return
        delay = retry_backoff_s(
            attempt,
            self.config.retry_backoff_s,
            self.config.retry_backoff_cap_s,
            self._rng,
        )
        time.sleep(min(delay, max(0.0, remaining)))

    def _note_failure(self, rep: Replica, err: ReplicaUnreachable) -> None:
        """The satellite-2 distinction, applied: refused = dead, eject
        immediately; timeout/reset = maybe-overloaded, count toward
        the consecutive-failure threshold."""
        if err.kind == "refused":
            rep.breaker.trip()
        else:
            rep.breaker.record_failure()

    # ---- fleet tracing (PR 19) ---------------------------------------

    def _tctx(self, trace_id: int, aid: str) -> Optional[dict]:
        """Per-request tracing context, or None when tracing is off
        (the None path is the byte-identical untraced router). ``n``
        salts one span id per hop; ``hops`` stages the per-hop seconds
        that ride ``body["hops"]`` into the replica's serve_request
        record; ``spans`` is the /requestz hop chain."""
        if not self._tracing:
            return None
        return {
            "tid": trace_id,
            "aid": aid,
            "n": 0,
            "t0": time.perf_counter(),
            "hops": {},
            "spans": [],
        }

    def _span_ctx(self, tctx: dict) -> tuple[int, str]:
        """Mint the next span under this request's trace → (span id,
        context line for the outgoing hop's body/header)."""
        span = derive_span_id(tctx["tid"], tctx["n"])
        tctx["n"] += 1
        return span, encode_trace_context(tctx["tid"], span, 0)

    def _hop(
        self, tctx: dict, name: str, t0_perf: float, dur_s: float,
        args: dict,
    ) -> None:
        """One finished router hop, on all three surfaces at once: a
        ``cat="hop"`` async span under the request's trace id (the
        merged fleet timeline's router half), the per-kind seconds
        summary (/metricsz), and the /requestz ring's hop chain."""
        self.tracer.async_complete(
            name, t0_perf, dur_s, tctx["aid"], args, cat=HOP_CAT
        )
        with self._lock:
            self.hop_seconds.setdefault(
                name.partition(".")[2], StatSummary()
            ).add(dur_s)
        tctx["spans"].append(
            {"name": name, "dur_s": round(dur_s, 6), "args": args}
        )

    def _hop_instant(self, tctx: dict, name: str, args: dict) -> None:
        self.tracer.async_instant(
            name, time.perf_counter(), tctx["aid"], args, cat=HOP_CAT
        )
        tctx["spans"].append({"name": name, "args": args})

    def _store_recent(self, tctx: dict, digest: dict) -> None:
        with self._lock:
            self._recent[digest["trace_id"]] = {
                "digest": digest,
                "hops": list(tctx["spans"]),
            }
            while len(self._recent) > self.REQUESTZ_RING:
                self._recent.popitem(last=False)

    def requestz(self, trace_id: str) -> Optional[dict]:
        """One recent request's router-side view (the /requestz ring):
        final digest + the hop chain, keyed by hex trace id."""
        with self._lock:
            entry = self._recent.get(str(trace_id))
            if entry is None:
                return None
            return {
                "trace_id": str(trace_id),
                "router": {
                    "digest": dict(entry["digest"]),
                    "hops": [dict(h) for h in entry["hops"]],
                },
            }

    # ---- the race: one attempt, optionally hedged --------------------

    def _race(
        self,
        first: Replica,
        prompt: Sequence[int],
        body: dict,
        deadline: float,
        exclude: set[int],
        tctx: Optional[dict] = None,
        model: Optional[str] = None,
    ):
        """Run one attempt; if it straggles past ``hedge_after_s``,
        duplicate it to a second replica — FIRST COMPLETION WINS, the
        loser's socket is closed (its replica finishes the wasted
        decode, but the client sees exactly one response). Returns
        ``(winner, status, payload, hedged, hedge_won, failures)``;
        ``winner`` None means every launched attempt failed at the
        connection level (``failures`` holds them for breaker/replay
        accounting)."""
        results: _queue.Queue = _queue.Queue()
        calls: dict[int, object] = {}
        att: dict[int, dict] = {}  # replica index → this attempt's span

        def _run(rep: Replica, call) -> None:
            try:
                status, payload = call.run()
                results.put((rep, status, payload, None))
            except ReplicaUnreachable as e:
                results.put((rep, None, None, e))
            finally:
                with self._lock:
                    rep.inflight -= 1

        def _launch(rep: Replica) -> None:
            remaining = max(0.05, deadline - self._clock())
            b = dict(body)
            # Deadline propagation: the replica's own queue-timeout
            # eviction enforces the same deadline we are racing, so a
            # doomed request dies in ITS queue, not on our socket.
            b["timeout"] = round(remaining, 3)
            t0p = time.perf_counter()
            if tctx is not None:
                # Each attempt is its own span under the request's
                # trace: the replica adopts this context at admission,
                # so its engine timeline's ``parent`` names exactly
                # which attempt produced it — how the merged fleet
                # trace tells a hedge winner's decode from the loser's.
                tctx["hops"].setdefault(
                    "queue_s", round(t0p - tctx["t0"], 6)
                )
                span, line = self._span_ctx(tctx)
                b["trace"] = line
                if tctx["hops"]:
                    b["hops"] = dict(tctx["hops"])
                att[rep.index] = {
                    "span": f"{span:016x}",
                    "attempt": tctx["n"],
                    "replica": rep.index,
                    "t0": t0p,
                }
            call = self.transport.start(
                rep.url, "/generate", b, remaining + 2.0
            )
            calls[rep.index] = call
            with self._lock:
                rep.inflight += 1
            threading.Thread(
                target=_run, args=(rep, call), daemon=True
            ).start()

        def _cancel_span(idx: int) -> None:
            """Close a cancelled attempt's span at cancellation time —
            the loser's thread dies without delivering a result."""
            a = att.pop(idx, None)
            if a is None:
                return
            now_p = time.perf_counter()
            self._hop(
                tctx, HOP_DISPATCH, a["t0"], now_p - a["t0"],
                {**{k: a[k] for k in ("attempt", "replica", "span")},
                 "winner": False, "cancelled": True},
            )

        _launch(first)
        outstanding = {first.index: first}
        hedged = False
        failures: list[tuple[Replica, ReplicaUnreachable]] = []
        hedge_at = (
            self._clock() + self.config.hedge_after_s
            if self.config.hedge_after_s is not None
            else None
        )
        while outstanding:
            now = self._clock()
            if (
                hedge_at is not None
                and not hedged
                and now >= hedge_at
            ):
                with self._lock:
                    second = self._select(
                        prompt,
                        exclude | set(outstanding),
                        model=model,
                    )
                if second is not None:
                    hedged = True
                    if tctx is not None:
                        self._hop_instant(
                            tctx, HOP_HEDGE, {"replica": second.index}
                        )
                    _launch(second)
                    outstanding[second.index] = second
                hedge_at = None  # one hedge per dispatch, fired or not
            if hedge_at is not None and not hedged:
                wait = max(0.005, min(hedge_at, deadline + 2.0) - now)
            else:
                wait = max(0.005, deadline + 2.0 - now)
            try:
                rep, status, payload, err = results.get(timeout=wait)
            except _queue.Empty:
                if self._clock() > deadline + 2.0:
                    # Transport timeouts should have fired already;
                    # treat stragglers as timeouts and let the retry
                    # loop (which re-checks the deadline) decide.
                    for idx in list(outstanding):
                        calls[idx].cancel()
                        if tctx is not None:
                            _cancel_span(idx)
                        del outstanding[idx]
                    return None, None, None, hedged, False, failures
                continue
            outstanding.pop(rep.index, None)
            if err is not None:
                if not err.cancelled:
                    failures.append((rep, err))
                    if tctx is not None:
                        a = att.pop(rep.index, None)
                        if a is not None:
                            now_p = time.perf_counter()
                            self._hop(
                                tctx, HOP_DISPATCH, a["t0"],
                                now_p - a["t0"],
                                {
                                    "attempt": a["attempt"],
                                    "replica": a["replica"],
                                    "span": a["span"],
                                    "winner": False,
                                    "error": err.kind,
                                },
                            )
                continue
            # First completion wins: cancel the rest.
            for idx in outstanding:
                calls[idx].cancel()
                if tctx is not None:
                    _cancel_span(idx)
            hedge_won = hedged and rep.index != first.index
            if tctx is not None:
                a = att.pop(rep.index, None)
                if a is not None:
                    # Held open until _handle_response decides whether
                    # this response is final — the span closes there,
                    # marked winner or loser.
                    tctx["last"] = {**a, "t1": time.perf_counter()}
            return rep, status, payload, hedged, hedge_won, failures
        return None, None, None, hedged, False, failures

    # ---- state -------------------------------------------------------

    def state(self) -> dict:
        """JSON-ready router snapshot: the fleet /statusz block, the
        render_fleet gauge source, and the fleet_poll record body."""
        with self._lock:
            by_state: dict[str, int] = {}
            for r in self.replicas:
                by_state[r.state] = by_state.get(r.state, 0) + 1
            return {
                "replicas": len(self.replicas),
                "replicas_healthy": by_state.get(HEALTHY, 0),
                "replicas_draining": by_state.get(DRAINING, 0),
                "replicas_dead": by_state.get(DEAD, 0),
                "replicas_starting": by_state.get(STARTING, 0),
                "breaker_open": sum(
                    1
                    for r in self.replicas
                    if r.breaker.state != CircuitBreaker.CLOSED
                ),
                "breaker_opens_total": sum(
                    r.breaker.opens_total for r in self.replicas
                ),
                "dispatched_total": self.dispatched_total,
                "completed_total": self.completed_total,
                "retries_total": self.retries_total,
                "replays_total": self.replays_total,
                "hedges_total": self.hedges_total,
                "hedge_wins_total": self.hedge_wins_total,
                "no_replica_total": self.no_replica_total,
                "deadline_exceeded_total": self.deadline_exceeded_total,
                # Lifecycle block: version → replica count, ABSENT
                # until any replica advertises one — the reload loop's
                # convergence check ("fleet serves exactly one
                # version") and /healthz read it; versionless fleets
                # stay byte-identical.
                **(
                    {
                        "model_versions": {
                            v: sum(
                                1
                                for r in self.replicas
                                if r.model_version == v
                            )
                            for v in sorted(
                                {
                                    r.model_version
                                    for r in self.replicas
                                    if r.model_version is not None
                                }
                            )
                        }
                    }
                    if any(
                        r.model_version is not None
                        for r in self.replicas
                    )
                    else {}
                ),
                # Disaggregation block: ABSENT on classic fleets, so
                # every downstream surface (fleet_poll records,
                # /metricsz gauges, health_report triage) stays
                # byte-identical when the feature is off.
                **(
                    {
                        "replica_roles": {
                            str(r.index): r.role
                            for r in self.replicas
                        },
                        "prefill_handoffs_total":
                            self.prefill_handoffs_total,
                        "migrations_total": self.migrations_total,
                        "migration_failures_total":
                            self.migration_failures_total,
                        "pages_migrated_total":
                            self.pages_migrated_total,
                        "directory_pulls_total":
                            self.directory_pulls_total,
                        "directory_pull_hits_total":
                            self.directory_pull_hits_total,
                        "directory_size": len(self._prefix_dir),
                        "migration_seconds":
                            self.migration_seconds.snapshot(ndigits=6),
                    }
                    if self._role_aware or self.config.directory
                    else {}
                ),
                # Fleet-tracing block: ABSENT unless a tracer is
                # attached and enabled — the untraced router's state()
                # (and every surface built from it) stays byte-
                # identical to PR 18's.
                **(
                    {
                        "trace_propagated_total":
                            self.trace_propagated_total,
                        "trace_orphaned_total":
                            self.trace_orphaned_total,
                        "hop_seconds": {
                            k: s.snapshot(ndigits=6)
                            for k, s in sorted(self.hop_seconds.items())
                        },
                    }
                    if self._tracing
                    else {}
                ),
                "replica_states": [r.snapshot() for r in self.replicas],
            }


# ---------------------------------------------------------------------
# Replica manager (process supervision)
# ---------------------------------------------------------------------


def _serve_script() -> str:
    """Default replica entrypoint: the repo's scripts/serve.py."""
    return os.path.join(
        os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
        "scripts",
        "serve.py",
    )


class ReplicaManager:
    """Spawns and supervises N ``scripts/serve.py`` replica processes.

    Each replica serves on its own port (restoring the same
    checkpoint — ``serve_args`` is the shared CLI tail). Supervision
    is the PR-5 recipe applied per replica: a dead process is
    CLASSIFIED (runtime/launch.classify_exit), restarted after capped
    exponential backoff, up to ``max_restarts`` times — independent
    budgets, because one crash-looping replica must not spend its
    siblings' budget. The poll loop keeps every replica's state
    current from ``/healthz`` (which also carries drain visibility
    and the queue-depth load signal) and runs the breakers' half-open
    probes. Fleet chaos fires from here via ``kill_replica`` /
    ``stall_replica`` (SIGKILL / SIGSTOP-then-SIGCONT).
    """

    def __init__(
        self,
        n_replicas: int,
        serve_args: Sequence[str],
        *,
        workdir: str,
        script: Optional[str] = None,
        python: str = sys.executable,
        max_restarts: int = 2,
        restart_backoff: float = 0.5,
        poll_interval: float = 0.25,
        probe_timeout: float = 2.0,
        startup_grace_s: float = 120.0,
        transport: Optional[HttpTransport] = None,
        clock: Callable[[], float] = time.monotonic,
        metrics=None,
        roles: Optional[Sequence[str]] = None,
        trace_dir: Optional[str] = None,
    ):
        if n_replicas < 1:
            raise ValueError(f"need at least 1 replica, got {n_replicas}")
        if roles is not None and len(roles) != n_replicas:
            raise ValueError(
                f"{len(roles)} roles for {n_replicas} replicas — the "
                "role list must name every replica"
            )
        self.roles = list(roles) if roles is not None else None
        self.serve_args = list(serve_args)
        self.script = script or _serve_script()
        self.python = python
        self.workdir = workdir
        self.max_restarts = int(max_restarts)
        self.restart_backoff = float(restart_backoff)
        self.poll_interval = float(poll_interval)
        self.probe_timeout = float(probe_timeout)
        self.startup_grace_s = float(startup_grace_s)
        self.transport = transport or HttpTransport()
        self._clock = clock
        self.metrics = metrics
        # Fleet tracing: each replica exports its Perfetto trace (and
        # runs --reqtrace) under trace_dir/replica<i>; None (default)
        # spawns byte-identical argv to the untraced manager.
        self.trace_dir = trace_dir
        self.replicas = [
            Replica(
                i,
                role=(
                    self.roles[i] if self.roles is not None
                    else ROLE_HYBRID
                ),
            )
            for i in range(n_replicas)
        ]
        self.restarts_total = 0
        self.rolling_restarts_total = 0
        self.fleet_reloads_total = 0
        self.chaos_kills = 0
        self.chaos_stalls = 0
        self._logs: dict[int, object] = {}
        self._stall_timers: list[threading.Timer] = []
        self._stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()  # spawn/kill vs poll

    # ---- lifecycle ---------------------------------------------------

    def start(self) -> "ReplicaManager":
        os.makedirs(self.workdir, exist_ok=True)
        for rep in self.replicas:
            self._spawn(rep)
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="fleet-poll", daemon=True
        )
        self._poll_thread.start()
        return self

    def stop(self, *, drain_timeout: float = 0.0) -> None:
        """Stop everything. ``drain_timeout > 0`` drains first: every
        replica gets SIGTERM (its graceful path) and that long to
        finish lanes before the kill."""
        self._stop.set()
        for t in self._stall_timers:
            t.cancel()
        for rep in self.replicas:
            rep.expected_exit = True
            rep.state = STOPPED
            if rep.proc is not None and rep.proc.poll() is None:
                try:
                    rep.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = self._clock() + max(0.0, drain_timeout)
        for rep in self.replicas:
            if rep.proc is None:
                continue
            try:
                rep.proc.wait(max(0.1, deadline - self._clock()))
            except subprocess.TimeoutExpired:
                rep.proc.kill()
                rep.proc.wait(10)
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5)
        for log in self._logs.values():
            try:
                log.close()
            except OSError:
                pass

    def __enter__(self) -> "ReplicaManager":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- spawning ----------------------------------------------------

    def _spawn(self, rep: Replica) -> None:
        port = free_port()
        argv = [
            self.python,
            self.script,
            *self.serve_args,
            # Role is per-replica (the one spot the shared CLI tail
            # differs): the replica advertises it on /healthz and
            # /statusz, and a restarted replica KEEPS its role — the
            # tier topology survives churn.
            *(
                ["--role", rep.role]
                if self.roles is not None
                else []
            ),
            # After serve_args, so the manager's per-replica trace dir
            # wins (argparse last-wins) and a restarted replica keeps
            # exporting to ITS directory — the merged fleet timeline
            # survives churn.
            *(
                [
                    "--trace_dir",
                    os.path.join(
                        self.trace_dir, f"replica{rep.index}"
                    ),
                    "--reqtrace",
                    # Distinct tracer pid per replica (router is 0):
                    # the merged fleet document pairs b/e spans per
                    # (pid, trace id, name), so two replicas serving
                    # the same trace id (hedge winner + cancelled
                    # loser) must never share a pid.
                    "--trace_rank",
                    str(rep.index + 1),
                ]
                if self.trace_dir is not None
                else []
            ),
            "--host",
            "127.0.0.1",
            "--port",
            str(port),
        ]
        log = self._logs.get(rep.index)
        if log is None:
            log = open(
                os.path.join(self.workdir, f"replica{rep.index}.log"),
                "ab",
            )
            self._logs[rep.index] = log
        rep.proc = subprocess.Popen(
            argv, stdout=log, stderr=subprocess.STDOUT
        )
        rep.port = port
        rep.url = f"http://127.0.0.1:{port}"
        rep.state = STARTING
        rep.started_at = self._clock()
        rep.respawn_at = None
        rep.refused_probes = 0
        logger.info(
            "fleet: replica %d spawned (pid %d, %s)",
            rep.index, rep.proc.pid, rep.url,
        )

    def wait_healthy(self, timeout: float = 180.0) -> bool:
        """Block until every replica is HEALTHY (startup barrier for
        CLIs/tests); False on timeout."""
        deadline = self._clock() + timeout
        while self._clock() < deadline:
            if all(r.state == HEALTHY for r in self.replicas):
                return True
            time.sleep(0.05)
        return False

    # ---- chaos -------------------------------------------------------

    def kill_replica(self, index: int) -> None:
        """SIGKILL replica ``index`` — the mid-traffic death drill.
        The poll loop classifies and restarts it; the router replays
        its in-flight requests."""
        rep = self.replicas[index]
        with self._lock:
            if rep.proc is not None and rep.proc.poll() is None:
                self.chaos_kills += 1
                logger.warning(
                    "fleet chaos: SIGKILL replica %d (pid %d)",
                    index, rep.proc.pid,
                )
                rep.proc.kill()

    def stall_replica(self, index: int, seconds: float) -> None:
        """SIGSTOP replica ``index`` for ``seconds`` (then SIGCONT) —
        the straggler drill hedging should beat: the process is alive
        but answers nothing, so probes time out (breaker counts them)
        and in-flight requests straggle."""
        rep = self.replicas[index]
        with self._lock:
            if rep.proc is None or rep.proc.poll() is not None:
                return
            self.chaos_stalls += 1
            pid = rep.proc.pid
            logger.warning(
                "fleet chaos: SIGSTOP replica %d for %.1fs (pid %d)",
                index, seconds, pid,
            )
            os.kill(pid, signal.SIGSTOP)

            def _resume() -> None:
                try:
                    os.kill(pid, signal.SIGCONT)
                except OSError:
                    pass

            t = threading.Timer(seconds, _resume)
            t.daemon = True
            t.start()
            self._stall_timers.append(t)

    # ---- rolling restart ---------------------------------------------

    def rolling_restart(
        self,
        *,
        drain_timeout: float = 30.0,
        healthy_timeout: float = 180.0,
    ) -> dict:
        """Drain → wait → restart → re-admit, ONE replica at a time.

        Marking the replica DRAINING stops new router dispatch
        immediately; SIGTERM triggers the replica's own graceful
        drain (running lanes finish, then a clean exit); the respawn
        must reach HEALTHY before the next replica starts — so the
        fleet never has more than one replica out, and no request is
        dropped. Returns a per-replica report."""
        report = []
        for rep in self.replicas:
            entry = {"replica": rep.index, "ok": False}
            report.append(entry)
            with self._lock:
                if rep.proc is None or rep.proc.poll() is not None:
                    entry["skipped"] = "not running"
                    continue
                rep.state = DRAINING  # router stops new dispatch NOW
                rep.expected_exit = True
                rep.proc.send_signal(signal.SIGTERM)
            try:
                rep.proc.wait(drain_timeout + 10.0)
            except subprocess.TimeoutExpired:
                rep.proc.kill()
                rep.proc.wait(10)
                entry["forced"] = True
            with self._lock:
                self._spawn(rep)
                rep.expected_exit = False
            deadline = self._clock() + healthy_timeout
            while self._clock() < deadline:
                if rep.state == HEALTHY:
                    entry["ok"] = True
                    break
                time.sleep(0.05)
            if not entry["ok"]:
                logger.warning(
                    "fleet: rolling restart stopped — replica %d did "
                    "not come back healthy", rep.index,
                )
                return {"ok": False, "replicas": report}
        self.rolling_restarts_total += 1
        return {"ok": True, "replicas": report}

    # ---- verified hot-swap (serve/lifecycle.py, zero churn) ----------

    def pin_checkpoint(
        self, directory: str, epoch: Optional[int] = None
    ) -> None:
        """Re-point the spawn argv at a checkpoint: every FUTURE
        respawn (crash recovery, rolling restart) starts on it.
        ``reload_fleet`` calls this only after the FIRST replica
        commits a verified swap — until then the swap target is not
        trusted, and a replica dying mid-swap restarts on its
        PREVIOUS checkpoint."""
        with self._lock:
            args = list(self.serve_args)
            for flag in ("--checkpoint_dir", "--epoch"):
                while flag in args:
                    i = args.index(flag)
                    del args[i : i + 2]
            args += ["--checkpoint_dir", directory]
            if epoch is not None:
                args += ["--epoch", str(int(epoch))]
            self.serve_args = args

    def _wait_replica_healthy(
        self, rep: Replica, timeout: float
    ) -> bool:
        deadline = self._clock() + timeout
        while self._clock() < deadline:
            if (
                rep.state == HEALTHY
                and rep.proc is not None
                and rep.proc.poll() is None
            ):
                return True
            time.sleep(0.05)
        return False

    def reload_fleet(
        self,
        directory: str,
        *,
        epoch: Optional[int] = None,
        drain_timeout: float = 30.0,
        reload_timeout: float = 300.0,
        healthy_timeout: float = 180.0,
        chaos: Optional["FleetChaos"] = None,
    ) -> dict:
        """Fleet-wide verified hot-swap, ONE replica at a time — the
        zero-churn upgrade ``/rollz`` cannot be.

        Each replica gets ``POST /reload`` (verify → load → barrier →
        swap → rollback, serve/server.py): no DRAINING, no SIGTERM, no
        respawn — requests in flight complete on the old weights and
        the next dispatch sees the new ones. Per replica the budget is
        two tries: a replica that dies mid-swap (the SIGKILL drill) is
        respawned by the poll loop on its PINNED checkpoint — the OLD
        one until the first successful commit trusts the target
        (``pin_checkpoint``) — and the reload is re-issued once it is
        healthy again. A NAMED verification rejection (manifest
        missing, CRC mismatch, spec skew) or a swap failure aborts the
        roll immediately: the fleet keeps serving the old version,
        converged. Returns {"ok", "version", "respawns", "replicas"}.
        """
        report: list[dict] = []
        respawns_before = self.restarts_total
        target_version: Optional[str] = None
        pinned = False
        for rep in self.replicas:
            entry: dict = {"replica": rep.index, "ok": False}
            report.append(entry)
            for attempt in (1, 2):
                entry["attempts"] = attempt
                if not self._wait_replica_healthy(rep, healthy_timeout):
                    entry["error"] = "never_healthy"
                    return {
                        "ok": False,
                        "version": target_version,
                        "respawns": self.restarts_total
                        - respawns_before,
                        "replicas": report,
                    }
                if (
                    target_version is not None
                    and rep.model_version == target_version
                ):
                    # Died AFTER committing (or after the pin): its
                    # respawn already serves the target — /healthz
                    # says so, nothing to re-issue.
                    entry["ok"] = True
                    entry["model_version"] = target_version
                    break
                if chaos is not None:
                    # Arms the kill:replica<R>@reload drill — a racing
                    # SIGKILL that lands while this reload is mid-
                    # flight.
                    chaos.on_replica_reload(rep.index)
                body = {
                    "checkpoint_dir": directory,
                    "drain_timeout": drain_timeout,
                }
                if epoch is not None:
                    body["epoch"] = int(epoch)
                try:
                    status, payload = self.transport.start(
                        rep.url, "/reload", body, reload_timeout
                    ).run()
                except ReplicaUnreachable:
                    # The process died mid-swap. Its device state died
                    # with it — nothing torn survives; the poll loop
                    # respawns it on the pinned checkpoint and the
                    # next attempt re-checks /healthz.
                    entry["died_mid_swap"] = True
                    logger.warning(
                        "fleet reload: replica %d died mid-swap "
                        "(attempt %d)", rep.index, attempt,
                    )
                    continue
                if status == 200:
                    entry["ok"] = True
                    entry["model_version"] = payload.get("model_version")
                    entry["swap_s"] = payload.get("swap_s")
                    target_version = payload.get("model_version")
                    if not pinned:
                        self.pin_checkpoint(
                            directory, payload.get("epoch")
                        )
                        pinned = True
                    break
                # Named rejection (409) or load/swap failure (500/503):
                # the replica rolled back or never started — abort the
                # roll, the whole fleet stays on the old version.
                entry["error"] = payload.get("error")
                if payload.get("detail"):
                    entry["detail"] = payload["detail"]
                logger.warning(
                    "fleet reload: replica %d rejected the target "
                    "(%s) — aborting the roll", rep.index,
                    entry["error"],
                )
                return {
                    "ok": False,
                    "aborted": entry["error"],
                    "version": target_version,
                    "respawns": self.restarts_total - respawns_before,
                    "replicas": report,
                }
            if not entry["ok"]:
                return {
                    "ok": False,
                    "version": target_version,
                    "respawns": self.restarts_total - respawns_before,
                    "replicas": report,
                }
        self.fleet_reloads_total += 1
        return {
            "ok": True,
            "version": target_version,
            "respawns": self.restarts_total - respawns_before,
            "replicas": report,
        }

    # ---- supervision -------------------------------------------------

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            for rep in self.replicas:
                try:
                    self._poll_replica(rep)
                except Exception:  # noqa: BLE001 — supervision survives
                    logger.exception(
                        "fleet: poll failed for replica %d", rep.index
                    )
            self._write_poll_record()
            self._stop.wait(self.poll_interval)

    def _poll_replica(self, rep: Replica) -> None:
        with self._lock:
            proc = rep.proc
            if proc is None or rep.state == STOPPED or rep.expected_exit:
                return
            code = proc.poll()
            if code is not None:
                self._handle_exit(rep, code)
                return
        # Probe outside the lock: a slow/black-holed replica must not
        # stall supervision of its siblings.
        try:
            health = self.transport.get_json(
                rep.url, "/healthz", self.probe_timeout
            )
        except ReplicaUnreachable as e:
            self._handle_probe_failure(rep, e)
            return
        ok = bool(health.get("ok"))
        with self._lock:
            rep.refused_probes = 0
            rep.slots = health.get("slots", rep.slots)
            rep.active = int(health.get("active") or 0)
            rep.queue_depth = int(health.get("queue_depth") or 0)
            # Lifecycle advertisement: which model version (and which
            # named models) this replica serves RIGHT NOW — what keeps
            # the router's model gate and the reload loop's
            # convergence check current across swaps and respawns.
            mv = health.get("model_version")
            rep.model_version = mv if isinstance(mv, str) else None
            models = health.get("models")
            rep.models = (
                tuple(sorted(models)) if isinstance(models, dict) else ()
            )
            if not ok:
                # Answers HTTP but reports sick (engine loop died):
                # breaker-open it like a timeout series would.
                rep.breaker.record_failure()
                return
            if health.get("draining"):
                rep.state = DRAINING
                return
            if rep.state in (STARTING, DRAINING):
                rep.state = HEALTHY
            # A successful probe is a success like any other: it
            # resets the consecutive-failure count (the documented
            # contract — sporadic probe timeouts hours apart must not
            # accumulate into a spurious open on an idle replica) and
            # closes a tripped breaker once its cooldown has moved it
            # to HALF_OPEN. During a real overload the probes time
            # out too (they share the replica's HTTP server), so this
            # never masks a sick replica. An OPEN breaker inside its
            # cooldown stays open — probe_due() is what spaces the
            # recovery probes.
            if rep.breaker.state == CircuitBreaker.CLOSED:
                rep.breaker.record_success()
            elif rep.breaker.probe_due():
                rep.breaker.record_success()

    def _handle_probe_failure(
        self, rep: Replica, err: ReplicaUnreachable
    ) -> None:
        with self._lock:
            if rep.state == STARTING:
                # Not bound yet: normal during startup (warmup
                # compiles take a while); only a stuck-forever start
                # is a failure.
                if (
                    rep.started_at is not None
                    and self._clock() - rep.started_at
                    > self.startup_grace_s
                ):
                    logger.warning(
                        "fleet: replica %d never became healthy "
                        "(%.0fs) — restarting", rep.index,
                        self.startup_grace_s,
                    )
                    if rep.proc is not None:
                        rep.proc.kill()
                return
            if err.kind == "refused":
                rep.breaker.trip()
                rep.refused_probes += 1
                # Defense in depth behind the proc-liveness check:
                # repeated REFUSED probes mean nothing is listening —
                # if waitpid somehow never reports the death (or the
                # process is alive but unbound), force the restart
                # path rather than trusting poll() forever.
                if rep.refused_probes >= 3 and rep.proc is not None:
                    logger.warning(
                        "fleet: replica %d refused %d probes — "
                        "forcing restart", rep.index, rep.refused_probes,
                    )
                    rep.refused_probes = 0
                    try:
                        rep.proc.kill()
                    except OSError:
                        pass
                    self._handle_exit(
                        rep, rep.proc.poll() if rep.proc else 1
                    )
            else:
                rep.breaker.record_failure()

    def _handle_exit(self, rep: Replica, code: int) -> None:
        """Process death under the lock: classify, budget, schedule
        the respawn (backoff rides ``respawn_at`` so one dead
        replica's backoff never blocks polling the others)."""
        now = self._clock()
        if rep.state != DEAD:
            rep.last_exit = classify_exit(code)
            rep.state = DEAD
            backoff = min(
                30.0, self.restart_backoff * (2.0 ** rep.restarts)
            )
            if rep.restarts >= self.max_restarts:
                rep.respawn_at = None
                logger.error(
                    "fleet: replica %d dead (%s) — %d/%d restarts "
                    "exhausted", rep.index, rep.last_exit,
                    rep.restarts, self.max_restarts,
                )
            else:
                rep.respawn_at = now + backoff
                logger.warning(
                    "fleet: replica %d died (%s) — restart %d/%d in "
                    "%.1fs", rep.index, rep.last_exit,
                    rep.restarts + 1, self.max_restarts, backoff,
                )
            return
        if rep.respawn_at is not None and now >= rep.respawn_at:
            rep.restarts += 1
            self.restarts_total += 1
            self._spawn(rep)

    # ---- telemetry ---------------------------------------------------

    def state(self) -> dict:
        return {
            "restarts_total": self.restarts_total,
            "rolling_restarts_total": self.rolling_restarts_total,
            "chaos_kills": self.chaos_kills,
            "chaos_stalls": self.chaos_stalls,
            "max_restarts": self.max_restarts,
            # Gated on use: pre-lifecycle fleets' state (and every
            # record/gauge built from it) stays byte-identical.
            **(
                {"fleet_reloads_total": self.fleet_reloads_total}
                if self.fleet_reloads_total
                else {}
            ),
        }

    def _write_poll_record(self) -> None:
        if self.metrics is None:
            return
        snap = {}
        by_state: dict[str, int] = {}
        for r in self.replicas:
            by_state[r.state] = by_state.get(r.state, 0) + 1
        snap.update(
            replicas=len(self.replicas),
            replicas_healthy=by_state.get(HEALTHY, 0),
            replicas_draining=by_state.get(DRAINING, 0),
            replicas_dead=by_state.get(DEAD, 0),
            breaker_open=sum(
                1
                for r in self.replicas
                if r.breaker.state != CircuitBreaker.CLOSED
            ),
            breaker_opens_total=sum(
                r.breaker.opens_total for r in self.replicas
            ),
            **self.state(),
        )
        if self.router is not None:
            rs = self.router.state()
            for k in (
                "dispatched_total", "retries_total", "replays_total",
                "hedges_total", "hedge_wins_total",
            ):
                snap[k] = rs[k]
            # Disaggregation counters ride only when the router runs
            # role-aware/directory dispatch (they are absent from
            # state() otherwise) — existing fleet_poll consumers (and
            # the health_report goldens) see byte-identical records.
            for k in (
                "prefill_handoffs_total", "migrations_total",
                "migration_failures_total", "pages_migrated_total",
                "directory_pulls_total", "directory_pull_hits_total",
                "directory_size", "migration_seconds",
            ):
                if k in rs:
                    snap[k] = rs[k]
        self.metrics.write("fleet_poll", **snap)

    # Set by attach_router (the poll record wants router counters too).
    router: Optional[Router] = None

    def attach_router(self, router: Router) -> Router:
        """Bind a router over this manager's replicas (they share the
        Replica objects, so poll-loop state flows straight into
        selection)."""
        self.router = router
        return router


# ---------------------------------------------------------------------
# Fleet chaos (grammar in runtime/chaos.py; firing lives here)
# ---------------------------------------------------------------------


class FleetChaos:
    """Fires ``kill:replica<R>@request<N>`` / ``stall:...`` events on
    the router's dispatch counter, and the lifecycle drills
    (``kill:replica<R>@reload`` / ``ckpt_corrupt:reload``) from the
    fleet reload loop's hooks. In-memory once-latch (a fleet frontend
    doesn't restart mid-drill the way a trainer does, so no ledger
    file); wire via ``Router(on_dispatch=chaos.on_dispatch)`` and
    ``FleetServer(chaos=...)``.
    """

    def __init__(
        self,
        events: Sequence[ChaosEvent] | str | None,
        manager: ReplicaManager,
    ):
        self.events = fleet_events(events)
        self.reloads = reload_events(events)
        self.manager = manager
        self._fired: set[str] = set()
        for ev in self.events:
            if ev.replica >= len(manager.replicas):
                raise ValueError(
                    f"chaos names replica {ev.replica} but the fleet "
                    f"has {len(manager.replicas)}"
                )

    @property
    def enabled(self) -> bool:
        return bool(self.events) or bool(self.reloads)

    def on_dispatch(self, ordinal: int) -> None:
        for ev in self.events:
            if ev.request == ordinal and ev.token not in self._fired:
                self._fired.add(ev.token)
                if ev.kind == "kill":
                    self.manager.kill_replica(ev.replica)
                else:
                    self.manager.stall_replica(ev.replica, ev.seconds)

    def on_reload_start(self, directory: str) -> None:
        """``ckpt_corrupt:reload``: corrupt the INCOMING checkpoint
        before the roll's first verify runs — the drill that proves a
        reload rejects-and-keeps-serving (named CRC reason, old model
        untouched) instead of installing garbage."""
        from ddp_tpu.runtime.chaos import corrupt_latest_checkpoint

        for ev in self.reloads:
            if (
                ev.kind == "ckpt_corrupt"
                and ev.token not in self._fired
            ):
                self._fired.add(ev.token)
                logger.warning(
                    "fleet chaos: corrupting reload target %s",
                    directory,
                )
                corrupt_latest_checkpoint(directory)

    def on_replica_reload(self, index: int) -> None:
        """``kill:replica<R>@reload``: SIGKILL replica R from a racing
        thread shortly after its reload is issued, so the kill lands
        mid-swap (during the verify/load/drain window) — the drill
        behind the "a torn model never survives a death" guarantee."""
        for ev in self.reloads:
            if (
                ev.kind == "kill"
                and ev.replica == index
                and ev.token not in self._fired
            ):
                self._fired.add(ev.token)

                def _kill() -> None:
                    time.sleep(0.05)
                    self.manager.kill_replica(index)

                threading.Thread(
                    target=_kill, name="chaos-reload-kill", daemon=True
                ).start()


# ---------------------------------------------------------------------
# HTTP front door
# ---------------------------------------------------------------------


class FleetServer:
    """The fleet's stdlib-HTTP frontend (scripts/fleet.py):

      POST /generate   → Router.dispatch (one response per request,
                         ``router`` digest included)
      GET  /healthz    → fleet liveness (ok while >= 1 replica is
                         dispatchable) + per-replica states
      GET  /statusz    → router/manager state + the obs/aggregate.py
                         fleet view scraped LIVE from member replicas
                         (merged latency summaries, per-endpoint
                         health with the timeout/refused distinction)
      GET  /metricsz   → linted ``ddp_tpu_fleet_*`` gauges
                         (obs/promtext.render_fleet)
      GET  /requestz?id=0xTRACEID
                       → one recent request's fleet hop chain
                         (router digest + hop spans, joined with the
                         serving replica's proxied engine timeline)
      POST /rollz      → rolling restart (drain → wait → restart →
                         re-admit, one replica at a time), in the
                         background; the response acknowledges start
      POST /reloadz    → fleet-wide verified hot-swap
                         {"checkpoint_dir": D, "epoch"?}: one replica
                         at a time through each member's POST /reload
                         — zero process churn, zero dropped requests;
                         backgrounded like /rollz, progress under
                         /statusz ``reload``

    Draining the FLEET (SIGTERM path): stop admitting here (503 +
    Retry-After, the single-replica contract), then drain members.
    """

    def __init__(
        self,
        manager: ReplicaManager,
        router: Router,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        drain_retry_after: float = 5.0,
        chaos: Optional[FleetChaos] = None,
    ):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.manager = manager
        self.router = router
        self.drain_retry_after = float(drain_retry_after)
        # Reload-scoped chaos (kill:replica<R>@reload /
        # ckpt_corrupt:reload) fires from the /reloadz path; None —
        # every non-drill fleet — changes nothing.
        self.chaos = chaos
        self._draining = threading.Event()
        self._roll_thread: Optional[threading.Thread] = None
        self._roll_state: dict = {"running": False}
        self._reload_thread: Optional[threading.Thread] = None
        self._reload_state: dict = {"running": False}
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: D102
                pass

            def _send_text(
                self, status, text, ctype, headers=None
            ) -> None:
                data = text.encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def _send(self, status, payload, headers=None) -> None:
                self._send_text(
                    status, json.dumps(payload), "application/json",
                    headers,
                )

            def do_GET(self):  # noqa: N802
                route, _, query = self.path.partition("?")
                if route == "/healthz":
                    payload = server.healthz()
                    self._send(
                        200 if payload["ok"] else 503, payload
                    )
                elif route == "/requestz":
                    self._send(*server.requestz(query))
                elif route == "/statusz":
                    self._send(200, server.statusz())
                elif route == "/metricsz":
                    from ddp_tpu.obs.promtext import CONTENT_TYPE

                    self._send_text(
                        200, server.metricsz(), CONTENT_TYPE
                    )
                else:
                    self._send(
                        404, {"error": f"no route {self.path}"}
                    )

            def do_POST(self):  # noqa: N802
                route = self.path.partition("?")[0]
                if route == "/rollz":
                    self._send(*server.start_roll())
                    return
                if route == "/reloadz":
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(n) or b"{}")
                        if not isinstance(body, dict):
                            raise ValueError(
                                "body must be a JSON object"
                            )
                    except (ValueError, TypeError) as e:
                        self._send(
                            400, {"error": f"bad JSON body: {e}"}
                        )
                        return
                    self._send(*server.start_reload(body))
                    return
                if route != "/generate":
                    self._send(
                        404, {"error": f"no route {self.path}"}
                    )
                    return
                if server.draining:
                    self._send(
                        503,
                        {
                            "error": "draining",
                            "retry_after_s": server.drain_retry_after,
                        },
                        {
                            "Retry-After": str(
                                int(server.drain_retry_after)
                            )
                        },
                    )
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(body, dict):
                        raise ValueError("body must be a JSON object")
                except (ValueError, TypeError) as e:
                    self._send(400, {"error": f"bad JSON body: {e}"})
                    return
                status, payload = server.router.dispatch(body)
                headers = None
                if payload.get("retry_after_s"):
                    headers = {
                        "Retry-After": str(
                            max(
                                1,
                                int(payload["retry_after_s"] + 0.999),
                            )
                        )
                    }
                self._send(status, payload, headers)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def begin_drain(self) -> None:
        self._draining.set()

    def start(self) -> "FleetServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "FleetServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- route bodies ------------------------------------------------

    def healthz(self) -> dict:
        rs = self.router.state()
        return {
            "ok": rs["replicas_healthy"] > 0,
            "draining": self.draining,
            "replicas": rs["replicas"],
            "replicas_healthy": rs["replicas_healthy"],
            "replicas_draining": rs["replicas_draining"],
            "replicas_dead": rs["replicas_dead"],
            # version → replica count, present only once members
            # advertise versions: one key while converged, two
            # mid-roll — the drill's convergence assertion reads this.
            **(
                {"model_versions": rs["model_versions"]}
                if "model_versions" in rs
                else {}
            ),
        }

    def requestz(self, query: str) -> tuple[int, dict]:
        """GET /requestz?id=0x...: ONE recent request's assembled
        fleet hop chain — the router's digest + hop spans from its
        /requestz ring, joined with the serving replica's own
        /requestz engine timeline (proxied live; ``null`` when that
        replica evicted the entry or died — the router half still
        answers)."""
        from urllib.parse import parse_qs

        tid = (parse_qs(query).get("id") or [""])[0]
        entry = self.router.requestz(tid)
        if entry is None:
            return 404, {"error": f"no recent request {tid!r}"}
        replica = None
        idx = entry["router"]["digest"].get("replica")
        if idx is not None and 0 <= int(idx) < len(self.router.replicas):
            rep = self.router.replicas[int(idx)]
            if rep.url is not None:
                try:
                    replica = self.manager.transport.get_json(
                        rep.url,
                        f"/requestz?id={tid}",
                        self.manager.probe_timeout,
                    )
                except ReplicaUnreachable:
                    replica = None
        return 200, {**entry, "replica": replica}

    def statusz(self) -> dict:
        """Router + manager state, plus the obs/aggregate.py fleet
        view scraped LIVE from the member replicas — the aggregator
        PR 11 built as "the router's input" is also the fleet's own
        status surface (one fleet view, not two)."""
        from ddp_tpu.obs.aggregate import merge_fleet, scrape_endpoint
        from ddp_tpu.obs.recorder import build_info

        # Scrape members in PARALLEL: the views are independent, and
        # a serial loop would add probe_timeout of blocking per sick
        # replica to every /statusz — exactly during the incidents
        # this endpoint exists to diagnose.
        urls = [
            r.url for r in self.router.replicas if r.url is not None
        ]
        views: list[Optional[dict]] = [None] * len(urls)

        def _scrape(i: int) -> None:
            views[i] = scrape_endpoint(
                urls[i], timeout=self.manager.probe_timeout
            )

        scrapers = [
            threading.Thread(target=_scrape, args=(i,), daemon=True)
            for i in range(len(urls))
        ]
        for t in scrapers:
            t.start()
        for t in scrapers:
            t.join()
        return {
            "ok": self.healthz()["ok"],
            "draining": self.draining,
            "router": self.router.state(),
            "manager": self.manager.state(),
            "roll": dict(self._roll_state),
            # Fleet-reload progress, present only once a /reloadz ran
            # (pre-lifecycle /statusz stays byte-identical).
            **(
                {"reload": dict(self._reload_state)}
                if self._reload_state.get("running")
                or len(self._reload_state) > 1
                else {}
            ),
            "fleet": merge_fleet([v for v in views if v is not None]),
            "build_info": build_info(),
        }

    def metricsz(self) -> str:
        from ddp_tpu.obs.promtext import render_fleet
        from ddp_tpu.obs.recorder import build_info

        rs = self.router.state()
        snap = {**rs, **self.manager.state(), "build_info": build_info()}
        return render_fleet(
            snap,
            up=rs["replicas_healthy"] > 0,
            draining=self.draining,
        )

    def start_roll(self) -> tuple[int, dict]:
        """POST /rollz: kick a rolling restart in the background —
        the request acknowledges the start; /statusz tracks progress
        (a roll takes replica-startup minutes; no HTTP client should
        hold a socket that long)."""
        if self._roll_thread is not None and self._roll_thread.is_alive():
            return 409, {"error": "rolling restart already running"}

        def _roll() -> None:
            self._roll_state = {"running": True}
            result = self.manager.rolling_restart()
            self._roll_state = {"running": False, **result}

        self._roll_thread = threading.Thread(
            target=_roll, name="fleet-roll", daemon=True
        )
        self._roll_thread.start()
        return 202, {"rolling": True}

    def start_reload(self, body: dict) -> tuple[int, dict]:
        """POST /reloadz: kick the fleet-wide verified hot-swap in the
        background (one member /reload at a time — a fleet's worth of
        drains and restores outlives any sane HTTP socket); /statusz
        ``reload`` tracks progress and the final report."""
        directory = body.get("checkpoint_dir")
        if not isinstance(directory, str) or not directory:
            return 400, {"error": "body needs checkpoint_dir (str)"}
        try:
            epoch = (
                int(body["epoch"]) if body.get("epoch") is not None
                else None
            )
        except (TypeError, ValueError):
            return 400, {"error": "epoch must be an int"}
        if (
            self._reload_thread is not None
            and self._reload_thread.is_alive()
        ):
            return 409, {"error": "fleet reload already running"}
        if self._roll_thread is not None and self._roll_thread.is_alive():
            return 409, {"error": "rolling restart running"}
        if self.chaos is not None:
            # The corrupt-target drill fires BEFORE the first verify —
            # the roll must reject it and leave every replica serving.
            self.chaos.on_reload_start(directory)

        def _reload() -> None:
            self._reload_state = {"running": True}
            result = self.manager.reload_fleet(
                directory, epoch=epoch, chaos=self.chaos
            )
            self._reload_state = {"running": False, **result}

        self._reload_thread = threading.Thread(
            target=_reload, name="fleet-reload", daemon=True
        )
        self._reload_thread.start()
        return 202, {"reloading": True}

"""Request queue with admission control — the serving front door.

The engine (serve/engine.py) owns a FIXED number of decode slots; this
module owns everything that happens before a request reaches one:

- **Admission control**: a request is validated at submit time against
  the engine's static limits (prompt fits the prefill width, prompt +
  budget fits the position table, budget positive) and the queue
  bound. Rejection is an explicit ``Admission`` with a machine-readable
  reason — the backpressure contract is *reject-with-reason at the
  door*, never queue-without-bound and OOM later.
- **FIFO with deadline eviction**: queued requests past their deadline
  are evicted (status ``timeout_queue``) rather than prefilled after
  they stopped mattering; the engine applies the same deadline to
  RUNNING requests (status ``timeout_evicted``), freeing the slot for
  the queue head.
- **Chunked-prefill planning**: the engine ingests prompts in
  power-of-two-bucketed chunks co-scheduled with decode steps
  (Sarathi-style stall-free prefill); ``plan_chunks`` decides which
  mid-prefill slots advance this step, accounting each chunk's width
  plus one token per decoding lane against a per-step token budget so
  one long prompt can never head-of-line-block the running lanes.

Pure host-side Python — no JAX here. ``clock`` is injectable so tests
drive time explicitly.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence


# Machine-readable rejection reasons (the HTTP layer maps these to 4xx
# bodies; tests assert on them).
QUEUE_FULL = "queue_full"
PROMPT_EMPTY = "prompt_empty"
PROMPT_TOO_LONG = "prompt_too_long"
BUDGET_NONPOSITIVE = "max_new_tokens_nonpositive"
BUDGET_EXCEEDS_CONTEXT = "budget_exceeds_context"
TOKEN_OUT_OF_RANGE = "token_out_of_range"
TOP_P_OUT_OF_RANGE = "top_p_out_of_range"
TOP_P_WITHOUT_SAMPLING = "top_p_without_sampling"
SEED_OUT_OF_RANGE = "seed_out_of_range"


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (1 for n <= 1)."""
    p = 1
    while p < n:
        p *= 2
    return p


def prev_pow2(n: int) -> int:
    """Largest power of two <= n (requires n >= 1)."""
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def classify_prompt(
    prompt_len: int, page_size: int, *, cutoff_tokens: int
) -> str:
    """Disaggregated-dispatch classification (PR 16) → "prefill" |
    "decode".

    Long prompts are the requests whose prefill steals step budget
    from every co-located decode lane, so they route through the
    prefill tier; short prompts prefill in one or two chunks and go
    straight to a decode replica. The cutoff is compared against the
    prompt's PAGE-ALIGNED length: only full pages ever migrate
    (serve/pages.release publishes full pages only), so a prompt
    whose page-aligned length is below the cutoff would ship fewer
    pages than the threshold promises. ``cutoff_tokens <= 0`` sends
    everything to the decode tier (disaggregation by role only, no
    length split). Pure — the router calls it, tests pin it.
    """
    if cutoff_tokens <= 0:
        return "decode"
    aligned = (
        (prompt_len // page_size) * page_size
        if page_size > 0
        else prompt_len
    )
    return "prefill" if aligned >= cutoff_tokens else "decode"


@dataclass
class Request:
    """One admitted generate request."""

    rid: int
    prompt: list[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_p: float = 1.0
    seed: int = 0
    deadline: Optional[float] = None  # absolute, in clock() time
    submitted: float = 0.0
    # 64-bit distributed-tracing id, assigned AT ADMISSION
    # (obs/reqtrace.derive_trace_id): the one key that follows the
    # request through the HTTP response, the metrics stream, the
    # Perfetto trace and /requestz. 0 = unassigned (bare schedulers
    # constructed without a trace seed in tests).
    trace_id: int = 0
    # Multi-model routing label (serve/lifecycle.py): which registered
    # model this request named (``model=`` in the body). None — every
    # pre-lifecycle client — means the default model; the server
    # routes on it, and per-model engines each run their own scheduler
    # so slot/page accounting stays per-model by construction.
    model: Optional[str] = None

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


@dataclass
class Admission:
    """Submit outcome: ``request`` on accept, ``reason`` on reject."""

    accepted: bool
    reason: Optional[str] = None
    request: Optional[Request] = None


@dataclass
class Scheduler:
    """Bounded FIFO queue + admission control for the serve engine.

    ``prefill_len``/``total_len`` mirror the engine's static shapes:
    a prompt longer than the prefill width can never be prefilled
    (one compiled prefill shape is the whole point), and prompt +
    max_new_tokens beyond the position table would decode garbage —
    both are admission errors, not runtime surprises.
    """

    max_queue: int
    prefill_len: int
    total_len: int
    vocab_size: int = 0  # 0 = skip the token-range check
    # Chunked-prefill policy (the engine sets these from its bucket
    # config; the defaults keep a bare Scheduler usable in tests).
    chunk: int = 0  # 0 = one prefill_len-wide chunk per prompt
    min_bucket: int = 0  # 0 = no bucketing below the chunk width
    token_budget: int = 0  # 0 = unlimited (no co-scheduling bound)
    # Seed for the per-request 64-bit trace ids (obs/reqtrace.py):
    # deterministic in (seed, rid) so tests can pin ids; a serving
    # process seeds from os.urandom so two replicas' id spaces don't
    # collide in a merged fleet trace.
    trace_seed: int = 0
    clock: Callable[[], float] = time.monotonic
    _queue: deque = field(default_factory=deque)
    _ids: "itertools.count" = field(default_factory=itertools.count)

    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        top_p: float = 1.0,
        seed: int = 0,
        timeout: Optional[float] = None,
        trace_id: Optional[int] = None,
        model: Optional[str] = None,
    ) -> Admission:
        """Validate + enqueue → Admission (never raises on bad input).

        ``trace_id`` overrides the locally-derived id with one ADOPTED
        from an inbound fleet trace context (the router's), so the
        replica's whole timeline hangs off the router's span instead
        of a freshly-minted id; None/0 keeps the local derivation.
        """
        try:
            prompt = [int(t) for t in prompt]
        except (TypeError, ValueError):
            # Non-numeric tokens: same front-door contract as a
            # numeric token outside the vocab — reject, don't raise.
            return Admission(False, TOKEN_OUT_OF_RANGE)
        if not prompt:
            return Admission(False, PROMPT_EMPTY)
        if len(prompt) > self.prefill_len:
            return Admission(False, PROMPT_TOO_LONG)
        if max_new_tokens < 1:
            return Admission(False, BUDGET_NONPOSITIVE)
        if len(prompt) + max_new_tokens > self.total_len:
            return Admission(False, BUDGET_EXCEEDS_CONTEXT)
        if self.vocab_size and not all(
            0 <= t < self.vocab_size for t in prompt
        ):
            return Admission(False, TOKEN_OUT_OF_RANGE)
        if not 0.0 < float(top_p) <= 1.0:
            return Admission(False, TOP_P_OUT_OF_RANGE)
        if float(temperature) <= 0.0 and float(top_p) < 1.0:
            # generate() refuses this combination for the same reason:
            # greedy decoding ignores the nucleus filter, and refusing
            # beats silently recording a setting that had no effect.
            return Admission(False, TOP_P_WITHOUT_SAMPLING)
        if not -(2**31) <= int(seed) < 2**31:
            # The engine threads seeds through int32 device state, and
            # generate()'s own jnp.asarray(seed) overflows past int32 —
            # out-of-range seeds can never sample the documented
            # stream, so they are a front-door error.
            return Admission(False, SEED_OUT_OF_RANGE)
        if len(self._queue) >= self.max_queue:
            return Admission(False, QUEUE_FULL)
        now = self.clock()
        from ddp_tpu.obs.reqtrace import derive_trace_id

        rid = next(self._ids)
        req = Request(
            rid=rid,
            prompt=prompt,
            max_new_tokens=int(max_new_tokens),
            temperature=float(temperature),
            top_p=float(top_p),
            seed=int(seed),
            deadline=None if timeout is None else now + float(timeout),
            submitted=now,
            trace_id=(
                int(trace_id)
                if trace_id
                else derive_trace_id(self.trace_seed, rid)
            ),
            model=model,
        )
        self._queue.append(req)
        return Admission(True, request=req)

    # ---- chunked-prefill planning -----------------------------------

    def bucket_list(self) -> list[int]:
        """The compiled chunk-width set, ascending: {min_bucket · 2^i}
        up to and including ``chunk``. Bounded, warmup-enumerable."""
        chunk = self.chunk or next_pow2(self.prefill_len)
        widths = []
        w = min(self.min_bucket or chunk, chunk)
        while w < chunk:
            widths.append(w)
            w *= 2
        widths.append(chunk)
        return widths

    def chunk_width(
        self,
        start: int,
        remaining: int,
        budget: Optional[int] = None,
    ) -> Optional[int]:
        """Compiled width for the next chunk at position ``start`` with
        ``remaining`` prompt tokens left; None if nothing fits
        ``budget``.

        Preference: the smallest bucket covering ``remaining`` (a
        short prompt/tail pays bucket-sized compute, not
        ``prefill_len``-sized). Two fit constraints shrink it:

        - ``start + width <= total_len`` ALWAYS — a wider chunk's pad
          positions would overrun the cache, and XLA's clamped
          dynamic_update_slice would silently shift the whole write
          over live lines (the engine's min_bucket clamp guarantees at
          least one bucket fits any admissible start);
        - ``width <= budget`` when given — rather than stalling a
          prompt whose covering bucket exceeds the step's leftover
          budget, ingest the largest budget-fitting bucket now and the
          rest on later steps (the chunk is simply non-final).
        """
        cap = self.total_len - start
        if budget is not None:
            cap = min(cap, budget)
        fitting = [w for w in self.bucket_list() if w <= cap]
        if not fitting:
            return None
        for w in fitting:
            if w >= remaining:
                return w
        return fitting[-1]

    def plan_chunks(
        self,
        prefilling: Sequence[tuple[int, int, int]],
        decoding: int,
    ) -> list[tuple[int, int]]:
        """Which mid-prefill slots advance this step → [(slot, width)].

        ``prefilling``: (slot, start, remaining-prompt-tokens) in
        refill order; ``decoding``: decode TOKENS dispatched this step
        — one per running lane on the plain path, lanes × γ under
        speculative verify (the engine multiplies; the verify program
        really does run γ positions per lane). Sarathi-style
        accounting: every planned chunk's width plus the decode tokens
        must fit ``token_budget``, so a long prompt is ingested across steps
        while running lanes keep decoding — never a full-prompt
        stall. Order is preserved (no short prompt overtakes within a
        step); a tight budget shrinks the head's chunk rather than
        starving it. Liveness: when nothing is decoding and the
        budget would starve even the first chunk, one unbudgeted
        chunk is planned anyway — an idle engine must make prefill
        progress.

        Paged engines (PR 12) need no per-step PAGE accounting here:
        a lane's whole page demand — every chunk's live tokens, the
        decode budget, and the speculative γ-1 write reserve — is
        acquired at BIND (serve/pages.page_demand), so any chunk this
        planner schedules writes into pages the lane already owns
        (pad overhang past the demand falls into the scratch page).
        The token budget stays the compute-side constraint; pages
        are the residency-side one.
        """
        budget = (
            self.token_budget - decoding
            if self.token_budget > 0
            else None
        )
        plan: list[tuple[int, int]] = []
        for slot, start, remaining in prefilling:
            width = self.chunk_width(start, remaining, budget)
            if width is None:
                break  # FIFO: later slots wait with the blocked head
            plan.append((slot, width))
            if budget is not None:
                budget -= width
        if not plan and prefilling and decoding == 0:
            slot, start, remaining = prefilling[0]
            width = self.chunk_width(start, remaining)
            if width is not None:  # None: no bucket fits this config
                plan.append((slot, width))
        return plan

    def evict_expired(self) -> list[Request]:
        """Drop queued requests past their deadline → the evicted."""
        now = self.clock()
        expired = [r for r in self._queue if r.expired(now)]
        if expired:
            dead = {r.rid for r in expired}
            self._queue = deque(
                r for r in self._queue if r.rid not in dead
            )
        return expired

    def next_request(self) -> Optional[Request]:
        """Pop the FIFO head, None when empty.

        Callers run ``evict_expired()`` first (the engine does, every
        step) — this only pops; an expired head that slipped between
        the two calls is still caught by the engine's running-request
        deadline check on its first decode step.
        """
        return self._queue.popleft() if self._queue else None

    def push_front(self, req: Request) -> None:
        """Return a popped request to the queue HEAD, order intact.

        The paged engine's admission backpressure (PR 12): with a
        paged KV cache the binding resource is FREE PAGES, not lanes ×
        ctx_len — a popped head whose page demand (serve/pages.
        page_demand, γ-reserve included) cannot be satisfied even
        after LRU eviction goes back to the front and admission stops
        for the step, so a big request is delayed, never starved by
        smaller ones overtaking it. Deliberately exempt from the
        ``max_queue`` bound: the request was already admitted once.
        """
        self._queue.appendleft(req)

    @property
    def depth(self) -> int:
        return len(self._queue)

"""Request queue with admission control — the serving front door.

The engine (serve/engine.py) owns a FIXED number of decode slots; this
module owns everything that happens before a request reaches one:

- **Admission control**: a request is validated at submit time against
  the engine's static limits (prompt fits the prefill width, prompt +
  budget fits the position table, budget positive) and the queue
  bound. Rejection is an explicit ``Admission`` with a machine-readable
  reason — the backpressure contract is *reject-with-reason at the
  door*, never queue-without-bound and OOM later.
- **FIFO with deadline eviction**: queued requests past their deadline
  are evicted (status ``timeout_queue``) rather than prefilled after
  they stopped mattering; the engine applies the same deadline to
  RUNNING requests (status ``timeout_evicted``), freeing the slot for
  the queue head.

Pure host-side Python — no JAX here. ``clock`` is injectable so tests
drive time explicitly.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence


# Machine-readable rejection reasons (the HTTP layer maps these to 4xx
# bodies; tests assert on them).
QUEUE_FULL = "queue_full"
PROMPT_EMPTY = "prompt_empty"
PROMPT_TOO_LONG = "prompt_too_long"
BUDGET_NONPOSITIVE = "max_new_tokens_nonpositive"
BUDGET_EXCEEDS_CONTEXT = "budget_exceeds_context"
TOKEN_OUT_OF_RANGE = "token_out_of_range"


@dataclass
class Request:
    """One admitted generate request."""

    rid: int
    prompt: list[int]
    max_new_tokens: int
    temperature: float = 0.0
    seed: int = 0
    deadline: Optional[float] = None  # absolute, in clock() time
    submitted: float = 0.0

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


@dataclass
class Admission:
    """Submit outcome: ``request`` on accept, ``reason`` on reject."""

    accepted: bool
    reason: Optional[str] = None
    request: Optional[Request] = None


@dataclass
class Scheduler:
    """Bounded FIFO queue + admission control for the serve engine.

    ``prefill_len``/``total_len`` mirror the engine's static shapes:
    a prompt longer than the prefill width can never be prefilled
    (one compiled prefill shape is the whole point), and prompt +
    max_new_tokens beyond the position table would decode garbage —
    both are admission errors, not runtime surprises.
    """

    max_queue: int
    prefill_len: int
    total_len: int
    vocab_size: int = 0  # 0 = skip the token-range check
    clock: Callable[[], float] = time.monotonic
    _queue: deque = field(default_factory=deque)
    _ids: "itertools.count" = field(default_factory=itertools.count)

    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        seed: int = 0,
        timeout: Optional[float] = None,
    ) -> Admission:
        """Validate + enqueue → Admission (never raises on bad input)."""
        try:
            prompt = [int(t) for t in prompt]
        except (TypeError, ValueError):
            # Non-numeric tokens: same front-door contract as a
            # numeric token outside the vocab — reject, don't raise.
            return Admission(False, TOKEN_OUT_OF_RANGE)
        if not prompt:
            return Admission(False, PROMPT_EMPTY)
        if len(prompt) > self.prefill_len:
            return Admission(False, PROMPT_TOO_LONG)
        if max_new_tokens < 1:
            return Admission(False, BUDGET_NONPOSITIVE)
        if len(prompt) + max_new_tokens > self.total_len:
            return Admission(False, BUDGET_EXCEEDS_CONTEXT)
        if self.vocab_size and not all(
            0 <= t < self.vocab_size for t in prompt
        ):
            return Admission(False, TOKEN_OUT_OF_RANGE)
        if len(self._queue) >= self.max_queue:
            return Admission(False, QUEUE_FULL)
        now = self.clock()
        req = Request(
            rid=next(self._ids),
            prompt=prompt,
            max_new_tokens=int(max_new_tokens),
            temperature=float(temperature),
            seed=int(seed),
            deadline=None if timeout is None else now + float(timeout),
            submitted=now,
        )
        self._queue.append(req)
        return Admission(True, request=req)

    def evict_expired(self) -> list[Request]:
        """Drop queued requests past their deadline → the evicted."""
        now = self.clock()
        expired = [r for r in self._queue if r.expired(now)]
        if expired:
            dead = {r.rid for r in expired}
            self._queue = deque(
                r for r in self._queue if r.rid not in dead
            )
        return expired

    def next_request(self) -> Optional[Request]:
        """Pop the FIFO head, None when empty.

        Callers run ``evict_expired()`` first (the engine does, every
        step) — this only pops; an expired head that slipped between
        the two calls is still caught by the engine's running-request
        deadline check on its first decode step.
        """
        return self._queue.popleft() if self._queue else None

    @property
    def depth(self) -> int:
        return len(self._queue)

"""Disaggregated serving: the KV-page transfer wire format.

PAPERS.md #1's serving gap is architectural: prefill is compute-bound
and bursty, decode is memory-bandwidth-bound and steady, and
co-locating them means every long prompt steals step budget from
every running decode lane. Splitting the phases across replicas needs
exactly one new capability — moving a prompt's prefilled K/V state
between processes — and the paged cache (PR 12) already stores that
state in the right unit: ``page_size``-token pages whose contents are
position-independent of the lane that wrote them (a page's bytes are
fully determined by the token prefix that spells its trie path).

This module is the WIRE FORMAT only: pure bytes <-> numpy, no JAX, no
sockets. The transport is ``POST /pages`` (serve/server.py), the
pool-side install is ``PrefixCache.adopt`` + ``ServeEngine.
install_prefix``, and the policy (who ships what to whom) lives in
the router (serve/fleet.py).

Frame layout (all integers little-endian)::

    magic   4s   b"DPKV"
    version u16  PAGE_WIRE_VERSION
    flags   u16  reserved, 0
    crc     u32  CRC32 over everything AFTER this field
    hlen    u32  header length in bytes
    header  hlen bytes of UTF-8 JSON
    frames  per header["frames"]: u32 length + raw bytes each

The header carries the shape/dtype contract (depth, h_kv, d_head,
page_size, dtype), the token prefix the pages hold K/V for, the
source lane's page-table row (pool-local page ids, shipped for
validation/debugging — the receiver allocates its OWN pages), the
prefilled position count, and the request's sampling state. Receivers
validate EVERYTHING against the declared shapes before any bytes
reach a cache: a corrupt, truncated, or version-skewed payload raises
:class:`PageWireError` with a machine-readable ``reason`` instead of
installing garbage pages.

K/V arrays are ``[depth, n_pages, page_size, h_kv, d_head]`` (the
pool layout with the page axis sliced to the shipped pages); int8
pools additionally ship per-page scale arrays
``[depth, n_pages, page_size, h_kv]`` float32 — the quantization
scales travel WITH the pages, so a migrated int8 prefix dequantizes
bit-identically on the receiver.
"""

from __future__ import annotations

import json
import struct
import zlib
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

MAGIC = b"DPKV"
PAGE_WIRE_VERSION = 1

# reason codes, in rough order of how early decoding fails
BAD_MAGIC = "bad_magic"
VERSION_SKEW = "version_skew"
TRUNCATED = "truncated"
CRC_MISMATCH = "crc_mismatch"
HEADER_INVALID = "header_invalid"
SHAPE_MISMATCH = "shape_mismatch"
MODEL_SKEW = "model_skew"  # raised at install time, not decode time

_PREFIX = struct.Struct("<4sHHII")  # magic, version, flags, crc, hlen
_FLEN = struct.Struct("<I")

_DTYPES = {"fp32": np.float32, "int8": np.int8}


class PageWireError(ValueError):
    """A /pages payload that must NOT be installed.

    ``reason`` is one of ``bad_magic`` / ``version_skew`` /
    ``truncated`` / ``crc_mismatch`` / ``header_invalid`` /
    ``shape_mismatch`` / ``model_skew`` — the receiver's 400 body and
    the named error the hardening tests pin. Raised before any byte
    touches a cache (``model_skew`` at install time, once the
    receiver's serving version is known).
    """

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason


@dataclass
class PageFrame:
    """One decoded /pages payload: everything a receiver needs to host
    the prefix locally.

    ``tokens`` is the exact token prefix the pages hold (length
    ``n_pages * page_size`` — only FULL pages ever ship, the same
    rule ``PrefixCache.release`` publishes under). ``table_row`` is
    the SOURCE pool's page ids in position order and ``positions``
    the source lane's prefilled length; both are validation/debug
    payload — the receiving pool allocates its own ids. ``sampling``
    echoes the request's (seed, temperature, top_p) so a decode
    replica can reconstruct the stream without re-asking the router.
    """

    page_size: int
    dtype: str  # "fp32" | "int8"
    tokens: list[int]
    k: np.ndarray  # [depth, n_pages, page_size, h_kv, d_head]
    v: np.ndarray
    k_scale: Optional[np.ndarray] = None  # int8 only: [..., h_kv] f32
    v_scale: Optional[np.ndarray] = None
    table_row: list[int] = field(default_factory=list)
    positions: int = 0
    sampling: dict = field(default_factory=dict)
    # Fleet trace context ("00-<trace>-<span>-<parent>", reqtrace
    # format) — None unless the exporting router threaded one through;
    # the header key is entirely absent in that case, so a tracing-off
    # fleet's wire bytes are identical to pre-trace builds.
    trace: Optional[str] = None
    # Serving model identity of the EXPORTER (lifecycle version token,
    # e.g. "ckpt_b@epoch0"). During a fleet /reloadz roll, replicas
    # briefly serve different versions; KV computed under one model is
    # garbage under another, so the install side refuses skewed frames
    # by name (``model_skew``). Same absent-key gate as ``trace``.
    model_version: Optional[str] = None

    @property
    def n_pages(self) -> int:
        return int(self.k.shape[1])


def encode_pages(
    tokens,
    k: np.ndarray,
    v: np.ndarray,
    *,
    page_size: int,
    k_scale: Optional[np.ndarray] = None,
    v_scale: Optional[np.ndarray] = None,
    table_row=(),
    positions: int = 0,
    sampling: Optional[dict] = None,
    trace: Optional[str] = None,
    model_version: Optional[str] = None,
) -> bytes:
    """Page arrays -> one self-validating binary payload.

    ``k``/``v`` are ``[depth, n_pages, page_size, h_kv, d_head]``;
    int8 pages must ship both scale arrays, fp32 pages neither.
    ``len(tokens)`` must equal ``n_pages * page_size`` — partial
    pages never ship (their tail positions were never prefilled).
    """
    k = np.ascontiguousarray(k)
    v = np.ascontiguousarray(v)
    if k.shape != v.shape or k.ndim != 5:
        raise ValueError(f"k/v shape mismatch: {k.shape} vs {v.shape}")
    depth, n_pages, ps, h_kv, d_head = k.shape
    if ps != page_size:
        raise ValueError(
            f"page axis {ps} != declared page_size {page_size}"
        )
    if len(tokens) != n_pages * page_size:
        raise ValueError(
            f"{len(tokens)} tokens cannot fill {n_pages} pages of "
            f"{page_size} (full pages only)"
        )
    dtype = "int8" if k.dtype == np.int8 else "fp32"
    quant = dtype == "int8"
    if quant != (k_scale is not None and v_scale is not None):
        raise ValueError(
            "int8 pages need k_scale AND v_scale; fp32 pages neither"
        )
    frames = [("k", k.astype(_DTYPES[dtype], copy=False)),
              ("v", v.astype(_DTYPES[dtype], copy=False))]
    if quant:
        want = (depth, n_pages, page_size, h_kv)
        for name, arr in (("k_scale", k_scale), ("v_scale", v_scale)):
            arr = np.ascontiguousarray(arr, dtype=np.float32)
            if arr.shape != want:
                raise ValueError(
                    f"{name} shape {arr.shape} != {want}"
                )
            frames.append((name, arr))
    header = {
        "page_size": int(page_size),
        "dtype": dtype,
        "depth": int(depth),
        "h_kv": int(h_kv),
        "d_head": int(d_head),
        "n_pages": int(n_pages),
        "tokens": [int(t) for t in tokens],
        "table_row": [int(p) for p in table_row],
        "positions": int(positions),
        "sampling": dict(sampling or {}),
        **({"trace": str(trace)} if trace is not None else {}),
        **(
            {"model_version": str(model_version)}
            if model_version is not None else {}
        ),
        "frames": [name for name, _ in frames],
    }
    hbytes = json.dumps(header, separators=(",", ":")).encode()
    body = bytearray()
    body += struct.pack("<I", len(hbytes))
    body += hbytes
    for _, arr in frames:
        raw = arr.tobytes()
        body += _FLEN.pack(len(raw))
        body += raw
    crc = zlib.crc32(bytes(body)) & 0xFFFFFFFF
    return (
        MAGIC
        + struct.pack("<HH", PAGE_WIRE_VERSION, 0)
        + struct.pack("<I", crc)
        + bytes(body)
    )


def decode_pages(buf: bytes) -> PageFrame:
    """One /pages payload -> :class:`PageFrame`, or
    :class:`PageWireError` — nothing half-decoded ever escapes.

    Validation order matters and is pinned by the hardening tests:
    magic, version, CRC (over header AND frames — a flipped bit
    anywhere fails here), then header schema, then frame shapes. A
    payload that passes returns arrays whose shapes/dtypes match the
    header exactly.
    """
    if len(buf) < _PREFIX.size:
        raise PageWireError(
            TRUNCATED, f"{len(buf)} bytes < {_PREFIX.size}-byte prefix"
        )
    magic, version, _flags, crc, hlen = _PREFIX.unpack_from(buf, 0)
    if magic != MAGIC:
        raise PageWireError(BAD_MAGIC, repr(magic))
    if version != PAGE_WIRE_VERSION:
        raise PageWireError(
            VERSION_SKEW,
            f"payload v{version}, this build speaks "
            f"v{PAGE_WIRE_VERSION}",
        )
    body = buf[12:]  # everything the CRC covers (hlen field included)
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise PageWireError(CRC_MISMATCH)
    off = 4  # past the hlen u32 (re-read from the CRC-checked body)
    (hlen,) = struct.unpack_from("<I", body, 0)
    if off + hlen > len(body):
        raise PageWireError(
            TRUNCATED, f"header wants {hlen} bytes past the payload"
        )
    try:
        header = json.loads(body[off : off + hlen].decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise PageWireError(HEADER_INVALID, str(e)) from e
    off += hlen
    try:
        page_size = int(header["page_size"])
        dtype = header["dtype"]
        depth = int(header["depth"])
        h_kv = int(header["h_kv"])
        d_head = int(header["d_head"])
        n_pages = int(header["n_pages"])
        tokens = [int(t) for t in header["tokens"]]
        table_row = [int(p) for p in header.get("table_row", [])]
        positions = int(header.get("positions", 0))
        sampling = dict(header.get("sampling", {}))
        trace = header.get("trace")
        if trace is not None:
            trace = str(trace)
        model_version = header.get("model_version")
        if model_version is not None:
            model_version = str(model_version)
        frame_names = list(header["frames"])
    except (KeyError, TypeError, ValueError) as e:
        raise PageWireError(HEADER_INVALID, str(e)) from e
    if dtype not in _DTYPES:
        raise PageWireError(HEADER_INVALID, f"unknown dtype {dtype!r}")
    if min(page_size, depth, h_kv, d_head) < 1 or n_pages < 0:
        raise PageWireError(HEADER_INVALID, "non-positive dimension")
    if len(tokens) != n_pages * page_size:
        raise PageWireError(
            SHAPE_MISMATCH,
            f"{len(tokens)} tokens vs {n_pages} pages of {page_size}",
        )
    quant = dtype == "int8"
    want_frames = ["k", "v"] + (["k_scale", "v_scale"] if quant else [])
    if frame_names != want_frames:
        raise PageWireError(
            SHAPE_MISMATCH,
            f"frames {frame_names} != required {want_frames}",
        )
    kv_shape = (depth, n_pages, page_size, h_kv, d_head)
    sc_shape = (depth, n_pages, page_size, h_kv)
    arrays: dict[str, np.ndarray] = {}
    for name in frame_names:
        if off + _FLEN.size > len(body):
            raise PageWireError(TRUNCATED, f"no length for frame {name}")
        (flen,) = _FLEN.unpack_from(body, off)
        off += _FLEN.size
        if off + flen > len(body):
            raise PageWireError(
                TRUNCATED, f"frame {name} wants {flen} bytes"
            )
        shape = sc_shape if name.endswith("_scale") else kv_shape
        np_dtype = (
            np.float32 if name.endswith("_scale") else _DTYPES[dtype]
        )
        expected = int(np.prod(shape)) * np.dtype(np_dtype).itemsize
        if flen != expected:
            raise PageWireError(
                SHAPE_MISMATCH,
                f"frame {name}: {flen} bytes != {expected} for "
                f"{shape} {np.dtype(np_dtype).name}",
            )
        arrays[name] = np.frombuffer(
            body, dtype=np_dtype, count=int(np.prod(shape)), offset=off
        ).reshape(shape)
        off += flen
    if off != len(body):
        raise PageWireError(
            TRUNCATED, f"{len(body) - off} trailing bytes"
        )
    return PageFrame(
        page_size=page_size,
        dtype=dtype,
        tokens=tokens,
        k=arrays["k"],
        v=arrays["v"],
        k_scale=arrays.get("k_scale"),
        v_scale=arrays.get("v_scale"),
        table_row=table_row,
        positions=positions,
        sampling=sampling,
        trace=trace,
        model_version=model_version,
    )

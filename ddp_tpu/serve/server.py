"""Stdlib-HTTP frontend for the serve engine.

One background thread drives ``ServeEngine.step()`` whenever work is
pending; HTTP handler threads only touch the engine under the same
lock (the engine is deliberately single-threaded — slots and cache are
one device program's state). No web framework: ``http.server`` is in
every container this repo targets, and the API is three routes:

  POST /generate   {"prompt_tokens": [...], "max_new_tokens": N,
                    "temperature"?, "top_p"?, "seed"?, "timeout"?,
                    "model"?: registered model name (multi-model
                    serving; absent → the default model)}
                   → 200 {"rid", "status", "tokens", "ttft_s", ...}
                   → 429 {"error": "queue_full"} + ``Retry-After``
                     (the measured queue-drain ETA) on backpressure
                   → 400 {"error": "prompt_too_long" | ...} on
                     permanently-invalid requests
                   → 400 on malformed bodies
                   → 503 {"error": "draining"} + ``Retry-After``
                     while the server drains for shutdown
  GET  /healthz    → 200 {"ok": true, "slots": S, ...} (liveness)
  GET  /stats      → 200 engine.stats() (TTFT/throughput summaries,
                    compile counts — the static-shape invariant is an
                    OBSERVABLE, not a comment)
  GET  /statusz    → 200 {"ok", "stats", "trace", "build_info"} —
                    stats (including mergeable summary states, SLO
                    state when --slo is set, and build provenance)
                    plus the live span-trace tail (``.trace`` is a
                    loadable Perfetto traceEvents document) and the
                    engine's goodput snapshot (ddp_tpu.obs); what the
                    fleet aggregator (scripts/obs_aggregate.py)
                    scrapes
  GET  /metricsz   → 200 Prometheus text exposition of the live
                    counters/summaries (TTFT/TPOT/queue-wait,
                    occupancy, rejects, SLO burn gauges, build info,
                    goodput — obs/promtext.py), so runs are
                    scrapeable without parsing JSONL
  POST /reload     {"checkpoint_dir": D, "epoch"?, "model"?,
                    "drain_timeout"?} — verified atomic hot-swap
                    (serve/lifecycle.py): verify the incoming
                    checkpoint (manifest CRCs + spec) BEFORE touching
                    device state, restore host-side while the old
                    model keeps serving, drain lanes to a barrier,
                    swap under the lock, roll back on any failure.
                   → 200 {"reloaded", "model_version", ...}
                   → 409 {"error": "manifest_missing" |
                     "crc_mismatch" | "spec_skew", "detail"} — named
                     rejections, old model untouched
                   → 500 load_failed / swap_failed (rolled_back)
                   → 503 swap_drain_timeout (lanes never retired)
  GET  /requestz?id=RID|0xTRACEID
                   → 200 one request's full lifecycle timeline
                    (admit → queue → prefill chunks → spec rounds →
                    decode → retire; obs/reqtrace.py); without ?id=,
                    the recently retired requests. 404 on unknown
                    ids; requires the engine's request tracing
                    (scripts/serve.py --reqtrace)

The handler blocks until its request completes (simple request/
response serving); queue position and slot availability decide
latency. Backpressure is visible: an admission rejection returns
immediately with the scheduler's reason.
"""

from __future__ import annotations

import json
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ddp_tpu.serve.engine import ServeEngine
from ddp_tpu.serve.scheduler import QUEUE_FULL

# Engine-loop idle poll; the loop burns no CPU when no work is queued.
_IDLE_SLEEP_S = 0.002


class LMServer:
    """Engine + driver thread + ThreadingHTTPServer, lifecycle-managed.

    ``port=0`` binds an ephemeral port (tests); ``server.port`` is the
    bound one. Use as a context manager or call ``start()``/``stop()``.
    """

    def __init__(
        self,
        engine: ServeEngine,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        drain_retry_after: float = 5.0,
        role: Optional[str] = None,
        models: Optional[dict] = None,
    ):
        # Disaggregated-serving role (PR 16): "prefill" | "decode" |
        # "hybrid" advertised on /healthz and /statusz so the fleet
        # router and aggregator can group by tier. None (the default,
        # and the only value single-replica setups ever see) keeps
        # every surface byte-identical to the pre-disagg server.
        self.role = role
        self.engine = engine
        # Multi-model serving (lifecycle tentpole): extra NAMED
        # engines, each with its own scheduler/slots/pages — per-model
        # accounting by construction. ``model=`` in a /generate body
        # routes here; absent routes to the default engine. Empty
        # (every pre-lifecycle setup) keeps all surfaces byte-
        # identical: no ``models`` key anywhere.
        self.models: dict = dict(models or {})
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._draining = threading.Event()
        # Advertised in the 503 Retry-After header while draining: a
        # well-behaved client re-resolves (the replacement process) and
        # retries after this many seconds.
        self.drain_retry_after = float(drain_retry_after)
        self._engine_error: Optional[str] = None
        # One reload at a time (non-blocking: a second POST /reload
        # while one runs answers 409 reload_in_progress rather than
        # queueing swaps).
        self._reload_lock = threading.Lock()
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._threads: list[threading.Thread] = []

    # ---- lifecycle --------------------------------------------------

    def start(self) -> "LMServer":
        for name, target in (
            ("serve-engine", self._engine_loop),
            ("serve-http", self._httpd.serve_forever),
        ):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        for t in self._threads:
            t.join(timeout=5)

    # ---- graceful drain ---------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def begin_drain(self) -> None:
        """Stop ADMITTING (new POSTs get 503 + Retry-After) while
        already-running lanes keep decoding to completion. Idempotent;
        visible on /healthz, /statusz and as the ``_draining`` gauge
        on /metricsz."""
        self._draining.set()

    def drain(self, timeout: float = 30.0, *, poll: float = 0.01) -> bool:
        """``begin_drain`` + wait for in-flight work to finish.

        The SIGTERM shutdown path (scripts/serve.py): a preempted
        serving process answers its running requests instead of
        killing them, bounded by ``timeout`` (a preemption grace
        window is finite). Returns True when the engine went idle,
        False when the timeout expired with lanes still running —
        either way the caller should exit afterwards.
        """
        self.begin_drain()
        deadline = time.monotonic() + max(0.0, timeout)
        while time.monotonic() < deadline:
            with self._lock:
                idle = not any(e.pending for e in self._engines())
            if idle or self._engine_error is not None:
                return True
            time.sleep(poll)
        with self._lock:
            return not any(e.pending for e in self._engines())

    def __enter__(self) -> "LMServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ---- engine driving ---------------------------------------------

    def _engines(self):
        """Every engine this server drives: default first, then the
        named multi-model extras in registration order."""
        return [self.engine, *self.models.values()]

    def _engine_loop(self) -> None:
        # An exception escaping step() (device OOM, runtime error) must
        # not kill this daemon thread SILENTLY: waiters would poll
        # forever and /healthz would keep answering ok. Record it, flip
        # health, and fail in-flight requests fast instead. Multi-model
        # engines step round-robin under the one lock — the device is
        # one program's state at a time either way.
        try:
            while not self._stop.is_set():
                busy = False
                for eng in self._engines():
                    with self._lock:
                        if eng.pending:
                            busy = True
                            eng.step()
                if not busy:
                    time.sleep(_IDLE_SLEEP_S)
        except Exception as e:  # noqa: BLE001 — terminal, reported
            self._engine_error = f"{type(e).__name__}: {e}"

    def submit_and_wait(
        self, body: dict, *, poll: float = 0.002
    ) -> tuple[int, dict]:
        """The POST /generate implementation → (http_status, payload).

        Also the in-process frontend: callers embedding the engine
        (tests, bench) can use it without a socket.
        """
        try:
            prompt = list(body["prompt_tokens"])
            max_new = int(body["max_new_tokens"])
            temperature = float(body.get("temperature", 0.0))
            top_p = float(body.get("top_p", 1.0))
            seed = int(body.get("seed", 0))
            timeout = float(body["timeout"]) if "timeout" in body else None
            # Fleet trace context + staged hop seconds (ISSUE 19):
            # optional, router-injected; a malformed context is the
            # engine's orphan-counting problem, never a 400.
            trace = body.get("trace")
            hops = body.get("hops")
            if hops is not None and not isinstance(hops, dict):
                hops = None
        except (KeyError, TypeError, ValueError):
            return 400, {
                "error": "body needs prompt_tokens (list[int]) and "
                "max_new_tokens (int); temperature/top_p/seed/timeout "
                "must be numeric"
            }
        # Multi-model routing: a named model goes to its own engine
        # (own scheduler/slots/pages); absent → the default engine. An
        # unknown name is a permanent client error, and the answer
        # lists what IS registered — misrouting to the default model
        # would silently serve the wrong weights.
        model = body.get("model")
        if model is not None:
            if model not in self.models:
                return 400, {
                    "error": "unknown_model",
                    "model": model,
                    "models": sorted(self.models),
                }
            engine = self.models[model]
        else:
            engine = self.engine
        if self._engine_error is not None:
            return 500, {"error": f"engine failed: {self._engine_error}"}
        if self._draining.is_set():
            # Draining: admitted work finishes, new work goes to the
            # replacement process. retry_after_s rides the JSON too so
            # in-process callers (no HTTP headers) see it.
            return 503, {
                "error": "draining",
                "retry_after_s": self.drain_retry_after,
            }
        with self._lock:
            adm = engine.submit(
                prompt,
                max_new,
                temperature=temperature,
                top_p=top_p,
                seed=seed,
                timeout=timeout,
                trace=trace,
                hops=hops,
                model=model,
            )
        if not adm.accepted:
            # Only queue_full is transient (retry-after-backoff
            # semantics); the validation reasons are permanent client
            # errors — a 429 would invite retry loops on requests that
            # can never be served.
            if adm.reason == QUEUE_FULL:
                # Backpressure carries WHEN to come back, like the
                # drain path's 503 does: the queue-drain-rate ETA
                # (bounded to a sane header range), falling back to
                # the static drain hint before any retire window
                # exists. In the JSON too, for in-process callers.
                with self._lock:
                    eta = engine.queue_drain_eta_s()
                retry_after = (
                    min(60.0, max(1.0, eta))
                    if eta is not None
                    else self.drain_retry_after
                )
                return 429, {
                    "error": adm.reason,
                    "retry_after_s": round(retry_after, 2),
                }
            return 400, {"error": adm.reason}
        rid = adm.request.rid
        while True:
            with self._lock:
                done = engine.pop_result(rid)
            if done is not None:
                break
            if self._engine_error is not None:
                return 500, {"error": f"engine failed: {self._engine_error}"}
            if self._stop.is_set():
                return 503, {"error": "server stopping"}
            time.sleep(poll)
        return 200, {
            "rid": done.rid,
            "status": done.status,
            "prompt_tokens": done.prompt,
            "tokens": done.tokens,
            # null for requests that never produced a token (queue
            # timeout / rejected at refill) — not a fake queue-wait.
            "ttft_s": round(done.ttft, 4) if done.ttft is not None
            else None,
            "decode_tokens_per_s": round(done.decode_tokens_per_s, 2),
            # Paged engines only (absent otherwise): how many prompt
            # tokens were served from cached pages — the signal a
            # disaggregated fleet's migration really landed (PR 16).
            **(
                {"prefix_hit_tokens": done.prefix_hit_tokens}
                if done.prefix_hit_tokens is not None
                else {}
            ),
            # Which model version served this request (absent on
            # engines that never loaded a versioned checkpoint — the
            # pre-lifecycle payload is byte-identical). The swap
            # drills read it to prove zero requests ever saw a torn
            # model.
            **(
                {"model_version": engine.model_version}
                if engine.model_version is not None
                else {}
            ),
            # Adoption echo (ISSUE 19): present ONLY when the request
            # carried a VALID inbound trace context — the router reads
            # it to count propagated-vs-orphaned. Requests without a
            # context (every pre-fleet-tracing client) see the exact
            # pre-PR payload.
            **(self._trace_echo(body.get("trace"))),
        }

    @staticmethod
    def _trace_echo(trace) -> dict:
        from ddp_tpu.obs.reqtrace import (
            format_trace_id,
            parse_trace_context,
        )

        ctx = parse_trace_context(trace) if trace is not None else None
        return (
            {"trace_id": format_trace_id(ctx[0])} if ctx else {}
        )

    # ---- verified atomic hot-swap (serve/lifecycle.py) --------------

    def reload_model(
        self, body: dict, *, poll: float = 0.005
    ) -> tuple[int, dict]:
        """The POST /reload implementation → (http_status, payload).

        verify → load → drain-to-barrier → swap → (rollback), with the
        old model serving until the instant of the swap and again
        after any failure — a reload can be slow, but it can never be
        torn. Verification and the host-side restore run OUTSIDE the
        engine lock (requests keep flowing); only the final barrier +
        pointer swap hold it, and only once ``active == 0``.
        """
        directory = body.get("checkpoint_dir")
        if not isinstance(directory, str) or not directory:
            return 400, {"error": "body needs checkpoint_dir (str)"}
        try:
            epoch = (
                int(body["epoch"]) if body.get("epoch") is not None
                else None
            )
            drain_timeout = float(body.get("drain_timeout", 30.0))
        except (TypeError, ValueError):
            return 400, {"error": "epoch/drain_timeout must be numeric"}
        model = body.get("model")
        if model is not None:
            if model not in self.models:
                return 400, {
                    "error": "unknown_model",
                    "model": model,
                    "models": sorted(self.models),
                }
            engine = self.models[model]
        else:
            engine = self.engine
        if self._engine_error is not None:
            return 500, {"error": f"engine failed: {self._engine_error}"}
        if not self._reload_lock.acquire(blocking=False):
            return 409, {"error": "reload_in_progress"}
        try:
            return self._reload_locked(
                engine, directory, epoch, drain_timeout, poll
            )
        finally:
            self._reload_lock.release()

    def _reload_locked(
        self, engine, directory, epoch, drain_timeout, poll
    ) -> tuple[int, dict]:
        from ddp_tpu.serve import lifecycle as lc

        def record(outcome: str, **fields) -> None:
            engine.metrics.write(
                "serve_reload", outcome=outcome, directory=directory,
                **fields,
            )

        # Stage 1 — verify, host-side, before anything else: manifest
        # present, CRCs intact, spec exactly the serving spec. A
        # rejection names its reason and device state was never
        # touched.
        t0 = time.monotonic()
        try:
            target = lc.verify_reload_target(
                directory,
                epoch=epoch,
                current_spec=engine.spec,
                num_heads_fallback=engine.spec.num_heads,
            )
        except lc.ReloadRejected as e:
            record("rejected", reason=e.reason)
            return 409, {"error": e.reason, "detail": e.detail}
        verify_s = round(time.monotonic() - t0, 4)

        # Stage 2 — restore to host. The old model keeps serving; a
        # failed read here (I/O error, torn file the manifest missed)
        # aborts with nothing installed.
        t0 = time.monotonic()
        try:
            new_params = lc.load_reload_target(target)
        except Exception as e:  # noqa: BLE001 — named in the payload
            record("load_failed", model_version=target.version)
            return 500, {
                "error": "load_failed",
                "detail": f"{type(e).__name__}: {e}",
            }
        load_s = round(time.monotonic() - t0, 4)

        # Stage 3 — drain lanes to the barrier. Admission pauses (new
        # work queues, NOTHING is dropped) while bound lanes decode to
        # completion; the swap happens in the same lock hold that
        # observes active == 0, so no lane can bind in between.
        t_swap = time.monotonic()
        with self._lock:
            engine.pause_admission()
        deadline = time.monotonic() + max(0.0, drain_timeout)
        try:
            while True:
                with self._lock:
                    if engine.active == 0:
                        return self._swap_at_barrier(
                            engine, target, new_params, t_swap,
                            verify_s, load_s, record,
                        )
                if time.monotonic() > deadline:
                    record("drain_timeout", model_version=target.version)
                    return 503, {
                        "error": "swap_drain_timeout",
                        "detail": f"lanes still bound after "
                        f"{drain_timeout}s",
                    }
                time.sleep(poll)
        finally:
            # Whatever happened — swap, rollback, timeout — the front
            # door reopens; paused admission must never outlive the
            # reload that paused it.
            with self._lock:
                engine.resume_admission()

    def _swap_at_barrier(
        self, engine, target, new_params, t_swap, verify_s, load_s,
        record,
    ) -> tuple[int, dict]:
        """Install under the already-held barrier lock hold; roll back
        to the old references on ANY failure. Caller holds self._lock
        with ``engine.active == 0``."""
        previous = engine.model_version
        invalidate = previous != target.version
        old_params = engine.params
        try:
            engine.install_params(
                new_params,
                model_version=target.version,
                invalidate_prefix=invalidate,
            )
        except Exception as e:  # noqa: BLE001 — rolled back, reported
            engine.params = old_params
            engine.model_version = previous
            engine.rollbacks_total += 1
            record(
                "swap_failed",
                model_version=target.version,
                rolled_back=True,
            )
            return 500, {
                "error": "swap_failed",
                "rolled_back": True,
                "detail": f"{type(e).__name__}: {e}",
            }
        swap_s = round(time.monotonic() - t_swap, 4)
        record(
            "swapped",
            model_version=target.version,
            **({"previous_version": previous} if previous else {}),
            epoch=target.epoch,
            verify_s=verify_s,
            load_s=load_s,
            swap_s=swap_s,
            invalidated_prefix=invalidate,
        )
        return 200, {
            "reloaded": True,
            "model_version": target.version,
            "previous_version": previous,
            "epoch": target.epoch,
            "verify_s": verify_s,
            "load_s": load_s,
            "swap_s": swap_s,
            "invalidated_prefix": invalidate,
        }

    def snapshot(self, route: str) -> Optional[dict | str]:
        """Route → JSON-ready dict, Prometheus text (str), or None."""
        if route == "/healthz":
            with self._lock:
                return {
                    "ok": self._engine_error is None,
                    "slots": self.engine.num_slots,
                    "active": self.engine.active,
                    "queue_depth": self.engine.scheduler.depth,
                    "draining": self.draining,
                    **({"role": self.role} if self.role else {}),
                    # Serving model version (absent until a versioned
                    # checkpoint loads): what the fleet's poll loop
                    # reads so the router never routes a ``model=``
                    # request to a not-yet-swapped replica, and what
                    # the reload loop's convergence check compares.
                    **(
                        {"model_version": self.engine.model_version}
                        if self.engine.model_version is not None
                        else {}
                    ),
                    **(
                        {
                            "models": {
                                name: {
                                    **(
                                        {"model_version": e.model_version}
                                        if e.model_version is not None
                                        else {}
                                    ),
                                    "slots": e.num_slots,
                                    "active": e.active,
                                    "queue_depth": e.scheduler.depth,
                                }
                                for name, e in self.models.items()
                            }
                        }
                        if self.models
                        else {}
                    ),
                    **(
                        {"engine_error": self._engine_error}
                        if self._engine_error
                        else {}
                    ),
                }
        if route == "/stats":
            with self._lock:
                return self.engine.stats(include_ledger=True)
        if route == "/metricsz":
            # Prometheus text, not JSON: rendered under the engine
            # lock from the same stats() snapshot /stats serves.
            from ddp_tpu.obs.promtext import render_serve

            with self._lock:
                return render_serve(
                    self.engine.stats(),
                    up=self._engine_error is None,
                    draining=self.draining,
                )
        if route == "/statusz":
            # Live observability snapshot (ddp_tpu.obs): operational
            # stats + goodput (inside engine.stats()) plus the tail of
            # the span trace — the ``trace`` value is itself a valid
            # Chrome/Perfetto ``traceEvents`` document, so
            # ``curl .../statusz | jq .trace > t.json`` loads directly.
            # include_states=True: the latency summaries' mergeable
            # StatSummary states ride along so a fleet aggregator
            # (obs/aggregate.py) merges EXACTLY instead of averaging
            # percentiles.
            with self._lock:
                return {
                    "ok": self._engine_error is None,
                    "draining": self.draining,
                    **({"role": self.role} if self.role else {}),
                    "stats": self.engine.stats(include_states=True),
                    # Named multi-model engines, each with its own full
                    # stats block (absent when none are registered).
                    **(
                        {
                            "models": {
                                name: e.stats(include_states=True)
                                for name, e in self.models.items()
                            }
                        }
                        if self.models
                        else {}
                    ),
                    "trace": self.engine.tracer.snapshot(limit=512),
                }
        return None

    # ---- disaggregated serving: the /pages transfer plane (PR 16) ---

    def pages_export(self, body: dict) -> tuple[int, "dict | bytes"]:
        """POST /pages/export: {"prompt_tokens": [...]} → the longest
        cached prefix of that prompt as one binary page frame
        (serve/disagg.py), or 404 when no full page of it is cached
        here (prefix_not_found — the puller falls back to a local
        prefill). 409 on non-paged engines: a fleet whose members
        disagree about paging is a config error worth naming."""
        try:
            prompt = [int(t) for t in body["prompt_tokens"]]
        except (KeyError, TypeError, ValueError):
            return 400, {"error": "body needs prompt_tokens (list[int])"}
        if not self.engine.paged:
            return 409, {"error": "not_paged"}
        # Optional fleet trace context: rides into the DPKV header so
        # the migration's install side sees the same trace id (absent
        # in the body → absent in the header → pre-PR wire bytes).
        trace = body.get("trace")
        if not isinstance(trace, str):
            trace = None
        with self._lock:
            buf = self.engine.export_prefix(prompt, trace=trace)
        if buf is None:
            return 404, {"error": "prefix_not_found"}
        return 200, buf

    def pages_install(self, raw: bytes) -> tuple[int, dict]:
        """POST /pages: one binary page frame → validate, adopt into
        the radix index, copy the missing pages into the pool
        (engine.install_prefix). A frame that fails validation gets a
        400 with the named reason and NOTHING is installed — the
        torn-page-set guarantee. A pool that cannot host the pages
        answers 409 pool_exhausted (the sender just skips the
        migration; the request replays from the prompt)."""
        from ddp_tpu.serve.disagg import PageWireError, decode_pages

        if self._engine_error is not None:
            return 500, {"error": f"engine failed: {self._engine_error}"}
        try:
            frame = decode_pages(raw)
        except PageWireError as e:
            return 400, {"error": e.reason, "detail": str(e)}
        try:
            with self._lock:
                res = self.engine.install_prefix(frame)
        except PageWireError as e:
            return 400, {"error": e.reason, "detail": str(e)}
        if res is None:
            return 409, {"error": "pool_exhausted"}
        # Echo the frame's trace context (gated on its presence, like
        # /generate's echo) so the pushing router can confirm the
        # context survived the DPKV round trip.
        return 200, {
            "installed": True,
            **res,
            **self._trace_echo(frame.trace),
        }

    def requestz(self, query: str) -> tuple[int, dict]:
        """GET /requestz[?id=...] → (status, payload): one request's
        reconstructed lifecycle timeline (obs/reqtrace.py), or the
        recently retired set when no id is given."""
        from urllib.parse import parse_qs

        if self.engine._reqtrace is None:
            return 404, {
                "error": "request tracing is off (scripts/serve.py "
                "--reqtrace, or ServeEngine(reqtrace=True))"
            }
        params = parse_qs(query or "")
        key = (params.get("id") or [None])[0]
        with self._lock:
            if key is None:
                return 200, {
                    "enabled": True,
                    "live": self.engine._reqtrace.live_count,
                    "recent": self.engine._reqtrace.recent(),
                }
            timeline = self.engine.request_timeline(key)
        if timeline is None:
            return 404, {
                "error": f"unknown request {key!r} (rid or 0x-prefixed "
                "trace id; retired timelines are retained up to the "
                "reqtrace_keep bound)"
            }
        return 200, timeline


def _make_handler(server: LMServer):
    class Handler(BaseHTTPRequestHandler):
        # Quiet: request logging goes through metrics, not stderr.
        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _send_text(
            self, status: int, text: str, ctype: str,
            headers: Optional[dict] = None,
        ) -> None:
            data = text.encode()
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def _send(
            self, status: int, payload: dict,
            headers: Optional[dict] = None,
        ) -> None:
            self._send_text(
                status, json.dumps(payload), "application/json", headers
            )

        def do_GET(self):  # noqa: N802
            route, _, query = self.path.partition("?")
            if route == "/requestz":
                status, payload = server.requestz(query)
                self._send(status, payload)
                return
            payload = server.snapshot(route)
            if payload is None:
                self._send(404, {"error": f"no route {self.path}"})
            elif isinstance(payload, str):
                # /metricsz: Prometheus text exposition, not JSON.
                from ddp_tpu.obs.promtext import CONTENT_TYPE

                self._send_text(200, payload, CONTENT_TYPE)
            else:
                # A dead engine must fail status-code liveness probes
                # (`curl -f /healthz`), not just flip a JSON field.
                status = 503 if payload.get("ok") is False else 200
                self._send(status, payload)

        def do_POST(self):  # noqa: N802
            if self.path == "/pages":
                # Binary page frame (serve/disagg.py), NOT JSON — the
                # payload is raw K/V bytes with its own header + CRC.
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n)
                status, payload = server.pages_install(raw)
                self._send(status, payload)
                return
            if self.path not in ("/generate", "/pages/export", "/reload"):
                self._send(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
            except (ValueError, TypeError) as e:
                self._send(400, {"error": f"bad JSON body: {e}"})
                return
            if self.path == "/reload":
                status, payload = server.reload_model(body)
                self._send(status, payload)
                return
            if self.path == "/pages/export":
                status, payload = server.pages_export(body)
                if isinstance(payload, bytes):
                    self.send_response(status)
                    self.send_header(
                        "Content-Type", "application/octet-stream"
                    )
                    self.send_header(
                        "Content-Length", str(len(payload))
                    )
                    self.end_headers()
                    self.wfile.write(payload)
                else:
                    self._send(status, payload)
                return
            status, payload = server.submit_and_wait(body)
            headers = None
            if status == 503 and payload.get("error") == "draining":
                # RFC 9110 Retry-After: tells clients/load-balancers
                # when to come back (to the replacement process).
                headers = {
                    "Retry-After": str(int(server.drain_retry_after))
                }
            elif status == 429 and payload.get("retry_after_s"):
                # Backpressure 429s carry the queue-drain ETA the
                # engine measured — a client (or the fleet router)
                # backs off for as long as a seat will actually take
                # to free, instead of a blind constant.
                headers = {
                    "Retry-After": str(
                        max(1, math.ceil(payload["retry_after_s"]))
                    )
                }
            self._send(status, payload, headers)

    return Handler

"""ddp_tpu.serve — continuous-batching TPU inference engine.

The framework's serving half (the ROADMAP north star "serves heavy
traffic"): a fixed-slot, static-shape decode batch over the
models/generate.py KV cache, fed by a FIFO queue with admission
control, fronted by a stdlib HTTP server, observable through the same
JSONL metrics stream the trainer writes. See docs/SERVING.md.

Layer map:

  scheduler.py   admission control, FIFO queue, deadlines, chunked-
                 prefill planning (pure host)
  pages.py       paged-KV page pool + radix prefix index: refcounted
                 page_size-token pages, LRU eviction of cached
                 prefixes, page-granular prompt matching (pure host)
  engine.py      slots, continuous batching, the device-resident
                 decode loop (fused on-device sampling, chunked
                 bucketed prefill, bounded compile set; optional
                 paged KV + prefix reuse via --page_size)
  server.py      stdlib HTTP frontend + background engine thread
  disagg.py      KV-page wire format for the POST /pages transfer
                 plane: versioned, CRC-guarded binary frames carrying
                 raw K/V pages (fp32 or int8 + per-page scales),
                 table row, positions, sampling state (pure host)
  fleet.py       N supervised replica processes behind a health-gated
                 router: prefix-affinity + least-loaded dispatch,
                 retry/hedging/circuit-breaking, replay on replica
                 death, rolling restart; role-aware prefill/decode
                 dispatch + fleet-global prefix directory (pure host)
  scripts/serve.py (repo root)  checkpoint → listening server CLI
  scripts/fleet.py (repo root)  N-replica fleet frontend CLI
"""

from ddp_tpu.serve.disagg import (  # noqa: F401
    PageFrame,
    PageWireError,
    decode_pages,
    encode_pages,
)
from ddp_tpu.serve.engine import Completion, ServeEngine  # noqa: F401
from ddp_tpu.serve.fleet import (  # noqa: F401
    ROLE_DECODE,
    ROLE_HYBRID,
    ROLE_PREFILL,
    CircuitBreaker,
    FleetServer,
    Replica,
    ReplicaManager,
    Router,
    RouterConfig,
)
from ddp_tpu.serve.pages import PrefixCache, page_demand  # noqa: F401
from ddp_tpu.serve.scheduler import (  # noqa: F401
    Admission,
    Request,
    Scheduler,
)

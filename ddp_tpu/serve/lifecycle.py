"""Zero-downtime model lifecycle: streaming restore + verified hot-swap.

Production fleets ship new checkpoints without restarting; this module
is the host-side half of that contract (the device-side swap lives in
serve/engine.install_params, the HTTP surface in serve/server.py
``POST /reload`` and serve/fleet.py ``POST /reloadz``):

  verify → load → drain-to-barrier → swap → (rollback on any failure)

``verify_reload_target`` runs BEFORE anything touches device state:
the incoming checkpoint must carry a readable PR-5 integrity manifest
(``manifest_missing``), every listed file must match its recorded
size + CRC (``crc_mismatch``), and the spec derived from its metadata
+ ``lm_spec.json`` sidecar must equal the serving spec exactly
(``spec_skew`` — the compiled program set is shape-addressed, so a
skewed tree could never install atomically). The three rejection
reasons are named constants because the fleet's reload loop and the
chaos drills pin them.

``StreamingRestore`` is the startup-latency half: a cold replica pays
restore THEN warmup serially; a streaming replica runs the orbax
partial restores on a background thread — embedding + first-K blocks
land first and open admission (requests queue against the paused
engine), the deep blocks land behind them — while the main thread
compiles the program set over same-shaped init params. Dispatch
correctness still requires the full tree (the forward pass reads
every layer), so first dispatch waits for full residency; the win is
wall-clock overlap (restore I/O behind XLA compiles, admission open
early), measured by ``bench.py serve_reload``.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
import threading
import time
from typing import Any, Iterable, Optional

logger = logging.getLogger("ddp_tpu")

# The named rejection reasons a reload can answer with — verification
# happens before device state is touched, so every one of these leaves
# the old model serving untouched.
REASON_MANIFEST_MISSING = "manifest_missing"
REASON_CRC_MISMATCH = "crc_mismatch"
REASON_SPEC_SKEW = "spec_skew"
REJECTION_REASONS = (
    REASON_MANIFEST_MISSING,
    REASON_CRC_MISMATCH,
    REASON_SPEC_SKEW,
)


class ReloadRejected(Exception):
    """A reload target verification failed — nothing was installed.

    ``reason`` is one of ``REJECTION_REASONS`` (the HTTP payload's
    ``error`` field); ``detail`` is the human-readable evidence.
    """

    def __init__(self, reason: str, detail: str):
        super().__init__(f"{reason}: {detail}")
        self.reason = reason
        self.detail = detail


def model_version_token(directory: str, epoch: int) -> str:
    """Canonical version label for "checkpoint dir D at epoch N".

    What /healthz advertises, serve_request records carry, and the
    fleet's convergence check compares — stable across replicas and
    restarts because it names the artifact, not the process.
    """
    base = os.path.basename(os.path.normpath(os.path.abspath(directory)))
    return f"{base}@epoch{epoch}"


@dataclasses.dataclass(frozen=True)
class ReloadTarget:
    """A verified swap target: everything the load + swap stages need,
    produced only by ``verify_reload_target``."""

    directory: str
    epoch: int
    version: str
    spec: Any


def verify_reload_target(
    directory: str,
    *,
    epoch: Optional[int] = None,
    current_spec: Any = None,
    num_heads_fallback: int = 4,
) -> ReloadTarget:
    """Verify a checkpoint as a hot-swap target → ``ReloadTarget``.

    Raises ``ReloadRejected`` with a named reason on any failure; no
    tensor data is read (metadata + manifest CRCs only), and device
    state is never touched — the caller's old model keeps serving.

    Deliberately STRICTER than the restore path: ``restore`` accepts
    manifest-less checkpoints for compatibility, but a hot-swap's
    failure mode is a live fleet serving a half-trusted model — no
    manifest, no swap.
    """
    from ddp_tpu.train.checkpoint import (
        CheckpointManager,
        derive_spec_with_sidecar,
        verify_manifest,
    )

    mgr = CheckpointManager(directory)
    try:
        if epoch is None:
            epoch = mgr.latest_epoch()
            if epoch is None:
                raise ReloadRejected(
                    REASON_MANIFEST_MISSING,
                    f"no checkpoint found in {directory}",
                )
        epoch = int(epoch)
        problems = verify_manifest(mgr.directory, epoch)
        if problems is None:
            raise ReloadRejected(
                REASON_MANIFEST_MISSING,
                f"epoch {epoch} has no readable integrity manifest — "
                "refusing to hot-swap an unverifiable checkpoint",
            )
        if problems:
            raise ReloadRejected(REASON_CRC_MISMATCH, "; ".join(problems))
        try:
            meta = mgr.params_metadata(epoch)
            spec = derive_spec_with_sidecar(
                directory, meta, num_heads_fallback=num_heads_fallback
            )
        except (KeyError, ValueError) as e:
            raise ReloadRejected(REASON_SPEC_SKEW, str(e))
        if current_spec is not None and spec != current_spec:
            diffs = [
                f"{f}: {getattr(spec, f)!r} != serving "
                f"{getattr(current_spec, f)!r}"
                for f in type(current_spec)._fields
                if getattr(spec, f, None) != getattr(current_spec, f)
            ]
            raise ReloadRejected(
                REASON_SPEC_SKEW,
                "; ".join(diffs) or "spec differs from the serving spec",
            )
        return ReloadTarget(
            directory=os.path.abspath(directory),
            epoch=epoch,
            version=model_version_token(directory, epoch),
            spec=spec,
        )
    finally:
        mgr.close()


def load_reload_target(target: ReloadTarget) -> Any:
    """Host-side restore of a verified target's params.

    The old model keeps serving throughout — nothing here touches the
    engine; the returned tree goes to ``engine.install_params`` once
    the lanes have drained to the swap barrier. Integrity-verified
    discovery and the qkv-format gate ride along for free
    (``restore_for_inference``).
    """
    from ddp_tpu.train.checkpoint import CheckpointManager

    mgr = CheckpointManager(target.directory)
    try:
        params, _, _ = mgr.restore_for_inference(target.epoch)
        return params
    finally:
        mgr.close()


# ---------------------------------------------------------------------
# Streaming restore
# ---------------------------------------------------------------------

_BLOCK_RE = re.compile(r"^block(\d+)$")


def split_param_groups(
    children: Iterable[str], first_blocks: int = 1
) -> tuple[list[str], list[str]]:
    """Param children → (admission group, deep group), in model order.

    The embedding, the positional table and the first
    ``first_blocks`` transformer blocks gate admission (they are what
    a prefill touches first); every deeper block and the final norm
    stream behind them. Unknown children land in the deep group — a
    foreign tree just degrades to "everything gates on full
    residency", never to serving without a layer.
    """
    names = [str(c) for c in children]
    blocks = sorted(
        (int(m.group(1)), n)
        for n in names
        for m in [_BLOCK_RE.match(n)]
        if m
    )
    early = {n for _, n in blocks[: max(0, first_blocks)]}
    # Emit MODEL order (embed → pos_embed → blocks ascending → rest),
    # not input order: metadata trees iterate alphabetically, and the
    # whole point of the admission group is restoring what a prefill
    # touches first, first.
    ordered = [n for n in ("embed", "pos_embed") if n in names]
    ordered += [n for _, n in blocks]
    ordered += sorted(
        n for n in names
        if n not in ordered
    )
    admission, deep = [], []
    for n in ordered:
        if n in ("embed", "pos_embed") or n in early:
            admission.append(n)
        else:
            deep.append(n)
    return admission, deep


class StreamingRestore:
    """Layer-streamed checkpoint restore for serving startup.

    Construction resolves the epoch (verified discovery) and derives
    the spec from checkpoint METADATA + the ``lm_spec.json`` sidecar —
    no tensor data read — so the caller can build and warm up the
    engine over init params while ``start()``'s background thread
    restores the real weights in residency order: the admission group
    first (``wait_admission`` returns → open the front door, requests
    queue), then the deep group (``wait`` returns the full tree →
    ``engine.install_params`` + resume admission). The deep phase
    re-checks the qkv-format sidecar exactly like
    ``restore_for_inference`` does.
    """

    def __init__(
        self,
        directory: str,
        *,
        epoch: Optional[int] = None,
        first_blocks: int = 1,
        num_heads_fallback: int = 4,
    ):
        from ddp_tpu.train.checkpoint import (
            CheckpointManager,
            derive_spec_with_sidecar,
        )

        self.directory = directory
        self._mgr = CheckpointManager(directory)
        try:
            if epoch is None:
                epoch = self._mgr.latest_intact_epoch()
                if epoch is None:
                    raise FileNotFoundError(
                        f"no checkpoints in {directory}"
                    )
            self.epoch = int(epoch)
            meta = self._mgr.params_metadata(self.epoch)
        except Exception:
            self._mgr.close()
            raise
        self.version = model_version_token(directory, self.epoch)
        self.spec = derive_spec_with_sidecar(
            directory, meta, num_heads_fallback=num_heads_fallback
        )
        self._children = [str(k) for k in meta]
        self._meta = meta
        self.admission_group, self.deep_group = split_param_groups(
            self._children, first_blocks
        )
        self._params: dict = {}
        self._error: Optional[str] = None
        self._admission_evt = threading.Event()
        self._done_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0: Optional[float] = None
        # Wall seconds from start() to each residency milestone — what
        # the bench's cold-vs-streaming TTFT split reads.
        self.admission_ready_s: Optional[float] = None
        self.complete_s: Optional[float] = None

    def placeholder_params(self) -> dict:
        """A zeros tree with the checkpoint's exact shapes/dtypes —
        the stand-in the engine builds and warms up over while the
        real weights stream. Zeros, not a random init: allocation is
        near-free, while seeding a real init costs seconds of PRNG
        work at exactly the moment the overlap is supposed to be
        winning (warmup compiles only care about shapes)."""
        import jax
        import jax.numpy as jnp

        return jax.tree.map(
            lambda m: jnp.zeros(m.shape, m.dtype), self._meta
        )

    def start(self) -> "StreamingRestore":
        if self._thread is not None:
            raise RuntimeError("streaming restore already started")
        self._t0 = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name="stream-restore", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        try:
            got = self._mgr.read_params_children(
                self.epoch, self.admission_group
            )
            self._params.update(got)
            self.admission_ready_s = round(
                time.monotonic() - self._t0, 4
            )
            self._admission_evt.set()
            got = self._mgr.read_params_children(
                self.epoch, self.deep_group
            )
            self._params.update(got)
            missing = [
                c for c in self._children if c not in self._params
            ]
            if missing:
                raise RuntimeError(
                    f"streamed restore left {missing} unrestored"
                )
            from ddp_tpu.train.checkpoint import _check_qkv_format

            fmt = self._mgr.read_partial(self.epoch, ("fmt",)).get("fmt")
            _check_qkv_format(
                int(fmt) if fmt is not None else None,
                self._params,
                f"checkpoint epoch {self.epoch}",
            )
            self.complete_s = round(time.monotonic() - self._t0, 4)
        except Exception as e:  # noqa: BLE001 — surfaced to waiters
            self._error = f"{type(e).__name__}: {e}"
        finally:
            # Waiters always wake; they check _error first.
            self._admission_evt.set()
            self._done_evt.set()
            try:
                self._mgr.close()
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass

    def wait_admission(self, timeout: Optional[float] = None) -> bool:
        """True once the admission group is resident (open the front
        door); raises if the restore already failed."""
        ok = self._admission_evt.wait(timeout)
        if self._error is not None:
            raise RuntimeError(
                f"streaming restore failed: {self._error}"
            )
        return ok

    def wait(self, timeout: Optional[float] = None) -> dict:
        """Block to FULL residency → the complete params tree."""
        if not self._done_evt.wait(timeout):
            raise TimeoutError(
                f"streaming restore of {self.directory} epoch "
                f"{self.epoch} did not finish in {timeout}s"
            )
        if self._error is not None:
            raise RuntimeError(
                f"streaming restore failed: {self._error}"
            )
        return self._params

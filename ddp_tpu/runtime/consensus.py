"""Cross-process agreement primitives.

The general form of the trainer's preemption handshake: rank-local
signals (a SIGTERM that landed on one host, an anomaly only one rank's
sentry saw, a watchdog close to firing) must become a *collective*
decision before anyone acts, because the actions — checkpoint save,
halt, epoch exit — are collectives themselves, and a one-rank exit
leaves every peer blocked in the next collective forever (the
reference's hang failure mode, SURVEY.md §5).

``agree_any`` / ``agree_all`` are **collective calls**: every process
must reach them the same number of times in the same order, at a
deterministic point (a fixed batch cadence, an epoch boundary). They
accept a scalar flag or a flat sequence of flags; a sequence is ORed /
ANDed *elementwise* so one gather can carry several independent
decisions (the trainer piggybacks preempt + health-halt + health-
rescue on a single allgather per cadence point).

Single-process runs short-circuit without touching ``jax.distributed``
— the primitives are safe to call unconditionally.
"""

from __future__ import annotations

from typing import Sequence, overload

import numpy as np


def _gather(flags: Sequence[bool]) -> np.ndarray:
    """allgather a bool vector → [num_processes, len(flags)]."""
    from jax.experimental import multihost_utils

    out = np.asarray(
        multihost_utils.process_allgather(np.asarray(flags, dtype=bool))
    )
    return out.reshape(-1, len(flags))


def _agree(flags, reduce, num_processes):
    scalar = isinstance(flags, (bool, np.bool_, int))
    vec = [bool(flags)] if scalar else [bool(f) for f in flags]
    if num_processes is None:
        import jax

        num_processes = jax.process_count()
    if num_processes <= 1:
        agreed = vec
    else:
        agreed = [bool(v) for v in reduce(_gather(vec), axis=0)]
    return agreed[0] if scalar else agreed


@overload
def agree_any(flags: bool, *, num_processes: int | None = ...) -> bool: ...
@overload
def agree_any(
    flags: Sequence[bool], *, num_processes: int | None = ...
) -> list[bool]: ...


def agree_any(flags, *, num_processes=None):
    """Collective OR: True wherever ANY process raised the flag.

    The escalation primitive — "somebody saw it, everybody acts".
    Scalar in → scalar out; sequence in → elementwise list out.
    ``num_processes`` overrides the ``jax.process_count()`` default
    (the trainer passes its DistContext's count so an emulated
    multi-process context exercises the gather path).
    """
    return _agree(flags, np.any, num_processes)


@overload
def agree_all(flags: bool, *, num_processes: int | None = ...) -> bool: ...
@overload
def agree_all(
    flags: Sequence[bool], *, num_processes: int | None = ...
) -> list[bool]: ...


def agree_all(flags, *, num_processes=None):
    """Collective AND: True only where EVERY process raised the flag.

    The readiness primitive — "proceed only when the whole world is
    ready" (e.g. every rank finished verifying a checkpoint before
    anyone restores it).
    """
    return _agree(flags, np.all, num_processes)

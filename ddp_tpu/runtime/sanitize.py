"""Runtime sanitizer — the dynamic half of the hazard linter.

``ddp_tpu.analysis`` finds host-sync and transfer patterns statically
(DDP002); ``--sanitize`` proves the dynamic instances: it arms
``jax.transfer_guard("disallow")`` around the hot loop so any
IMPLICIT host↔device transfer — a numpy array slipping into the
jitted step, a stray ``float(loss)`` added outside the log cadence, a
``.item()`` in a callback — raises an ``XlaRuntimeError`` at the
offending call instead of silently stalling every step. The loop's
DELIBERATE syncs (the log-cadence reads, the one-step-behind health
retire, the consensus gather, the serve engine's ``[slots]`` fetch)
run inside explicit ``allow()`` windows: the contract in the code,
enforced at runtime.

Explicit transfers (``jax.device_put`` / ``device_get`` — how the
loader and the drain fetch move data ON PURPOSE) stay legal under the
guard; so does trace-time constant embedding (probed: jit compile
under ``disallow`` is clean on this jax).

The second half is the desync watchdog: with ``--sanitize`` and no
explicit ``--watchdog_timeout``, the step watchdog arms at
``--sanitize_timeout`` with an abort that names the likely cause — a
rank-divergent collective (the DDP001 class) leaves every peer
blocked mid-collective, which from one host is indistinguishable
from a hang except by the flight-recorder tail the forensics hook
dumps.
"""

from __future__ import annotations

import contextlib
import logging

logger = logging.getLogger("ddp_tpu")

# Default seconds of no step progress before the desync watchdog
# aborts (generous: must clear the first-step XLA compile).
DESYNC_TIMEOUT_DEFAULT = 300.0


class Sanitizer:
    """Transfer-guard windows for a hot loop.

    ``enabled=False`` (the default everywhere) makes both context
    managers free no-ops, so call sites wire them unconditionally —
    the same pattern as the tracer's null spans.
    """

    def __init__(self, enabled: bool):
        self.enabled = bool(enabled)

    def guard(self):
        """Arm ``disallow`` for the enclosed hot-loop region."""
        if not self.enabled:
            return contextlib.nullcontext()
        import jax

        return jax.transfer_guard("disallow")

    def allow(self):
        """A deliberate-sync window inside a guarded region (log
        cadence, health retire, consensus gather, drain fetch)."""
        if not self.enabled:
            return contextlib.nullcontext()
        import jax

        return jax.transfer_guard("allow")


def desync_abort(num_processes: int):
    """Watchdog abort for ``--sanitize``: same forensics + hard exit
    as the default, prefixed with the desync diagnosis so the
    post-mortem starts at the right hazard class."""
    from ddp_tpu.utils.watchdog import _default_abort

    def _abort(seconds: float) -> None:
        logger.error(
            "sanitize: no step progress for %.0fs across %d "
            "process(es) — suspected collective desync (a rank left a "
            "collective early, or entered one alone: the DDP001 "
            "class). The flight recorder dumps next; check the last "
            "per-rank steps for the divergence point.",
            seconds,
            num_processes,
        )
        _default_abort(seconds)

    return _abort

"""Runtime layer: distributed context, backend selection, device mesh.

TPU-native replacement for the reference's process-group runtime
(``utils.py:5-19`` — ``setup``/``cleanup`` over c10d) and its implicit
launcher (``train_ddp.py:222-224`` ``torch.multiprocessing.spawn``):
JAX runs one process per *host* (each process owns all local chips),
rendezvous is ``jax.distributed.initialize`` instead of env:// TCP, and
collectives are compiled into the program instead of called at runtime.
"""

from ddp_tpu.runtime.dist import DistContext, setup, cleanup  # noqa: F401
from ddp_tpu.runtime.mesh import make_mesh, MeshSpec  # noqa: F401

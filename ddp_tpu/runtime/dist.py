"""Distributed context: process bring-up, backend selection, teardown.

Capability parity with the reference's ``utils.py:5-19``:

- ``setup(rank, world_size, backend)`` there does backend auto-selection
  (``"nccl" if torch.cuda.is_available() else "gloo"``, utils.py:6),
  env:// rendezvous (utils.py:7-11) and device pinning (utils.py:12-13).
  Here the backend switch grows the TPU branch the north-star asks for:
  ``tpu`` when TPU chips are present, else ``cpu`` (optionally with
  emulated multi-device for dev boxes — the TPU analogue of running
  2-proc gloo on a laptop).
- Multi-host rendezvous is ``jax.distributed.initialize(coordinator, N,
  id)``; the coordinator address plays MASTER_ADDR/MASTER_PORT's role.
  Unlike the reference (which never sets MASTER_ADDR — SURVEY.md §1 L2),
  single-process runs need no rendezvous at all and just work.
- ``cleanup()`` mirrors ``dist.destroy_process_group()`` (utils.py:18)
  via ``jax.distributed.shutdown()`` plus the same rank-tagged log line.

No NCCL, no CUDA: collectives lower onto ICI/DCN through XLA.
"""

from __future__ import annotations

import dataclasses
import logging
import os

logger = logging.getLogger("ddp_tpu")


def _ensure_host_device_count(n: int) -> None:
    """Request ``n`` emulated host (CPU) devices.

    Must run before the XLA CPU client is created. This is the dev-box
    stand-in for a multi-chip slice, like the reference's 2-process gloo
    quickstart (README.md:67-70) stands in for a GPU cluster.
    """
    import re

    flag = f"--xla_force_host_platform_device_count={n}"
    existing = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in existing:
        # Replace a stale count rather than silently keeping it.
        os.environ["XLA_FLAGS"] = re.sub(
            r"--xla_force_host_platform_device_count=\d+", flag, existing
        )
        return
    os.environ["XLA_FLAGS"] = (existing + " " + flag).strip()


def force_cpu_backend(num_devices: int | None = None) -> None:
    """Select the CPU platform (optionally with emulated devices).

    Call before any JAX computation. Overrides platform plugins that
    pin ``jax_platforms`` at import time.
    """
    if num_devices is not None:
        _ensure_host_device_count(num_devices)
    import jax

    jax.config.update("jax_platforms", "cpu")


@dataclasses.dataclass(frozen=True)
class DistContext:
    """What the reference smears across env vars and c10d global state.

    ``process_id``/``num_processes`` are the rank/world_size analogues —
    but per *host*, not per chip: JAX owns all local chips from one
    process (SURVEY.md §2b N9).
    """

    backend: str  # resolved platform: "tpu" | "cpu" | "gpu" | plugin name
    process_id: int
    num_processes: int
    num_devices: int  # global device (chip) count
    local_device_count: int
    coordinator_address: str | None = None

    @property
    def is_main(self) -> bool:
        """True on the process that does filesystem writes and logging.

        The rank-0 role from ``train_ddp.py:204`` (checkpoint save) and
        ``train_ddp.py:201`` (loss logging).
        """
        return self.process_id == 0


_ACTIVE: DistContext | None = None


def setup(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    backend: str | None = None,
    emulate_devices: int | None = None,
) -> DistContext:
    """Bring up the distributed runtime and return its context.

    Parity with ``utils.py:5-14`` ``setup(rank, world_size, backend=None)``:
    ``backend=None`` auto-selects (tpu if present, else cpu) the way the
    reference picks nccl-if-cuda-else-gloo. Multi-host runs pass
    ``coordinator_address``/``num_processes``/``process_id`` (or rely on
    the TPU metadata auto-detection built into jax.distributed).

    ``emulate_devices=N`` forces N virtual CPU devices — the dev-box
    path used by tests and the driver's multi-chip dry run.
    """
    global _ACTIVE

    if backend == "cpu" or emulate_devices is not None:
        force_cpu_backend(emulate_devices)

    import jax

    multi_host = (
        coordinator_address is not None
        or (num_processes is not None and num_processes > 1)
        or os.environ.get("JAX_COORDINATOR_ADDRESS")
    )
    if multi_host:
        # Careful: nothing here may touch the backend (even
        # jax.default_backend() would initialize it, and initialize()
        # refuses to run after that) — decide from the requested
        # backend / platform config only.
        platforms = (
            getattr(jax.config, "jax_platforms", None)
            or os.environ.get("JAX_PLATFORMS")
            or ""
        )
        if backend == "cpu" or platforms.split(",")[0] == "cpu":
            # Multi-process collectives on the CPU backend need the
            # gloo transport; without it every cross-process program
            # dies with "Multiprocess computations aren't implemented
            # on the CPU backend" (XLA's default CPU client). This is
            # the reference's 2-proc gloo quickstart made literal —
            # and what lets the whole multihost test tier (and the
            # --spawn restart loop) run on a dev box. Must be set
            # before initialize(); harmless when already set.
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo"
                )
            except (AttributeError, ValueError):  # older jaxlib
                pass
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )

    devices = jax.devices()
    actual = devices[0].platform
    if backend is not None and backend != actual:
        raise RuntimeError(
            f"requested backend {backend!r} but JAX resolved platform "
            f"{actual!r} — refusing to run on the wrong hardware silently"
        )
    ctx = DistContext(
        backend=actual,
        process_id=jax.process_index(),
        num_processes=jax.process_count(),
        num_devices=len(devices),
        local_device_count=jax.local_device_count(),
        coordinator_address=coordinator_address,
    )
    _ACTIVE = ctx
    # Same observable bring-up line as utils.py:14's
    # "Rank {rank}/{world_size} initialized with backend {backend}".
    logger.info(
        "Process %d/%d initialized with backend %s (%d devices, %d local)",
        ctx.process_id,
        ctx.num_processes,
        ctx.backend,
        ctx.num_devices,
        ctx.local_device_count,
    )
    return ctx


def current() -> DistContext:
    """The active context, creating a single-process one if needed."""
    global _ACTIVE
    if _ACTIVE is None:
        _ACTIVE = setup()
    return _ACTIVE


def cleanup() -> None:
    """Tear down the distributed runtime (utils.py:16-19 parity)."""
    global _ACTIVE
    import jax

    ctx, _ACTIVE = _ACTIVE, None
    if ctx is None:
        return  # already torn down (or never set up) — idempotent
    if ctx.num_processes > 1:
        jax.distributed.shutdown()
    logger.info("Process %d cleanup complete", ctx.process_id)


def sync_global_devices(tag: str) -> None:
    """Host-level barrier — the ``dist.barrier()`` of train_ddp.py:63.

    Only needed for control-plane filesystem races (checkpoint discovery
    after rank-0 writes); data-plane sync is compiled into the step.
    """
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)

"""Deterministic fault injection — drills for the fault-tolerance layer.

Preemptible TPU fleets make failure the common case; a recovery path
that is only exercised by real outages is a recovery path that does
not work. This module turns the ``--health_inject_nan`` precedent into
a general harness: a seeded, *deterministic* schedule of process
kills, SIGTERMs, checkpoint corruption, and input-pipeline stalls that
fire at exact points of a run — usable from the CLI (``--chaos``) and
from tests, and safe to leave in a relaunch loop because every event
fires **once** across restarts (a per-rank ledger file next to the
checkpoints records what already fired; the resumed run replays the
same steps without replaying the faults).

Spec grammar (comma-separated events; see docs/ROBUSTNESS.md)::

    kill:rank<R>@step<N>          SIGKILL rank R before global step N
    kill:rank<R>@epoch<N>         ... at the top of epoch N
    sigterm:rank<R>@step<N>       graceful-preemption signal instead
    sigterm:rank<R>@epoch<N>
    shrink:rank<R>@step<N>        rank R is PERMANENTLY lost (exits
    shrink:rank<R>@epoch<N>       with launch.SHRINK_EXIT_CODE): an
                                  elastic supervisor relaunches the
                                  world one worker smaller — the
                                  scale-down drill
    grow:+1@step<N>               a lost host is restored (rank 0
    grow:+1@epoch<N>              exits launch.GROW_EXIT_CODE): the
                                  elastic supervisor relaunches one
                                  worker larger — the scale-up drill
    stall:input@step<N>:<S>s      sleep S seconds before step N's
                                  dispatch, on every rank (an input-
                                  pipeline stall the straggler sentry
                                  should see)
    ckpt_corrupt:latest           at process start (rank 0, before
                                  restore): truncate the largest file
                                  of the latest checkpoint on disk —
                                  the torn-write drill for the
                                  manifest/quarantine fallback path
    kill:replica<R>@request<N>    FLEET drills (serve/fleet.py): when
    stall:replica<R>@request<N>:<S>s
                                  the fleet router dispatches its Nth
                                  request, the replica MANAGER SIGKILLs
                                  replica R (the mid-traffic death the
                                  router must replay around) or
                                  SIGSTOPs it for S seconds (the
                                  straggling replica hedging should
                                  beat; SIGCONT restores it). Replica
                                  events never fire inside a trainer —
                                  ``ChaosEngine`` skips them; the
                                  fleet manager owns their firing.
    kill:replica<R>@reload        LIFECYCLE drills (serve/lifecycle.py
    ckpt_corrupt:reload           + serve/fleet.py /reloadz): SIGKILL
                                  replica R while ITS hot-swap is in
                                  flight (the mid-swap death the
                                  manager must restart on the
                                  PREVIOUS checkpoint), or corrupt the
                                  INCOMING checkpoint at the start of
                                  a reload so verification must
                                  reject-and-rollback while the old
                                  model keeps serving. Reload events
                                  never fire inside a trainer; the
                                  fleet reload loop owns their firing.
    kill:stage<K>@step<N>         MPMD pipeline drills (parallel/
    stall:stage<K>@step<N>:<S>s   mpmd.py): SIGKILL stage K's process
                                  before its step-N dispatch (the
                                  mid-epoch death the supervisor must
                                  restart from the stage-sliced
                                  checkpoint) or sleep it S seconds
                                  (a straggling stage the bubble
                                  accounting should attribute). Stage
                                  events fire only inside an engine
                                  armed with ``stage=K`` — a trainer
                                  rank or an SPMD run never owns one.

"Step N" means the global optimizer-step counter (which survives
restarts via the checkpoint), checked at the step boundary before the
dispatch that would run step N — so a resumed run re-approaches the
same trigger point deterministically, and the ledger is what stops a
second firing.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
import signal
import time
from typing import Iterable, Sequence

logger = logging.getLogger("ddp_tpu")

KINDS = ("kill", "sigterm", "shrink", "grow", "stall", "ckpt_corrupt")

_EVENT_RE = re.compile(
    r"^(?P<kind>kill|sigterm|shrink)"
    r":rank(?P<rank>\d+)"
    r"@(?P<unit>step|epoch)(?P<at>\d+)$"
)
_GROW_RE = re.compile(
    r"^grow:\+1@(?P<unit>step|epoch)(?P<at>\d+)$"
)
_STALL_RE = re.compile(
    r"^stall:input@(?P<unit>step|epoch)(?P<at>\d+)"
    r":(?P<seconds>\d+(?:\.\d+)?)s$"
)
_CORRUPT_RE = re.compile(r"^ckpt_corrupt:latest$")
# Fleet drills (serve/fleet.py): the trigger point is the router's
# global dispatch counter, not a training step — a serving fleet has
# no step clock, but "the Nth request" is just as deterministic.
_REPLICA_RE = re.compile(
    r"^(?P<kind>kill|stall)"
    r":replica(?P<replica>\d+)"
    r"@request(?P<request>\d+)"
    r"(?::(?P<seconds>\d+(?:\.\d+)?)s)?$"
)
# Lifecycle drills (serve/lifecycle.py): the trigger point is a model
# hot-swap, not a request ordinal — kill the replica whose swap is in
# flight, or corrupt the checkpoint the swap is about to install.
_REPLICA_RELOAD_RE = re.compile(
    r"^kill:replica(?P<replica>\d+)@reload$"
)
_CORRUPT_RELOAD_RE = re.compile(r"^ckpt_corrupt:reload$")
# MPMD pipeline drills (parallel/mpmd.py): the trigger point is the
# pipeline's optimizer-step counter, but the victim is a STAGE process
# — step-only (an MPMD run has no epoch clock).
_STAGE_RE = re.compile(
    r"^(?P<kind>kill|stall)"
    r":stage(?P<stage>\d+)"
    r"@step(?P<at>\d+)"
    r"(?::(?P<seconds>\d+(?:\.\d+)?)s)?$"
)


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """One scheduled fault. ``rank`` None = every rank (stalls);
    ``step``/``epoch`` are mutually exclusive trigger points; kills
    and sigterms name exactly one rank; ``seconds`` is the stall
    duration. ``ckpt_corrupt`` has no trigger point — it fires at
    process start on rank 0, before checkpoint discovery."""

    kind: str
    rank: int | None = None
    step: int | None = None
    epoch: int | None = None
    seconds: float = 0.0
    # Fleet drills: ``replica`` + ``request`` instead of rank +
    # step/epoch. A replica event belongs to the fleet manager
    # (serve/fleet.py), never to a trainer rank.
    replica: int | None = None
    request: int | None = None
    # MPMD drills: ``stage`` + ``step``. A stage event belongs to one
    # pipeline-stage process (parallel/mpmd.py), never to a trainer
    # rank or an SPMD run.
    stage: int | None = None
    # Lifecycle drills: the trigger point is a model hot-swap instead
    # of a request ordinal / step counter. ``kill:replica<R>@reload``
    # sets replica+reload; ``ckpt_corrupt:reload`` sets reload alone
    # (the victim is the INCOMING checkpoint, wherever the reload
    # points). Owned by the fleet reload loop, never a trainer.
    reload: bool = False

    @property
    def token(self) -> str:
        """Canonical spec token (the ledger id; format/parse round-trip)."""
        if self.kind == "ckpt_corrupt":
            return (
                "ckpt_corrupt:reload" if self.reload
                else "ckpt_corrupt:latest"
            )
        if self.replica is not None:
            if self.reload:
                return f"kill:replica{self.replica}@reload"
            base = f"{self.kind}:replica{self.replica}@request{self.request}"
            if self.kind == "stall":
                base += f":{self.seconds:g}s"
            return base
        if self.stage is not None:
            base = f"{self.kind}:stage{self.stage}@step{self.step}"
            if self.kind == "stall":
                base += f":{self.seconds:g}s"
            return base
        at = (
            f"step{self.step}" if self.step is not None
            else f"epoch{self.epoch}"
        )
        if self.kind == "stall":
            return f"stall:input@{at}:{self.seconds:g}s"
        if self.kind == "grow":
            return f"grow:+1@{at}"
        return f"{self.kind}:rank{self.rank}@{at}"


def parse_chaos(spec: str | None) -> tuple[ChaosEvent, ...]:
    """``--chaos`` string → events. Raises ValueError naming the bad
    token; an empty/None spec parses to ()."""
    if not spec or not spec.strip():
        return ()
    events = []
    for token in spec.split(","):
        token = token.strip()
        if not token:
            continue
        m = _EVENT_RE.match(token)
        if m:
            at = int(m.group("at"))
            events.append(
                ChaosEvent(
                    kind=m.group("kind"),
                    rank=int(m.group("rank")),
                    step=at if m.group("unit") == "step" else None,
                    epoch=at if m.group("unit") == "epoch" else None,
                )
            )
            continue
        m = _GROW_RE.match(token)
        if m:
            at = int(m.group("at"))
            events.append(
                ChaosEvent(
                    kind="grow",
                    step=at if m.group("unit") == "step" else None,
                    epoch=at if m.group("unit") == "epoch" else None,
                )
            )
            continue
        m = _STALL_RE.match(token)
        if m:
            at = int(m.group("at"))
            seconds = float(m.group("seconds"))
            if seconds <= 0:
                raise ValueError(
                    f"chaos stall duration must be > 0: {token!r}"
                )
            events.append(
                ChaosEvent(
                    kind="stall",
                    step=at if m.group("unit") == "step" else None,
                    epoch=at if m.group("unit") == "epoch" else None,
                    seconds=seconds,
                )
            )
            continue
        if _CORRUPT_RE.match(token):
            events.append(ChaosEvent(kind="ckpt_corrupt"))
            continue
        if _CORRUPT_RELOAD_RE.match(token):
            events.append(ChaosEvent(kind="ckpt_corrupt", reload=True))
            continue
        m = _REPLICA_RELOAD_RE.match(token)
        if m:
            events.append(
                ChaosEvent(
                    kind="kill",
                    replica=int(m.group("replica")),
                    reload=True,
                )
            )
            continue
        m = _REPLICA_RE.match(token)
        if m:
            kind = m.group("kind")
            seconds = m.group("seconds")
            if kind == "stall":
                if seconds is None:
                    raise ValueError(
                        f"chaos replica stall needs a duration: {token!r}"
                    )
                if float(seconds) <= 0:
                    raise ValueError(
                        f"chaos stall duration must be > 0: {token!r}"
                    )
            elif seconds is not None:
                raise ValueError(
                    f"chaos replica kill takes no duration: {token!r}"
                )
            events.append(
                ChaosEvent(
                    kind=kind,
                    replica=int(m.group("replica")),
                    request=int(m.group("request")),
                    seconds=float(seconds) if seconds else 0.0,
                )
            )
            continue
        m = _STAGE_RE.match(token)
        if m:
            kind = m.group("kind")
            seconds = m.group("seconds")
            if kind == "stall":
                if seconds is None:
                    raise ValueError(
                        f"chaos stage stall needs a duration: {token!r}"
                    )
                if float(seconds) <= 0:
                    raise ValueError(
                        f"chaos stall duration must be > 0: {token!r}"
                    )
            elif seconds is not None:
                raise ValueError(
                    f"chaos stage kill takes no duration: {token!r}"
                )
            events.append(
                ChaosEvent(
                    kind=kind,
                    stage=int(m.group("stage")),
                    step=int(m.group("at")),
                    seconds=float(seconds) if seconds else 0.0,
                )
            )
            continue
        raise ValueError(
            f"bad chaos event {token!r}; grammar: "
            "kill:rank<R>@step<N>|epoch<N>, "
            "sigterm:rank<R>@step<N>|epoch<N>, "
            "shrink:rank<R>@step<N>|epoch<N>, grow:+1@step<N>|epoch<N>, "
            "stall:input@step<N>|epoch<N>:<S>s, ckpt_corrupt:latest, "
            "kill:replica<R>@request<N>, "
            "stall:replica<R>@request<N>:<S>s, "
            "kill:replica<R>@reload, ckpt_corrupt:reload, "
            "kill:stage<K>@step<N>, stall:stage<K>@step<N>:<S>s"
        )
    return tuple(events)


def format_chaos(events: Iterable[ChaosEvent]) -> str:
    """Events → canonical spec string (``parse_chaos`` round-trips)."""
    return ",".join(e.token for e in events)


def fleet_events(
    events: Iterable[ChaosEvent] | str | None,
) -> tuple[ChaosEvent, ...]:
    """The replica-scoped subset of a plan — what the fleet manager
    (serve/fleet.py) owns. Accepts a spec string for CLI plumbing."""
    if isinstance(events, str) or events is None:
        events = parse_chaos(events)
    return tuple(e for e in events if e.replica is not None)


def reload_events(
    events: Iterable[ChaosEvent] | str | None,
) -> tuple[ChaosEvent, ...]:
    """The reload-scoped subset of a plan — what the fleet's hot-swap
    loop (serve/fleet.py /reloadz) owns. Accepts a spec string."""
    if isinstance(events, str) or events is None:
        events = parse_chaos(events)
    return tuple(e for e in events if e.reload)


def stage_events(
    events: Iterable[ChaosEvent] | str | None,
) -> tuple[ChaosEvent, ...]:
    """The stage-scoped subset of a plan — what the MPMD pipeline
    supervisor (parallel/mpmd.py) owns. Accepts a spec string."""
    if isinstance(events, str) or events is None:
        events = parse_chaos(events)
    return tuple(e for e in events if e.stage is not None)


def corrupt_latest_checkpoint(
    directory: str, *, seed: int = 0
) -> str | None:
    """Truncate the largest file of the latest committed checkpoint.

    The deterministic torn-write drill: picks ``epoch_<max>`` under
    ``directory``, then its largest file (ties broken by path, so the
    choice is stable), and truncates it to half its size — exactly the
    artifact a mid-write kill leaves. Returns the corrupted path, or
    None when there is nothing to corrupt. ``seed`` varies WHERE the
    truncation lands (half ± a seeded offset) without changing which
    file is hit.
    """
    try:
        epochs = [
            (int(m.group(1)), name)
            for name in os.listdir(directory)
            for m in [re.match(r"^epoch_(\d+)$", name)]
            if m and os.path.isdir(os.path.join(directory, name))
        ]
    except OSError:
        return None
    if not epochs:
        return None
    _, latest = max(epochs)
    step_dir = os.path.join(directory, latest)
    files = []
    for root, _, names in os.walk(step_dir):
        for n in names:
            p = os.path.join(root, n)
            try:
                files.append((os.path.getsize(p), p))
            except OSError:
                continue
    if not files:
        return None
    size, victim = max(files, key=lambda t: (t[0], t[1]))
    # Half the file ± up to 25%, seeded — never 0 (an empty file is a
    # different failure than a torn one) unless the file was tiny.
    cut = max(1, size // 2 + (seed % max(1, size // 4)) - size // 8)
    cut = min(cut, max(0, size - 1))
    with open(victim, "r+b") as f:
        f.truncate(cut)
    logger.warning(
        "chaos: truncated %s from %d to %d bytes", victim, size, cut
    )
    return victim


class ChaosEngine:
    """Arms a rank's share of a chaos plan and fires events once.

    The trainer calls ``on_start`` (before checkpoint discovery),
    ``on_epoch`` (top of each epoch) and ``on_step`` (each step
    boundary, with the global step counter). Events that already fired
    — in this process or a previous incarnation, per the ledger — are
    skipped, which is what makes ``--chaos kill:...`` + a restart loop
    terminate instead of crash-looping.
    """

    def __init__(
        self,
        events: Sequence[ChaosEvent] | str | None,
        *,
        rank: int = 0,
        stage: int | None = None,
        ledger_path: str | None = None,
        seed: int = 0,
    ):
        if isinstance(events, str) or events is None:
            events = parse_chaos(events)
        self.events = tuple(events)
        self.rank = int(rank)
        self.stage = None if stage is None else int(stage)
        self.seed = int(seed)
        self._ledger_path = ledger_path
        self._fired: set[str] | None = None  # lazy ledger load

    @property
    def enabled(self) -> bool:
        return bool(self.events)

    def has_step_events(self) -> bool:
        return any(e.step is not None for e in self.events)

    # ---- ledger ------------------------------------------------------

    def _load_ledger(self) -> set[str]:
        if self._fired is not None:
            return self._fired
        fired: set[str] = set()
        if self._ledger_path:
            try:
                with open(self._ledger_path) as f:
                    data = json.load(f)
                fired = set(data.get("fired", []))
            except (OSError, ValueError):
                fired = set()
        self._fired = fired
        return fired

    def _mark_fired(self, ev: ChaosEvent) -> None:
        fired = self._load_ledger()
        fired.add(ev.token)
        if not self._ledger_path:
            return
        # Durable BEFORE the fault lands: a kill must not forget it
        # fired, or the relaunched run kills itself at the same step
        # forever. Atomic (tmp+replace) like every sidecar here.
        try:
            os.makedirs(
                os.path.dirname(os.path.abspath(self._ledger_path)),
                exist_ok=True,
            )
            tmp = f"{self._ledger_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"fired": sorted(fired)}, f)
            os.replace(tmp, self._ledger_path)
        except OSError as e:  # unwritable dir: fire anyway, log why
            logger.warning("chaos ledger write failed: %s", e)

    # ---- trigger points ----------------------------------------------

    def _mine(self, ev: ChaosEvent) -> bool:
        if ev.reload:
            # Lifecycle events (kill:replica<R>@reload,
            # ckpt_corrupt:reload) fire from the fleet's hot-swap loop
            # — a trainer rank never owns one (in particular rank 0's
            # on_start must NOT corrupt a checkpoint that only a
            # reload is supposed to see corrupted).
            return False
        if ev.replica is not None:
            # Fleet events (kill:replica<R>@request<N>) fire from the
            # replica MANAGER's dispatch counter (serve/fleet.py) —
            # a trainer rank never owns one.
            return False
        if ev.stage is not None:
            # Stage events (kill:stage<K>@step<N>) belong to one MPMD
            # stage process; an engine armed without ``stage`` (any
            # trainer rank, any SPMD run) rejects them outright.
            return self.stage is not None and ev.stage == self.stage
        if ev.kind in ("ckpt_corrupt", "grow"):
            # one filesystem, one corruptor; one world, one grow
            # requester (any single rank works — rank 0 is the
            # convention every other singleton here uses)
            return self.rank == 0
        return ev.rank is None or ev.rank == self.rank

    def _fire(self, ev: ChaosEvent, checkpoint_dir: str | None = None) -> None:
        self._mark_fired(ev)
        logger.warning("chaos: firing %s (rank %d)", ev.token, self.rank)
        if ev.kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        elif ev.kind == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
        elif ev.kind in ("shrink", "grow"):
            # The elastic resize contract rides the exit code
            # (runtime/launch.py). os._exit, not sys.exit: a reclaimed
            # host runs no cleanup — and a SystemExit would be caught
            # by the trainer's post-mortem machinery as an exception.
            from ddp_tpu.runtime.launch import (
                GROW_EXIT_CODE,
                SHRINK_EXIT_CODE,
            )

            os._exit(
                SHRINK_EXIT_CODE if ev.kind == "shrink" else GROW_EXIT_CODE
            )
        elif ev.kind == "stall":
            time.sleep(ev.seconds)
        elif ev.kind == "ckpt_corrupt" and checkpoint_dir:
            corrupt_latest_checkpoint(checkpoint_dir, seed=self.seed)

    def _pending(self) -> Iterable[ChaosEvent]:
        fired = self._load_ledger()
        return (
            e for e in self.events
            if self._mine(e) and e.token not in fired
        )

    def on_start(self, checkpoint_dir: str | None) -> None:
        """Process-start events (``ckpt_corrupt``) — call BEFORE
        checkpoint discovery/restore."""
        if not self.events:
            return
        for ev in list(self._pending()):
            if ev.kind == "ckpt_corrupt":
                self._fire(ev, checkpoint_dir=checkpoint_dir)

    def on_epoch(self, epoch: int) -> None:
        if not self.events:
            return
        for ev in list(self._pending()):
            if ev.epoch is not None and ev.epoch == epoch:
                self._fire(ev)

    def on_step(self, step: int) -> None:
        """``step`` = the global optimizer step the NEXT dispatch will
        run (the counter restored from checkpoints, so trigger points
        survive restarts). Chaos-off is free: the guard is one tuple
        truthiness test in the hot loop."""
        if not self.events:
            return
        for ev in list(self._pending()):
            if ev.step is not None and ev.step == step:
                self._fire(ev)

"""Single-host multi-process launcher — ``torch.multiprocessing.spawn`` parity.

The reference launches its 2 ranks with ``torch.multiprocessing.spawn(
ddp_train, args=(world_size, epochs, batch_size), nprocs=world_size)``
(train_ddp.py:222-224). The JAX production launch is one process per
*host* (SURVEY.md §2b N9) rendezvoused by ``jax.distributed``; this
launcher reproduces the reference's dev-box experience on top of that —
N local processes, each a ``jax.distributed`` participant with its own
emulated CPU device(s) and a localhost coordinator — which is also how
multi-host code paths are tested without a cluster (SURVEY.md §4: the
TPU analogue of "2-proc gloo on a laptop").

Workers must be module-level callables ``fn(rank, world_size, *args)``
(the reference's ``ddp_train`` signature). They are resolved by source
file + qualified name in the child, so functions from test modules and
scripts work even when those modules aren't importable by package name.

Restart-with-resume (``max_restarts > 0``): when any rank dies, the
parent classifies the exit (signal vs exception vs the watchdog's
124), reaps the WHOLE world — survivors are typically blocked in a
collective waiting for the dead peer and would hang forever — and
relaunches every rank on a fresh coordinator port after a bounded
exponential backoff. Recovery itself is the workers' job: the trainer
auto-resumes from the latest checkpoint, and its ``goodput.json``
sidecar counts the relaunch as a restart, so goodput accounting
reflects the crash loop's true cost (obs/goodput.py).

Elastic supervision (``elastic=True``): restart-with-resume assumes
the world that restarts is the world that died; preemptible fleets
don't. A rank that exits with ``SHRINK_EXIT_CODE`` declares itself
PERMANENTLY gone (a reclaimed host), and instead of burning the
restart budget re-spawning a rank that will never come back, the
supervisor relaunches the next generation one worker smaller — down
to ``min_world``. ``GROW_EXIT_CODE`` is the inverse signal (a lost
host restored): the next generation is one worker larger, capped at
the original ``nprocs``. Resize generations do NOT consume
``max_restarts`` — they are accounted separately (``events_out``)
and the workers' goodput sidecar attributes their downtime as
*resize* downtime, distinct from restart downtime. Workers re-derive
everything world-shaped on relaunch: the mesh from the live device
count (runtime/mesh.live_world_spec), the per-process batch from the
``elastic.json`` global-batch contract (data/sampler.py shard math),
and sharded checkpoint state by resharding — or, for ZeRO's padded
flat buckets, re-bucketing — on restore (train/checkpoint.py).
"""

from __future__ import annotations

import importlib
import importlib.util
import logging
import multiprocessing
import os
import signal as _signal
import socket
import sys
import time
from typing import Callable, Sequence

logger = logging.getLogger("ddp_tpu")

# Exit code the step watchdog uses for its os._exit on hang
# (utils/watchdog.py) — a hang converted into a classifiable crash.
WATCHDOG_EXIT_CODE = 124

# Elastic resize contract between workers and the supervisor, carried
# in the only channel a dead process has — its exit code. A rank
# exiting SHRINK declares itself permanently lost (the supervisor
# relaunches the world one smaller); GROW requests the inverse (one
# larger, capped at the original size). runtime/chaos.py's
# ``shrink:rankN@step``/``grow:+1@epoch`` faults exit with these, so
# the whole scale-down/scale-up path is drillable like every other
# recovery path. Chosen clear of the meaningful small codes (1
# exception, 124 watchdog) and of 128+signal.
SHRINK_EXIT_CODE = 86
GROW_EXIT_CODE = 87


def classify_exit(exitcode: int | None) -> str:
    """Human-readable failure class for a dead worker's exit code.

    multiprocessing reports death-by-signal as a NEGATIVE code;
    ``WATCHDOG_EXIT_CODE`` is the step watchdog's hang conversion;
    anything else is an uncaught exception (Python exits 1).
    """
    if exitcode is None:
        return "unknown"
    if exitcode < 0:
        try:
            name = _signal.Signals(-exitcode).name
        except ValueError:
            name = f"signal {-exitcode}"
        return f"killed by {name}"
    if exitcode == WATCHDOG_EXIT_CODE:
        return "watchdog timeout (hang converted to exit 124)"
    if exitcode == SHRINK_EXIT_CODE:
        return "permanently lost (elastic shrink, exit 86)"
    if exitcode == GROW_EXIT_CODE:
        return "scale-up requested (elastic grow, exit 87)"
    return f"exception (exit {exitcode})"


def free_port() -> int:
    """An OS-assigned free TCP port (the coordinator's MASTER_PORT role)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _resolve(src_file: str, module_name: str, qualname: str) -> Callable:
    try:
        mod = importlib.import_module(module_name)
    except ImportError:
        spec = importlib.util.spec_from_file_location(module_name, src_file)
        if spec is None or spec.loader is None:
            raise
        mod = importlib.util.module_from_spec(spec)
        sys.modules[module_name] = mod
        spec.loader.exec_module(mod)
    obj = mod
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj


def _child_main(
    src_file: str,
    module_name: str,
    qualname: str,
    rank: int,
    world_size: int,
    port: int,
    devices_per_process: int,
    args: tuple,
) -> None:
    # Platform must be pinned before any JAX backend initializes in the
    # fresh ('spawn') interpreter. dist imports jax lazily, so using its
    # flag helper here is safe.
    os.environ["JAX_PLATFORMS"] = "cpu"
    from ddp_tpu.runtime import dist

    dist._ensure_host_device_count(devices_per_process)

    dist.setup(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=world_size,
        process_id=rank,
        backend="cpu",
    )
    # Per-rank span tracing (ddp_tpu.obs): when the parent exported
    # DDP_TPU_TRACE_DIR (inherited by this 'spawn' child), the global
    # tracer turns on with pid=rank and registers an atexit export, so
    # every rank leaves trace_rank{N}.trace.json for
    # scripts/trace_merge.py — with zero changes to worker signatures.
    from ddp_tpu.obs.tracer import get_tracer, install_from_env

    install_from_env(process_id=rank)
    fn = _resolve(src_file, module_name, qualname)
    try:
        with get_tracer().span("worker_main", {"rank": rank}):
            fn(rank, world_size, *args)
    finally:
        dist.cleanup()


def _reap_world(procs: list, grace: float) -> None:
    """Terminate every surviving rank: SIGTERM (the trainer's graceful
    preemption path — a healthy survivor checkpoints and exits clean),
    ``grace`` seconds to comply, then SIGKILL for ranks wedged in a
    collective whose peer is already dead (C-level blocks never run the
    Python signal handler). No rank may outlive its world: a leaked
    survivor would hold the coordinator port and the next generation's
    rendezvous would join a half-dead gang."""
    for p in procs:
        if p.is_alive():
            p.terminate()
    deadline = time.monotonic() + grace
    for p in procs:
        p.join(max(0.5, deadline - time.monotonic()))
    for p in procs:
        if p.is_alive():
            p.kill()
            p.join(10)


def spawn(
    fn: Callable,
    nprocs: int,
    args: Sequence = (),
    *,
    devices_per_process: int = 1,
    coordinator_port: int | None = None,
    timeout: float | None = 600.0,
    grace: float = 15.0,
    max_restarts: int = 0,
    restart_backoff: float = 1.0,
    elastic: bool = False,
    min_world: int = 1,
    events_out: list | None = None,
) -> int:
    """Run ``fn(rank, world_size, *args)`` in ``nprocs`` processes.

    Same contract as the reference's launcher (spawn prepends the rank,
    train_ddp.py:222-224) with the c10d env:// rendezvous replaced by a
    localhost ``jax.distributed`` coordinator. Blocks until every rank
    exits (``timeout=None`` waits forever; a finite ``timeout`` spans
    ALL generations). Fails fast: the first rank to die with a non-zero
    exit code is reported as the culprit, and surviving ranks —
    typically blocked in a collective waiting for the dead one, the
    reference's hang failure mode (SURVEY.md §5) — get ``grace``
    seconds to exit before being terminated (then killed).

    ``max_restarts > 0`` adds restart-with-resume: after a failed
    generation the whole world is reaped and relaunched (fresh
    coordinator port — the dead coordinator's socket may linger) with
    exponential backoff ``restart_backoff * 2^i`` seconds (capped at
    30 s). Workers are responsible for resuming from their own durable
    state; the trainer's latest-checkpoint auto-resume makes the
    combination an automatic kill-and-recover loop. Returns the number
    of restarts consumed. The overall ``timeout`` is never restarted.

    ``elastic=True`` adds world RESIZE on top: a generation whose only
    failures are ``SHRINK_EXIT_CODE``/``GROW_EXIT_CODE`` exits is not a
    crash — it is a topology change. The next generation launches with
    ``world - shrinks + grows`` workers (never above ``nprocs``, never
    below ``min_world`` — below raises), on a fresh coordinator port
    after a single ``restart_backoff`` beat (no exponential growth: a
    resize is not a crash loop). Resizes do NOT consume
    ``max_restarts``; mixed generations (a shrink plus an unrelated
    crash) count as a resize — the crash is usually the reaped
    survivor's collateral. ``events_out`` (a caller-provided list)
    receives one dict per restart/resize so tests and bench can audit
    the world-size trajectory without parsing worker logs.
    """
    import inspect

    if min_world < 1:
        raise ValueError(f"min_world must be >= 1, got {min_world}")
    if min_world > nprocs:
        raise ValueError(
            f"min_world {min_world} exceeds the launched world {nprocs}"
        )
    src_file = os.path.abspath(inspect.getfile(fn))
    ctx = multiprocessing.get_context("spawn")
    deadline = None if timeout is None else time.monotonic() + timeout
    restarts = 0
    resizes = 0
    world = nprocs
    # Runaway-resize backstop: chaos events fire once (their ledgers),
    # but a worker that UNCONDITIONALLY exits SHRINK/GROW would
    # otherwise bounce the supervisor forever without ever touching
    # the restart budget.
    max_resizes = 2 * nprocs + 8
    while True:
        # An explicit coordinator_port only pins generation 0: the
        # dead coordinator's socket may linger (TIME_WAIT) and a
        # relaunch binding the same port would burn every restart on
        # the rendezvous instead of the workload.
        port = (
            coordinator_port
            if coordinator_port and restarts == 0 and resizes == 0
            else free_port()
        )
        procs = [
            ctx.Process(
                target=_child_main,
                args=(
                    src_file,
                    fn.__module__,
                    fn.__qualname__,
                    rank,
                    world,
                    port,
                    devices_per_process,
                    tuple(args),
                ),
                daemon=False,
            )
            for rank in range(world)
        ]
        for p in procs:
            p.start()
        bad: dict[int, int] = {}
        try:
            while True:
                exited = {
                    r: p.exitcode
                    for r, p in enumerate(procs)
                    if not p.is_alive()
                }
                bad = {r: c for r, c in exited.items() if c != 0}
                if bad:
                    # Give blocked survivors a moment to exit on their
                    # own before the reap, so the report names the
                    # actual failure rather than a survivor's kill.
                    grace_end = time.monotonic() + grace
                    for p in procs:
                        p.join(max(0.0, grace_end - time.monotonic()))
                    # Re-collect AFTER the grace joins: a staggered
                    # SHRINK/GROW exit landing during the window must
                    # be classified (an elastic shrink misread as a
                    # plain crash would burn the restart budget — or
                    # fail the run — for a rank that is simply gone).
                    # Still-alive survivors are reaped in the finally
                    # below, AFTER this snapshot, so their kill codes
                    # never pollute the classification.
                    bad = {
                        r: p.exitcode
                        for r, p in enumerate(procs)
                        if not p.is_alive() and p.exitcode != 0
                    }
                    break
                if len(exited) == world:
                    return restarts
                if deadline is not None and time.monotonic() > deadline:
                    alive = [r for r, p in enumerate(procs) if p.is_alive()]
                    raise RuntimeError(
                        f"ranks {alive} still running after {timeout}s"
                        + (f" ({restarts} restart(s))" if restarts else "")
                    )
                time.sleep(0.2)
        finally:
            _reap_world(procs, grace)
        classified = {
            r: classify_exit(c) for r, c in sorted(bad.items())
        }
        shrinks = sorted(r for r, c in bad.items() if c == SHRINK_EXIT_CODE)
        grows = sorted(r for r, c in bad.items() if c == GROW_EXIT_CODE)
        resize = elastic and (shrinks or grows)
        if resize:
            new_world = min(nprocs, world - len(shrinks) + len(grows))
            if new_world < min_world:
                raise RuntimeError(
                    f"elastic resize would shrink the world to "
                    f"{new_world} (< min_world {min_world}): "
                    + "; ".join(
                        f"rank {r}: {why}"
                        for r, why in classified.items()
                    )
                )
            resizes += 1
            if resizes > max_resizes:
                raise RuntimeError(
                    f"{resizes} elastic resizes without completing — a "
                    "worker is exiting SHRINK/GROW unconditionally "
                    f"(last: {bad})"
                )
            backoff = max(0.0, min(30.0, restart_backoff))
            # A grow capped at the original size (or shrink+grow
            # cancelling out) still reaped the world — the relaunch is
            # mandatory — but topologically it is a SAME-SIZE restart:
            # report it as one so the supervisor's event stream and the
            # workers' goodput attribution (keyed on the world delta)
            # agree about the boundary. It stays in the elastic branch
            # (no restart budget: the workers asked for this exit).
            logger.warning(
                "launch: elastic %s %d -> %d workers (%s) — "
                "relaunching in %.1fs",
                "resize" if new_world != world else "grow capped",
                world,
                new_world,
                "; ".join(
                    f"rank {r}: {why}" for r, why in classified.items()
                ),
                backoff,
            )
            if events_out is not None:
                events_out.append(
                    {
                        "kind": (
                            "resize" if new_world != world else "restart"
                        ),
                        "old_world": world,
                        "new_world": new_world,
                        "shrunk_ranks": shrinks,
                        "grew": len(grows),
                        "time": time.time(),
                    }
                )
            world = new_world
        else:
            if restarts >= max_restarts:
                raise RuntimeError(
                    f"worker failures (rank: exitcode): {bad} — "
                    + "; ".join(
                        f"rank {r}: {why}" for r, why in classified.items()
                    )
                    + (
                        f"; {restarts}/{max_restarts} restarts exhausted"
                        if max_restarts
                        else ""
                    )
                )
            backoff = min(30.0, restart_backoff * (2.0 ** restarts))
            restarts += 1
            logger.warning(
                "launch: generation failed (%s) — restart %d/%d in %.1fs",
                "; ".join(
                    f"rank {r}: {why}" for r, why in classified.items()
                ),
                restarts,
                max_restarts,
                backoff,
            )
            if events_out is not None:
                events_out.append(
                    {
                        "kind": "restart",
                        "old_world": world,
                        "new_world": world,
                        "failures": dict(bad),
                        "time": time.time(),
                    }
                )
        if deadline is not None and time.monotonic() + backoff > deadline:
            raise RuntimeError(
                f"worker failures (rank: exitcode): {bad}; no budget "
                f"left to restart (timeout {timeout}s)"
            )
        time.sleep(backoff)

"""Single-host multi-process launcher — ``torch.multiprocessing.spawn`` parity.

The reference launches its 2 ranks with ``torch.multiprocessing.spawn(
ddp_train, args=(world_size, epochs, batch_size), nprocs=world_size)``
(train_ddp.py:222-224). The JAX production launch is one process per
*host* (SURVEY.md §2b N9) rendezvoused by ``jax.distributed``; this
launcher reproduces the reference's dev-box experience on top of that —
N local processes, each a ``jax.distributed`` participant with its own
emulated CPU device(s) and a localhost coordinator — which is also how
multi-host code paths are tested without a cluster (SURVEY.md §4: the
TPU analogue of "2-proc gloo on a laptop").

Workers must be module-level callables ``fn(rank, world_size, *args)``
(the reference's ``ddp_train`` signature). They are resolved by source
file + qualified name in the child, so functions from test modules and
scripts work even when those modules aren't importable by package name.
"""

from __future__ import annotations

import importlib
import importlib.util
import multiprocessing
import os
import socket
import sys
import time
from typing import Callable, Sequence


def free_port() -> int:
    """An OS-assigned free TCP port (the coordinator's MASTER_PORT role)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _resolve(src_file: str, module_name: str, qualname: str) -> Callable:
    try:
        mod = importlib.import_module(module_name)
    except ImportError:
        spec = importlib.util.spec_from_file_location(module_name, src_file)
        if spec is None or spec.loader is None:
            raise
        mod = importlib.util.module_from_spec(spec)
        sys.modules[module_name] = mod
        spec.loader.exec_module(mod)
    obj = mod
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj


def _child_main(
    src_file: str,
    module_name: str,
    qualname: str,
    rank: int,
    world_size: int,
    port: int,
    devices_per_process: int,
    args: tuple,
) -> None:
    # Platform must be pinned before any JAX backend initializes in the
    # fresh ('spawn') interpreter. dist imports jax lazily, so using its
    # flag helper here is safe.
    os.environ["JAX_PLATFORMS"] = "cpu"
    from ddp_tpu.runtime import dist

    dist._ensure_host_device_count(devices_per_process)

    dist.setup(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=world_size,
        process_id=rank,
        backend="cpu",
    )
    # Per-rank span tracing (ddp_tpu.obs): when the parent exported
    # DDP_TPU_TRACE_DIR (inherited by this 'spawn' child), the global
    # tracer turns on with pid=rank and registers an atexit export, so
    # every rank leaves trace_rank{N}.trace.json for
    # scripts/trace_merge.py — with zero changes to worker signatures.
    from ddp_tpu.obs.tracer import get_tracer, install_from_env

    install_from_env(process_id=rank)
    fn = _resolve(src_file, module_name, qualname)
    try:
        with get_tracer().span("worker_main", {"rank": rank}):
            fn(rank, world_size, *args)
    finally:
        dist.cleanup()


def spawn(
    fn: Callable,
    nprocs: int,
    args: Sequence = (),
    *,
    devices_per_process: int = 1,
    coordinator_port: int | None = None,
    timeout: float | None = 600.0,
    grace: float = 15.0,
) -> None:
    """Run ``fn(rank, world_size, *args)`` in ``nprocs`` processes.

    Same contract as the reference's launcher (spawn prepends the rank,
    train_ddp.py:222-224) with the c10d env:// rendezvous replaced by a
    localhost ``jax.distributed`` coordinator. Blocks until every rank
    exits (``timeout=None`` waits forever). Fails fast: the first rank
    to die with a non-zero exit code is reported as the culprit, and
    surviving ranks — typically blocked in a collective waiting for the
    dead one, the reference's hang failure mode (SURVEY.md §5) — get
    ``grace`` seconds to exit before being terminated.
    """
    import inspect

    src_file = os.path.abspath(inspect.getfile(fn))
    port = coordinator_port or free_port()
    ctx = multiprocessing.get_context("spawn")
    procs = [
        ctx.Process(
            target=_child_main,
            args=(
                src_file,
                fn.__module__,
                fn.__qualname__,
                rank,
                nprocs,
                port,
                devices_per_process,
                tuple(args),
            ),
            daemon=False,
        )
        for rank in range(nprocs)
    ]
    for p in procs:
        p.start()
    deadline = None if timeout is None else time.monotonic() + timeout
    try:
        while True:
            exited = {r: p.exitcode for r, p in enumerate(procs) if not p.is_alive()}
            bad = {r: c for r, c in exited.items() if c != 0}
            if bad:
                # Give blocked survivors a moment, then report the
                # actual failure rather than a survivor's timeout.
                grace_end = time.monotonic() + grace
                for p in procs:
                    p.join(max(0.0, grace_end - time.monotonic()))
                raise RuntimeError(f"worker failures (rank: exitcode): {bad}")
            if len(exited) == nprocs:
                return
            if deadline is not None and time.monotonic() > deadline:
                alive = [r for r, p in enumerate(procs) if p.is_alive()]
                raise RuntimeError(
                    f"ranks {alive} still running after {timeout}s"
                )
            time.sleep(0.2)
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
                p.join(10)

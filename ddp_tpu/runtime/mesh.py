"""Device-mesh construction — the scale-out geometry of the framework.

The reference's only geometry is a flat rank list (world_size processes,
c10d communicator over all of them). The TPU-native shape is an N-D
``jax.sharding.Mesh`` whose axes name the parallelism strategies; XLA
lowers collectives onto ICI (intra-slice) / DCN (inter-slice) from axis
placement alone.

Axis vocabulary (fixed across the framework):

- ``dcn``    — the SLICE axis of a multi-slice pod: groups of chips
               joined by the slow inter-slice data-center network
               rather than ICI. Outermost by construction, so the
               flattened device order keeps each slice's chips in one
               contiguous block (replica-group ids stay slice-local —
               what the HLO comm cross-check keys on). A data axis for
               batch sharding; the hierarchical zero step reduces
               within a slice over ICI and exchanges only 1/N shards
               across slices over this axis (PAPERS.md #5).
- ``data``   — data parallelism: batch sharded, params replicated,
               gradient all-reduce (the reference's entire capability,
               SURVEY.md §2c).
- ``fsdp``   — parameter/optimizer sharding (ZeRO-style) on top of data
               parallelism.
- ``expert`` — expert parallelism: MoE expert weights shard their
               leading (expert) dim; tokens shard their batch dim over
               this axis too, so it doubles as a data axis for dense
               layers (GShard-style).
- ``model``  — tensor parallelism within layers.
- ``seq``    — sequence/context parallelism (ring attention).
- ``pipe``   — pipeline stages.

A 1-D ``('data',)`` mesh over all chips reproduces DDP exactly; the
other axes exist so the same train step scales without restructuring.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Mapping, Sequence

import numpy as np

AXIS_ORDER = ("dcn", "pipe", "data", "fsdp", "expert", "seq", "model")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape; -1 on at most one axis means "all the rest".

    ``MeshSpec()`` (all defaults) is pure DDP: every device on ``data``.
    """

    data: int = -1
    fsdp: int = 1
    expert: int = 1
    model: int = 1
    seq: int = 1
    pipe: int = 1
    dcn: int = 1

    def resolve(self, num_devices: int) -> dict[str, int]:
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        bad = {a: s for a, s in sizes.items() if s < 1 and s != -1}
        if bad:
            raise ValueError(
                f"mesh axis sizes must be ≥ 1 (or -1 for 'the rest'): {bad}"
            )
        unknown = [a for a, s in sizes.items() if s == -1]
        if len(unknown) > 1:
            raise ValueError(f"at most one -1 axis, got {unknown}")
        known = math.prod(s for s in sizes.values() if s != -1)
        if unknown:
            if num_devices % known:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes {sizes}"
                )
            sizes[unknown[0]] = num_devices // known
        elif known != num_devices:
            raise ValueError(f"mesh {sizes} needs {known} devices, have {num_devices}")
        return sizes


def detect_slices(devices: Sequence) -> int:
    """Number of distinct pod slices among ``devices``.

    Real multi-slice TPU pods expose ``slice_index`` per device; CPU
    emulation (and single-slice pods) have none, which reads as one
    slice. The ``--mesh_dcn`` flag stays explicit — this is the
    auto-detection input, not a policy.
    """
    idx = {getattr(d, "slice_index", None) for d in devices}
    idx.discard(None)
    return max(1, len(idx))


def _slice_major(devices: Sequence, n_slices: int) -> list:
    """Order devices so equal-slice groups are contiguous (the ``dcn``
    axis is outermost, so a plain reshape then maps each slice to one
    dcn index). Real pods group by ``slice_index``; emulated worlds
    (multi-process gloo "slices") group by process — jax device order
    is already process-major there, so the sort is stable either way.

    When the devices carry real ``slice_index`` info it must AGREE
    with ``n_slices``: on (say) a 4-slice pod, ``--mesh_dcn 2`` would
    silently build a mesh whose "ICI" axis spans two real slices —
    the heavy within-slice collectives would ride the slow fabric and
    the ici/dcn attribution would report them on the wrong side. That
    misconfiguration is rejected with both numbers named; uneven
    slices are rejected too.
    """
    detected = detect_slices(devices)
    # slice_index is AUTHORITATIVE on real accelerators (a single-
    # slice pod genuinely has one slice — asking for --mesh_dcn 2
    # there would stamp within-slice ICI traffic as DCN, rejected
    # below). CPU devices report the same degenerate one-slice shape
    # but mean "no fabric at all": there the flag is an EMULATION and
    # slices group by process instead.
    has_slice_info = any(
        getattr(d, "slice_index", None) is not None for d in devices
    )
    real_slices = has_slice_info and (
        detected > 1 or getattr(devices[0], "platform", "cpu") != "cpu"
    )
    if real_slices and detected != n_slices:
        raise ValueError(
            f"--mesh_dcn {n_slices} but the devices report {detected} "
            "slice(s) (slice_index) — the dcn axis must match the "
            "physical slice count or the within-slice collectives "
            "silently cross the slow fabric"
        )
    order = sorted(
        range(len(devices)),
        key=lambda i: (
            getattr(devices[i], "slice_index", None)
            if real_slices
            and getattr(devices[i], "slice_index", None) is not None
            else getattr(devices[i], "process_index", 0),
            i,
        ),
    )
    devs = [devices[i] for i in order]
    per = len(devs) // n_slices
    if real_slices:
        counts: dict = {}
        for d in devs:
            k = getattr(d, "slice_index", 0)
            counts[k] = counts.get(k, 0) + 1
        if len(set(counts.values())) > 1 or per * n_slices != len(devs):
            raise ValueError(
                f"--mesh_dcn {n_slices}: device slices are uneven "
                f"({counts}) — every slice must contribute the same "
                "chip count"
            )
    else:
        # Emulated slices group by process. A slice may span WHOLE
        # processes (a real slice holds many hosts), but a single
        # process split ACROSS slice blocks would put "within-slice"
        # collectives on the very process boundary the emulation calls
        # DCN — the math would still be right, the ici/dcn attribution
        # (records, per-axis xprof check, the hier bench claim) wrong.
        procs = [getattr(d, "process_index", 0) for d in devs]
        if len(set(procs)) > 1:
            for p in set(procs):
                blocks = {
                    i // per
                    for i, proc in enumerate(procs)
                    if proc == p
                }
                if len(blocks) > 1:
                    raise ValueError(
                        f"--mesh_dcn {n_slices}: process {p}'s devices "
                        f"span emulated slice blocks ({len(devs)} "
                        f"devices / {len(set(procs))} processes do not "
                        f"tile {n_slices} slices) — each process must "
                        "sit inside one slice; adjust --spawn/"
                        "--emulate_devices or --mesh_dcn"
                    )
    return devs


def make_mesh(
    spec: MeshSpec | Mapping[str, int] | None = None,
    *,
    devices: Sequence | None = None,
):
    """Build a ``jax.sharding.Mesh`` from a logical spec.

    Uses ``mesh_utils.create_device_mesh`` when possible so axis order
    maps onto the physical ICI torus (innermost axes get the
    fastest-varying/nearest chips); falls back to a plain reshape for
    emulated CPU devices. A ``dcn`` axis > 1 orders devices slice-major
    first (``slice_index`` on real pods, process on emulated worlds) so
    the outermost axis genuinely separates the DCN fabric.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if spec is None:
        spec = MeshSpec()
    elif isinstance(spec, Mapping):
        spec = MeshSpec(**dict(spec))
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXIS_ORDER)

    if sizes["dcn"] > 1:
        devices = _slice_major(devices, sizes["dcn"])
        per = len(devices) // sizes["dcn"]
        if devices[0].platform == "tpu":
            try:
                from jax.experimental import mesh_utils

                # Torus-aware layout per slice, stacked along dcn —
                # ICI adjacency is a within-slice property.
                mesh_devices = np.stack(
                    [
                        mesh_utils.create_device_mesh(
                            shape[1:], devices=devices[i * per : (i + 1) * per]
                        )
                        for i in range(sizes["dcn"])
                    ]
                )
                return Mesh(mesh_devices, AXIS_ORDER)
            except Exception:  # non-standard topology: plain reshape
                pass
        return Mesh(np.asarray(devices).reshape(shape), AXIS_ORDER)

    if devices[0].platform == "tpu":
        try:
            from jax.experimental import mesh_utils

            mesh_devices = mesh_utils.create_device_mesh(shape, devices=devices)
            return Mesh(mesh_devices, AXIS_ORDER)
        except Exception:  # non-standard topology: fall through to reshape
            pass
    return Mesh(np.asarray(devices).reshape(shape), AXIS_ORDER)


def live_world_spec(
    spec: MeshSpec | Mapping[str, int], num_devices: int
) -> MeshSpec:
    """Re-derive the data axis from the LIVE world (elastic restart).

    An elastic relaunch (runtime/launch.py ``elastic=True``) brings up
    however many workers survived — the mesh cannot be the config's
    mesh, it must be *this* world's. The fixed (non-data) axes are the
    model's sharding contract and survive the resize unchanged; the
    data axis absorbs whatever device count is actually present. Raises
    with the resize named when the fixed axes no longer tile the shrunk
    world (e.g. ``--mesh_model 4`` after dropping to 2 devices) — that
    topology genuinely cannot run, and the supervisor's ``min_world``
    is the knob that prevents reaching it.
    """
    if isinstance(spec, Mapping):
        spec = MeshSpec(**dict(spec))
    fixed = {
        a: getattr(spec, a) for a in AXIS_ORDER if a != "data"
    }
    bad = {a: s for a, s in fixed.items() if s < 1}
    if bad:
        raise ValueError(
            f"elastic resize: fixed mesh axes must be explicit (>= 1), "
            f"got {bad} — only the data axis may be world-derived"
        )
    # One owner for the tiling arithmetic: resolve() already absorbs
    # the -1 axis and rejects indivisible device counts — this wrapper
    # only adds the resize framing to the failure.
    try:
        sizes = dataclasses.replace(spec, data=-1).resolve(num_devices)
    except ValueError as e:
        raise ValueError(
            f"elastic resize: {num_devices} live device(s) cannot carry "
            f"the fixed mesh axes {fixed}; the data axis must absorb an "
            "integer multiple — scale --min_world (or the fixed axes) "
            f"so every reachable world tiles ({e})"
        ) from e
    return dataclasses.replace(spec, data=sizes["data"])


def data_axes(mesh) -> tuple[str, ...]:
    """Axes over which the batch is sharded and grads are averaged.

    ``dcn`` (slice groups each see different data), ``fsdp`` and
    ``expert`` participate in batch sharding — so DDP gradient
    reduction runs over all four. Only axes the mesh actually has are
    returned, so hand-built meshes (e.g. ``Mesh(devices, ('data',))``)
    work too.
    """
    return tuple(
        a for a in ("dcn", "data", "fsdp", "expert") if a in mesh.shape
    )


def dcn_size(mesh) -> int:
    """Slice count of the mesh's ``dcn`` axis (1 on flat meshes)."""
    return int(mesh.shape.get("dcn", 1))


def slice_block_size(mesh) -> int:
    """Devices per slice in the mesh's flattened device order.

    ``dcn`` is the OUTERMOST axis, so flattened device ids group into
    contiguous per-slice blocks of this size — the id arithmetic the
    HLO replica-group ici/dcn attribution keys on
    (obs/xprof.hlo_axis_traffic).
    """
    return mesh.devices.size // dcn_size(mesh)

"""Device-mesh construction — the scale-out geometry of the framework.

The reference's only geometry is a flat rank list (world_size processes,
c10d communicator over all of them). The TPU-native shape is an N-D
``jax.sharding.Mesh`` whose axes name the parallelism strategies; XLA
lowers collectives onto ICI (intra-slice) / DCN (inter-slice) from axis
placement alone.

Axis vocabulary (fixed across the framework):

- ``data``   — data parallelism: batch sharded, params replicated,
               gradient all-reduce (the reference's entire capability,
               SURVEY.md §2c).
- ``fsdp``   — parameter/optimizer sharding (ZeRO-style) on top of data
               parallelism.
- ``expert`` — expert parallelism: MoE expert weights shard their
               leading (expert) dim; tokens shard their batch dim over
               this axis too, so it doubles as a data axis for dense
               layers (GShard-style).
- ``model``  — tensor parallelism within layers.
- ``seq``    — sequence/context parallelism (ring attention).
- ``pipe``   — pipeline stages.

A 1-D ``('data',)`` mesh over all chips reproduces DDP exactly; the
other axes exist so the same train step scales without restructuring.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Mapping, Sequence

import numpy as np

AXIS_ORDER = ("pipe", "data", "fsdp", "expert", "seq", "model")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape; -1 on at most one axis means "all the rest".

    ``MeshSpec()`` (all defaults) is pure DDP: every device on ``data``.
    """

    data: int = -1
    fsdp: int = 1
    expert: int = 1
    model: int = 1
    seq: int = 1
    pipe: int = 1

    def resolve(self, num_devices: int) -> dict[str, int]:
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        bad = {a: s for a, s in sizes.items() if s < 1 and s != -1}
        if bad:
            raise ValueError(
                f"mesh axis sizes must be ≥ 1 (or -1 for 'the rest'): {bad}"
            )
        unknown = [a for a, s in sizes.items() if s == -1]
        if len(unknown) > 1:
            raise ValueError(f"at most one -1 axis, got {unknown}")
        known = math.prod(s for s in sizes.values() if s != -1)
        if unknown:
            if num_devices % known:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes {sizes}"
                )
            sizes[unknown[0]] = num_devices // known
        elif known != num_devices:
            raise ValueError(f"mesh {sizes} needs {known} devices, have {num_devices}")
        return sizes


def make_mesh(
    spec: MeshSpec | Mapping[str, int] | None = None,
    *,
    devices: Sequence | None = None,
):
    """Build a ``jax.sharding.Mesh`` from a logical spec.

    Uses ``mesh_utils.create_device_mesh`` when possible so axis order
    maps onto the physical ICI torus (innermost axes get the
    fastest-varying/nearest chips); falls back to a plain reshape for
    emulated CPU devices.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if spec is None:
        spec = MeshSpec()
    elif isinstance(spec, Mapping):
        spec = MeshSpec(**dict(spec))
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXIS_ORDER)

    if devices[0].platform == "tpu":
        try:
            from jax.experimental import mesh_utils

            mesh_devices = mesh_utils.create_device_mesh(shape, devices=devices)
            return Mesh(mesh_devices, AXIS_ORDER)
        except Exception:  # non-standard topology: fall through to reshape
            pass
    return Mesh(np.asarray(devices).reshape(shape), AXIS_ORDER)


def live_world_spec(
    spec: MeshSpec | Mapping[str, int], num_devices: int
) -> MeshSpec:
    """Re-derive the data axis from the LIVE world (elastic restart).

    An elastic relaunch (runtime/launch.py ``elastic=True``) brings up
    however many workers survived — the mesh cannot be the config's
    mesh, it must be *this* world's. The fixed (non-data) axes are the
    model's sharding contract and survive the resize unchanged; the
    data axis absorbs whatever device count is actually present. Raises
    with the resize named when the fixed axes no longer tile the shrunk
    world (e.g. ``--mesh_model 4`` after dropping to 2 devices) — that
    topology genuinely cannot run, and the supervisor's ``min_world``
    is the knob that prevents reaching it.
    """
    if isinstance(spec, Mapping):
        spec = MeshSpec(**dict(spec))
    fixed = {
        a: getattr(spec, a) for a in AXIS_ORDER if a != "data"
    }
    bad = {a: s for a, s in fixed.items() if s < 1}
    if bad:
        raise ValueError(
            f"elastic resize: fixed mesh axes must be explicit (>= 1), "
            f"got {bad} — only the data axis may be world-derived"
        )
    # One owner for the tiling arithmetic: resolve() already absorbs
    # the -1 axis and rejects indivisible device counts — this wrapper
    # only adds the resize framing to the failure.
    try:
        sizes = dataclasses.replace(spec, data=-1).resolve(num_devices)
    except ValueError as e:
        raise ValueError(
            f"elastic resize: {num_devices} live device(s) cannot carry "
            f"the fixed mesh axes {fixed}; the data axis must absorb an "
            "integer multiple — scale --min_world (or the fixed axes) "
            f"so every reachable world tiles ({e})"
        ) from e
    return dataclasses.replace(spec, data=sizes["data"])


def data_axes(mesh) -> tuple[str, ...]:
    """Axes over which the batch is sharded and grads are averaged.

    ``fsdp`` and ``expert`` participate in batch sharding (each group
    sees different data) — so DDP gradient reduction runs over all
    three. Only axes the mesh actually has are returned, so hand-built
    meshes (e.g. ``Mesh(devices, ('data',))``) work too.
    """
    return tuple(a for a in ("data", "fsdp", "expert") if a in mesh.shape)

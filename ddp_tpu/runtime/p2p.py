"""Point-to-point activation transport for the MPMD pipeline runtime.

The in-graph pipeline schedules (``parallel/one_f1b.py`` and friends)
move activations between stages with ``ppermute`` inside ONE XLA
program — every host compiles every stage. The MPMD runtime
(``parallel/mpmd.py``) breaks the pipeline into one OS process per
stage, so activations and activation-cotangents must cross process
boundaries instead. This module is that boundary: the PR-16 ``DPKV``
wire discipline (serve/disagg.py) applied to activation tensors, plus
a small TCP transport the stage runners drive from host code.

Two layers, deliberately separate:

* **Wire format** (``encode_msg`` / ``decode_msg``): pure bytes <->
  numpy, no sockets, no JAX. Versioned, CRC-checked, length-prefixed::

      magic   4s   b"ACTV"
      version u16  WIRE_VERSION
      flags   u16  reserved, 0
      crc     u32  CRC32 over everything AFTER this field
      hlen    u32  header length in bytes
      header  hlen bytes of UTF-8 JSON
      frames  per header["frames"]: u32 length + raw bytes each

  The JSON header carries the message kind (``act`` / ``cot`` /
  ``sync_up`` / ``sync_down`` / ``hello``), the step and microbatch
  ids, and the dtype/shape contract for every tensor frame.
  Validation order is pinned exactly like DPKV's — magic, version,
  CRC, header schema, frame shapes, trailing bytes — and every
  rejection carries a machine-readable ``reason``.

* **Transport** (``Listener`` / ``Conn`` / ``Channel``): u32
  length-prefixed payloads over a TCP socket between neighbor stages.
  ``Channel.recv`` takes the EXPECTED (kind, step, microbatch) —
  1F1B over a FIFO byte stream makes the arrival sequence exact, so
  any deviation is a protocol bug and raises ``out_of_order`` rather
  than silently training on the wrong microbatch.

Everything here is host code: the blocking send/recv loops run
between jitted per-stage programs, never inside them.
"""

from __future__ import annotations

import json
import queue
import socket
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

MAGIC = b"ACTV"
WIRE_VERSION = 1

# reason codes, in rough order of how early decoding fails
BAD_MAGIC = "bad_magic"
VERSION_SKEW = "version_skew"
TRUNCATED = "truncated"
CRC_MISMATCH = "crc_mismatch"
HEADER_INVALID = "header_invalid"
SHAPE_MISMATCH = "shape_mismatch"
OUT_OF_ORDER = "out_of_order"  # channel-level: valid bytes, wrong slot

_PREFIX = struct.Struct("<4sHHII")  # magic, version, flags, crc, hlen
_FLEN = struct.Struct("<I")

# Message kinds the pipeline speaks. ``act`` flows downstream (stage k
# -> k+1), ``cot`` upstream, the ``sync_*`` pair relays the tied-embed
# gradients + step scalars along the chain once per step, and
# ``hello`` opens a connection (generation fencing).
KIND_ACT = "act"
KIND_COT = "cot"
KIND_SYNC_UP = "sync_up"
KIND_SYNC_DOWN = "sync_down"
KIND_HELLO = "hello"

_KINDS = (KIND_ACT, KIND_COT, KIND_SYNC_UP, KIND_SYNC_DOWN, KIND_HELLO)

NO_MICROBATCH = -1  # microbatch id for sync/hello messages


def _np_dtypes() -> Dict[str, np.dtype]:
    """Wire dtype names -> numpy dtypes (bf16 via ml_dtypes, which
    ships with jax — imported lazily so the wire layer stays usable
    before a stage process pins its JAX platform env)."""
    import ml_dtypes

    return {
        "fp32": np.dtype(np.float32),
        "bf16": np.dtype(ml_dtypes.bfloat16),
        "f16": np.dtype(np.float16),
        "int32": np.dtype(np.int32),
    }


def _dtype_name(dt: np.dtype) -> str:
    for name, cand in _np_dtypes().items():
        if dt == cand:
            return name
    raise ValueError(f"unsupported wire dtype {dt!r}")


class P2PWireError(ValueError):
    """An activation payload that must NOT be consumed.

    ``reason`` is one of ``bad_magic`` / ``version_skew`` /
    ``truncated`` / ``crc_mismatch`` / ``header_invalid`` /
    ``shape_mismatch`` / ``out_of_order`` — the named rejection the
    hardening tests pin. Raised before any byte reaches a stage
    program.
    """

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason


class PeerGone(RuntimeError):
    """The neighbor stage hung up (or never answered): the step

    cannot complete and the runner must wait for the supervisor's
    re-placement decision instead of retrying into a dead socket."""


class Aborted(RuntimeError):
    """The supervisor raised the abort flag (halt/reconfigure) while

    this runner was blocked in transport I/O."""


@dataclass
class TensorMsg:
    """One decoded p2p message: kind + (step, microbatch) identity +
    named tensor frames + a small JSON ``meta`` side-channel."""

    kind: str
    step: int
    microbatch: int
    arrays: Dict[str, np.ndarray]
    meta: dict = field(default_factory=dict)


def encode_msg(
    kind: str,
    step: int,
    microbatch: int,
    arrays: Dict[str, np.ndarray],
    *,
    meta: Optional[dict] = None,
) -> bytes:
    """Named tensors -> one self-validating binary payload.

    ``arrays`` values must be fp32/bf16/f16/int32 numpy arrays
    (zero-size arrays are legal — an empty microbatch still has an
    identity on the wire). Frame order is the dict's insertion order
    and is part of the contract the receiver re-derives from the
    header.
    """
    if kind not in _KINDS:
        raise ValueError(f"unknown message kind {kind!r}")
    if microbatch < NO_MICROBATCH:
        raise ValueError(f"bad microbatch id {microbatch}")
    frames = []
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        frames.append((str(name), _dtype_name(arr.dtype), arr))
    header = {
        "kind": kind,
        "step": int(step),
        "microbatch": int(microbatch),
        "meta": dict(meta or {}),
        "frames": [
            {"name": n, "dtype": d, "shape": [int(s) for s in a.shape]}
            for n, d, a in frames
        ],
    }
    hbytes = json.dumps(header, separators=(",", ":")).encode()
    body = bytearray()
    body += struct.pack("<I", len(hbytes))
    body += hbytes
    for _, _, arr in frames:
        raw = arr.tobytes()
        body += _FLEN.pack(len(raw))
        body += raw
    crc = zlib.crc32(bytes(body)) & 0xFFFFFFFF
    return (
        MAGIC
        + struct.pack("<HH", WIRE_VERSION, 0)
        + struct.pack("<I", crc)
        + bytes(body)
    )


def decode_msg(buf: bytes) -> TensorMsg:
    """One payload -> :class:`TensorMsg`, or :class:`P2PWireError` —
    nothing half-decoded ever escapes.

    Validation order matters and is pinned by the hardening tests:
    magic, version, CRC (over header AND frames — a flipped bit
    anywhere fails here), then header schema, then frame shapes,
    then the no-trailing-bytes check.
    """
    if len(buf) < _PREFIX.size:
        raise P2PWireError(
            TRUNCATED, f"{len(buf)} bytes < {_PREFIX.size}-byte prefix"
        )
    magic, version, _flags, crc, hlen = _PREFIX.unpack_from(buf, 0)
    if magic != MAGIC:
        raise P2PWireError(BAD_MAGIC, repr(magic))
    if version != WIRE_VERSION:
        raise P2PWireError(
            VERSION_SKEW,
            f"payload v{version}, this build speaks v{WIRE_VERSION}",
        )
    body = buf[12:]  # everything the CRC covers (hlen field included)
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise P2PWireError(CRC_MISMATCH)
    off = 4  # past the hlen u32 (re-read from the CRC-checked body)
    (hlen,) = struct.unpack_from("<I", body, 0)
    if off + hlen > len(body):
        raise P2PWireError(
            TRUNCATED, f"header wants {hlen} bytes past the payload"
        )
    try:
        header = json.loads(body[off : off + hlen].decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise P2PWireError(HEADER_INVALID, str(e)) from e
    off += hlen
    try:
        kind = header["kind"]
        step = int(header["step"])
        microbatch = int(header["microbatch"])
        meta = dict(header.get("meta", {}))
        frame_specs = [
            (str(f["name"]), str(f["dtype"]),
             tuple(int(s) for s in f["shape"]))
            for f in header["frames"]
        ]
    except (KeyError, TypeError, ValueError) as e:
        raise P2PWireError(HEADER_INVALID, str(e)) from e
    if kind not in _KINDS:
        raise P2PWireError(HEADER_INVALID, f"unknown kind {kind!r}")
    if step < 0 or microbatch < NO_MICROBATCH:
        raise P2PWireError(
            HEADER_INVALID, f"bad ids step={step} mb={microbatch}"
        )
    dtypes = _np_dtypes()
    for name, dname, shape in frame_specs:
        if dname not in dtypes:
            raise P2PWireError(
                HEADER_INVALID, f"unknown dtype {dname!r}"
            )
        if any(s < 0 for s in shape):
            raise P2PWireError(
                HEADER_INVALID, f"negative dim in {name}: {shape}"
            )
    arrays: Dict[str, np.ndarray] = {}
    for name, dname, shape in frame_specs:
        if off + _FLEN.size > len(body):
            raise P2PWireError(TRUNCATED, f"no length for frame {name}")
        (flen,) = _FLEN.unpack_from(body, off)
        off += _FLEN.size
        if off + flen > len(body):
            raise P2PWireError(
                TRUNCATED, f"frame {name} wants {flen} bytes"
            )
        np_dtype = dtypes[dname]
        count = int(np.prod(shape)) if shape else 1
        expected = count * np_dtype.itemsize
        if flen != expected:
            raise P2PWireError(
                SHAPE_MISMATCH,
                f"frame {name}: {flen} bytes != {expected} for "
                f"{shape} {dname}",
            )
        arrays[name] = np.frombuffer(
            body, dtype=np_dtype, count=count, offset=off
        ).reshape(shape)
        off += flen
    if off != len(body):
        raise P2PWireError(
            TRUNCATED, f"{len(body) - off} trailing bytes"
        )
    return TensorMsg(
        kind=kind,
        step=step,
        microbatch=microbatch,
        arrays=arrays,
        meta=meta,
    )


# --------------------------------------------------------------------
# Transport: u32 length-prefixed payloads over TCP. Host code only.
# --------------------------------------------------------------------

_MAX_PAYLOAD = 1 << 31  # sanity cap on a length prefix
_POLL_S = 0.1  # socket timeout granularity for abort checks

_CLOSE = object()  # sender-queue sentinel


def _recv_exact(
    sock: socket.socket,
    n: int,
    *,
    abort: Optional[threading.Event],
    deadline: Optional[float],
) -> bytes:
    """Read exactly ``n`` bytes, polling the abort flag between
    socket timeouts. Raises :class:`Aborted` / :class:`PeerGone`."""
    chunks = []
    got = 0
    while got < n:
        if abort is not None and abort.is_set():
            raise Aborted("abort flag raised during recv")
        if deadline is not None and time.monotonic() > deadline:
            raise PeerGone(f"recv timed out with {got}/{n} bytes")
        try:
            chunk = sock.recv(min(n - got, 1 << 20))
        except socket.timeout:
            continue
        except OSError as e:
            raise PeerGone(f"recv failed: {e}") from e
        if not chunk:
            raise PeerGone(f"peer closed with {got}/{n} bytes read")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


class Conn:
    """One established neighbor link.

    Sends are queued to a background thread (the 1F1B schedule wants
    a stage to fire its activation downstream and immediately start
    the next slot, not block on the peer's recv pace); receives are
    blocking with abort/timeout polling.
    """

    def __init__(self, sock: socket.socket):
        sock.settimeout(_POLL_S)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._sock = sock
        self._q: "queue.Queue" = queue.Queue()
        self._send_err: Optional[BaseException] = None
        self._closed = False
        self._sender = threading.Thread(
            target=self._drain, name="p2p-send", daemon=True
        )
        self._sender.start()

    def _drain(self) -> None:
        while True:
            item = self._q.get()
            if item is _CLOSE:
                return
            try:
                self._sock.sendall(_FLEN.pack(len(item)) + item)
            except OSError as e:
                self._send_err = PeerGone(f"send failed: {e}")
                return

    def send_bytes(self, payload: bytes) -> None:
        if self._send_err is not None:
            raise self._send_err
        if self._closed:
            raise PeerGone("send on closed conn")
        if len(payload) >= _MAX_PAYLOAD:
            raise ValueError(f"payload too large: {len(payload)}")
        self._q.put(bytes(payload))

    def recv_bytes(
        self,
        *,
        abort: Optional[threading.Event] = None,
        timeout: Optional[float] = None,
    ) -> bytes:
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        raw = _recv_exact(
            self._sock, _FLEN.size, abort=abort, deadline=deadline
        )
        (n,) = _FLEN.unpack(raw)
        if n >= _MAX_PAYLOAD:
            raise PeerGone(f"insane length prefix {n}")
        return _recv_exact(self._sock, n, abort=abort, deadline=deadline)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(_CLOSE)
        self._sender.join(timeout=5.0)
        try:
            self._sock.close()
        except OSError:
            pass


class Listener:
    """A stage's inbound endpoint. Bind once (port 0 -> ephemeral),
    re-``accept`` per supervisor generation."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(8)
        self._srv.settimeout(_POLL_S)
        self.port = int(self._srv.getsockname()[1])

    def accept(
        self,
        *,
        abort: Optional[threading.Event] = None,
        timeout: Optional[float] = None,
    ) -> Conn:
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        while True:
            if abort is not None and abort.is_set():
                raise Aborted("abort flag raised during accept")
            if deadline is not None and time.monotonic() > deadline:
                raise PeerGone("accept timed out")
            try:
                sock, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError as e:
                raise PeerGone(f"accept failed: {e}") from e
            return Conn(sock)

    def close(self) -> None:
        try:
            self._srv.close()
        except OSError:
            pass


def dial(
    host: str,
    port: int,
    *,
    abort: Optional[threading.Event] = None,
    timeout: float = 30.0,
) -> Conn:
    """Connect to a neighbor's :class:`Listener`, retrying until it
    answers (stage processes come up in arbitrary order)."""
    deadline = time.monotonic() + timeout
    while True:
        if abort is not None and abort.is_set():
            raise Aborted("abort flag raised during dial")
        if time.monotonic() > deadline:
            raise PeerGone(f"dial {host}:{port} timed out")
        try:
            sock = socket.create_connection((host, port), timeout=2.0)
            return Conn(sock)
        except OSError:
            time.sleep(0.05)


class Channel:
    """The typed layer the stage runners speak: encode on send,
    decode + identity check on recv.

    ``recv`` rejects a structurally VALID message that is not the one
    the schedule expects next — with one FIFO TCP stream per neighbor
    and a deterministic 1F1B timetable on both ends, the expected
    (kind, step, microbatch) sequence is exact, so a mismatch means a
    protocol bug (or a stale message from a dead generation) and the
    tensors must not be consumed.
    """

    def __init__(self, conn: Conn):
        self._conn = conn

    def send(
        self,
        kind: str,
        step: int,
        microbatch: int,
        arrays: Dict[str, np.ndarray],
        *,
        meta: Optional[dict] = None,
    ) -> None:
        self._conn.send_bytes(
            encode_msg(kind, step, microbatch, arrays, meta=meta)
        )

    def recv(
        self,
        kind: str,
        step: int,
        microbatch: int,
        *,
        abort: Optional[threading.Event] = None,
        timeout: Optional[float] = None,
    ) -> TensorMsg:
        msg = decode_msg(
            self._conn.recv_bytes(abort=abort, timeout=timeout)
        )
        if (msg.kind, msg.step, msg.microbatch) != (
            kind,
            int(step),
            int(microbatch),
        ):
            raise P2PWireError(
                OUT_OF_ORDER,
                f"got ({msg.kind}, step {msg.step}, mb "
                f"{msg.microbatch}), expected ({kind}, step {step}, "
                f"mb {microbatch})",
            )
        return msg

    def close(self) -> None:
        self._conn.close()

"""Tensor + expert parallelism for the shard_map sequence family —
the combined param-spec authority for every non-data axis the seq
models shard over (``model``, ``expert``, ``fsdp``).

The image/GSPMD family gets TP/EP by annotation (parallel/spmd.py
ShardingRules); the sequence family cannot ride that path — ring/
Ulysses attention needs an explicit ``shard_map`` over ``seq`` — so
this module supplies the layouts *inside* the shard_map body
(generalizing /root/reference/train_ddp.py:199's inherited parallel
machinery; SURVEY.md §2c TP/EP rows). Tensor parallelism is the
Megatron column/row pairing below; expert parallelism shards MoE
expert weights' leading dim over ``expert`` with explicit all-to-all
token dispatch (the compute lives in models/moe.py MoEMLP — this
module owns only the at-rest/param-spec side).

Layout per transformer block, ``model`` axis of size ``tp``:

- ``attn/qkv``  — COLUMN parallel: kernel [d, 3d] shards its output
  dim, each member holds ``num_heads/tp`` heads end to end through
  the attention kernel (heads are embarrassingly parallel — ring/
  Ulysses hop over ``seq`` per head, so the two axes compose freely);
- ``attn/proj`` — ROW parallel: kernel [d, d] shards its input dim;
  each member contributes a partial [B, T, d] product, combined by
  ONE ``lax.psum`` over ``model`` (models/vit.py RowParallelDense);
- ``mlp1``      — COLUMN parallel on the hidden dim;
- ``mlp2``      — ROW parallel, the block's second psum.

Everything else (LayerNorms, embeddings, the tied LM head, position
tables) stays replicated over ``model``. No Megatron-style f/g
custom-VJP ops and no gradient rescaling are needed: ``shard_map``'s
transpose handles the whole structure exactly — replicated-input
cotangents are psum'd over unmentioned axes only where the forward
actually diverged, which was verified numerically (dense-reference
gradient parity for replicated LN/head/pos params, sharded kernels,
and the residual stream; adding f/g double-counts — see
tests/test_tp.py).

FSDP composes orthogonally: a leaf can shard dim 0 over ``fsdp`` and
dim 1 over ``model`` (or vice versa). ``gather_sharded`` all-gathers
ONLY the fsdp dims inside the step — model dims stay local, that is
the point of TP — and AD transposes each gather into a psum_scatter
(the ZeRO reduce-scatter, parallel/seq_fsdp.py).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddp_tpu.parallel.seq_fsdp import fsdp_size


def tp_size(mesh: Mesh) -> int:
    return int(mesh.shape.get("model", 1))


# Megatron's f/g custom-VJP pair — needed ONLY where a Megatron block's
# gradient is taken by an EXPLICIT ``jax.vjp`` *inside* the shard_map
# body (the hand-scheduled pipeline kernels, parallel/one_f1b.py /
# interleaved.py). There the shard_map transpose never runs, so the
# cross-member summation it would insert for replicated-input
# cotangents must live in the ops themselves: ``f`` (identity forward,
# psum backward) makes the column matmuls' input cotangents sum across
# members; ``g`` (psum forward, identity backward) is the row matmul's
# combine whose backward must NOT psum again. The seq family's
# annotation-free blocks remain correct under the shard_map transpose
# and would DOUBLE-COUNT with f/g added (verified numerically,
# tests/test_tp.py) — hence the explicit opt-in flag
# (``tp_inner_vjp``) on the block modules instead of always-on ops.


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def megatron_f(x, axis_name: str):
    """Identity forward; backward psums the cotangent over ``axis_name``."""
    return x


def _megatron_f_fwd(x, axis_name):
    return x, None


def _megatron_f_bwd(axis_name, _res, ct):
    return (lax.psum(ct, axis_name),)


megatron_f.defvjp(_megatron_f_fwd, _megatron_f_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def megatron_g(x, axis_name: str):
    """psum forward; backward passes the cotangent through unchanged."""
    return lax.psum(x, axis_name)


def _megatron_g_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _megatron_g_bwd(axis_name, _res, ct):
    return (ct,)


megatron_g.defvjp(_megatron_g_fwd, _megatron_g_bwd)


def ep_size(mesh: Mesh) -> int:
    return int(mesh.shape.get("expert", 1))


def _path_str(path) -> str:
    return "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in path
    )


# Tree-path suffixes → which dim the ``model`` axis shards. The names
# are the flax module names models/vit.py and models/lm.py create.
_COLUMN_KERNELS = ("attn/qkv/kernel", "mlp1/kernel")  # dim 1 (output)
_COLUMN_BIASES = ("attn/qkv/bias", "mlp1/bias")  # dim 0
_ROW_KERNELS = ("attn/proj/kernel", "mlp2/kernel")  # dim 0 (input)
# MoE expert weights (models/moe.py MoEMLP): leading dim = expert index,
# sharded over the ``expert`` axis; wi/wo additionally take ``fsdp`` on
# a non-expert dim where divisible. The router stays with the fallback
# rule (replicated/fsdp — every member routes with identical weights).
_EXPERT_LEAVES = ("moe/wi", "moe/bi", "moe/wo", "moe/bo")


def seq_param_specs(params: Any, mesh: Mesh) -> Any:
    """Per-leaf PartitionSpec combining ``model`` (TP), ``expert``
    (EP), and ``fsdp``.

    With ``model`` and ``expert`` size 1 this reduces exactly to
    parallel/seq_fsdp.py ``fsdp_specs`` (dim 0 over ``fsdp`` where it
    divides). With TP active, block kernels/biases take their
    Megatron dim on ``model``; with EP active, MoE expert weights
    take their leading (expert) dim on ``expert``; in both cases the
    *other* kernel dim takes ``fsdp`` where divisible. Everything
    else falls back to the fsdp rule. Pure function of leaf
    shapes+paths — step builder and state builder recompute it
    independently and always agree.
    """
    tp = tp_size(mesh)
    ep = ep_size(mesh)
    n = fsdp_size(mesh)

    def fsdp_dim0(shape):
        if n > 1 and len(shape) >= 1 and shape[0] > 0 and shape[0] % n == 0:
            return P("fsdp")
        return P()

    def spec(path, leaf):
        shape = jnp.shape(leaf)
        p = _path_str(path) if (tp > 1 or ep > 1) else ""
        if ep > 1 and p.endswith(_EXPERT_LEAVES):
            _check_divides(p, shape[0], ep)
            # wi [E, d, mlp] / wo [E, mlp, d]: fsdp rides dim 1 when it
            # divides; biases [E, 1, f] shard the expert dim only.
            if (
                n > 1 and len(shape) > 1 and shape[1] > 1
                and shape[1] % n == 0
            ):
                return P("expert", "fsdp")
            return P("expert")
        if tp > 1:
            if p.endswith(_COLUMN_KERNELS):
                _check_divides(p, shape[1], tp)
                d0 = (
                    "fsdp" if n > 1 and shape[0] % n == 0 else None
                )
                return P(d0, "model")
            if p.endswith(_COLUMN_BIASES):
                _check_divides(p, shape[0], tp)
                return P("model")
            if p.endswith(_ROW_KERNELS):
                _check_divides(p, shape[0], tp)
                d1 = (
                    "fsdp" if n > 1 and shape[1] % n == 0 else None
                )
                return P("model", d1)
        return fsdp_dim0(shape)

    return jax.tree_util.tree_map_with_path(spec, params)


def _check_divides(path: str, dim: int, axis: int) -> None:
    if dim % axis:
        raise ValueError(
            f"{path}: dim {dim} not divisible by the sharding axis "
            f"size {axis} (num_heads and mlp_dim must divide by "
            f"--mesh_model; num_experts by --mesh_expert)"
        )


def shard_seq_params(params: Any, mesh: Mesh) -> Any:
    """Place params at rest per ``seq_param_specs``."""
    specs = seq_param_specs(params, mesh)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def gather_sharded(params: Any, specs: Any) -> Any:
    """Inside shard_map: all-gather every ``fsdp`` dim; ``model`` dims
    stay local (they ARE the tensor parallelism).

    fp32 gather before any compute-dtype cast, so the transpose — the
    gradient psum_scatter — reduces in fp32 (parallel/seq_fsdp.py
    rationale).
    """

    def g(leaf, s):
        for i, ax in enumerate(s):
            if ax == "fsdp":
                leaf = lax.all_gather(leaf, "fsdp", axis=i, tiled=True)
        return leaf

    return jax.tree.map(g, params, specs)

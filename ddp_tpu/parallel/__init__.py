"""Parallelism layer: every strategy the mesh vocabulary names.

The reference implements exactly one strategy — synchronous data
parallelism (SURVEY.md §2c). This package provides it as a compiled
SPMD step (``ddp.py``), and builds the rest of the axis vocabulary
(runtime.mesh) out for real:

- ``ddp``      — data parallelism: shard_map step, explicit ``pmean``
                 gradient all-reduce (the reference's whole capability).
- ``spmd``     — GSPMD step: tensor parallelism (``model`` axis) +
                 ZeRO-style parameter/optimizer sharding (``fsdp``)
                 from PartitionSpec rules; XLA inserts the collectives.
- ``ring``     — sequence/context parallelism (``seq`` axis): ring
                 attention via ``ppermute`` and Ulysses all-to-all.
- ``pipeline`` — GPipe microbatch pipelining (``pipe`` axis) via
                 ``ppermute`` ring shifts, differentiable schedule.
- ``zero``     — ZeRO-style weight-update sharding on the pure-DP
                 path: bucketed ``psum_scatter`` of gradients, the
                 optimizer on 1/N flat shards (moments rest sharded),
                 ``all_gather`` of params — with an in-graph GSPMD
                 twin for the causal LM's jit-level step.
"""

from ddp_tpu.parallel.ddp import (  # noqa: F401
    TrainState,
    StepMetrics,
    create_train_state,
    make_train_step,
    make_eval_step,
    replicate_state,
)
from ddp_tpu.parallel.pipeline import (  # noqa: F401
    make_pipelined_apply,
    spmd_pipeline,
    stack_stage_params,
)
from ddp_tpu.parallel.ring import (  # noqa: F401
    ring_attention,
    sequence_sharded_attention,
    ulysses_attention,
)
from ddp_tpu.parallel.spmd import (  # noqa: F401
    ShardingRules,
    create_spmd_state,
    make_spmd_eval_step,
    make_spmd_train_step,
    param_specs,
)
from ddp_tpu.parallel.zero import (  # noqa: F401
    BucketLayout,
    build_layout,
    create_zero_state,
    make_zero_train_step,
    zero_gspmd_update,
)

"""Parallelism layer: DDP today; TP/FSDP/sequence axes by design.

The reference implements exactly one strategy — synchronous data
parallelism (SURVEY.md §2c). This package provides it as a compiled
SPMD step (``ddp.py``) over a mesh whose extra axes (``model``,
``fsdp``, ``seq``, ``pipe`` — see runtime.mesh) keep tensor, sharded-
optimizer, sequence/ring-attention, and pipeline parallelism reachable
without restructuring the trainer.
"""

from ddp_tpu.parallel.ddp import (  # noqa: F401
    TrainState,
    StepMetrics,
    create_train_state,
    make_train_step,
    make_eval_step,
    replicate_state,
)

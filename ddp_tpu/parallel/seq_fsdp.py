"""FSDP (ZeRO-3 style) parameter sharding for the shard_map sequence
family.

The image families get fsdp via GSPMD annotation rules
(parallel/spmd.py); the sequence models can't ride that path — ring/
Ulysses attention needs an explicit ``shard_map`` over the ``seq``
axis. This module supplies the manual equivalent, lifting round 1's
"seq models compose with data+seq only" wall (VERDICT.md weak #4 /
"do this" #3): without it, replicated LM params cap model size at one
chip's HBM no matter how many chips the mesh has.

Mechanics — textbook FSDP expressed in shard_map terms:

- at rest, every parameter whose leading dim divides by the ``fsdp``
  axis size lives SHARDED on dim 0 over ``fsdp`` (``fsdp_specs``);
  optimizer moments inherit the same layout from ``optimizer.init`` on
  the sharded params, so Adam state memory also drops by the axis size
  (this is simultaneously ZeRO-1/2/3 — params, grads, and moments all
  shard);
- inside the step, ``gather_fsdp`` materializes full parameters with
  one ``all_gather`` per sharded leaf (XLA overlaps them with compute
  where the schedule allows);
- the backward needs no extra code: AD transposes ``all_gather`` into
  ``psum_scatter``, so each device receives exactly its shard's
  gradient, already summed over the ring — the reduce-scatter half of
  ZeRO, derived rather than written.

The batch meanwhile shards over ``fsdp`` too (it is a data axis —
runtime/mesh.py ``data_axes``), which is what makes this FSDP rather
than tensor parallelism: each fsdp group member sees different rows
and identical (gathered) weights.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def fsdp_size(mesh: Mesh) -> int:
    return int(mesh.shape.get("fsdp", 1))


def fsdp_specs(params: Any, mesh: Mesh) -> Any:
    """Per-leaf PartitionSpec: dim 0 over ``fsdp`` where it divides.

    Leaves that can't shard (scalars, dim0 not divisible — e.g. the
    [1, L, d] position table) stay replicated. A pure function of leaf
    SHAPES, so the step builder can recompute it at trace time and the
    state builder at init time and always agree.
    """
    n = fsdp_size(mesh)

    def spec(leaf):
        shape = jnp.shape(leaf)
        if n > 1 and len(shape) >= 1 and shape[0] > 0 and shape[0] % n == 0:
            return P("fsdp")
        return P()

    return jax.tree.map(spec, params)


def shard_fsdp_params(params: Any, mesh: Mesh) -> Any:
    """Place params at rest: dim-0 sharded over ``fsdp`` per the specs."""
    specs = fsdp_specs(params, mesh)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def gather_fsdp(params: Any, specs: Any) -> Any:
    """Inside shard_map: full parameters from their fsdp shards.

    fp32 gather (before any compute-dtype cast) so the transpose — the
    gradient ``psum_scatter`` — also reduces in fp32; halving the
    collective payload by casting first would silently sum gradients
    in bf16.
    """

    def g(leaf, s):
        if s == P("fsdp"):
            return lax.all_gather(leaf, "fsdp", axis=0, tiled=True)
        return leaf

    return jax.tree.map(g, params, specs)

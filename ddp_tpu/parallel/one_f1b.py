"""1F1B (PipeDream-flush) pipeline schedule with O(S) activation stash.

The GPipe schedule in ``parallel/pipeline.py`` derives its backward
from AD: simple and exact, but the forward scan stashes residuals for
every tick — O(M) microbatch activations per device. 1F1B interleaves
one backward between forwards as soon as the first microbatch returns,
so a device never holds more than S − d in-flight microbatches: the
activation stash is O(S), independent of M. That is the schedule's
entire point (the bubble fraction is the same (S−1)/(M+S−1) as GPipe);
it is what lets M grow to amortize the bubble without activation
memory growing with it.

Because the backward slots are hand-scheduled, this module does NOT go
through ``jax.grad``. The schedule is computed once on the host by a
greedy simulator (``schedule_1f1b`` — prefer-backward policy, which
reproduces the canonical PipeDream-flush timetable; the simulator
*asserts* the two properties the kernel relies on: cotangents hop
exactly one slot per stage, and at most one produced-but-unconsumed
forward activation waits per device). The device program is one
``lax.scan`` over the slot tables: each slot every device runs its
scheduled op via ``lax.switch`` — a forward (stash the stage input,
one microbatch) or a backward (recompute the stage from the stashed
input and apply its VJP — the remat-style 1F1B that stores inputs
only). The loss runs INSIDE the last stage's backward, which is what
frees outputs from ever being collected.

Data movement per slot (all uniform, collectives outside the switch):
fresh microbatches reach stage 0 by a masked ``psum`` from their home
shard (inputs rest sharded over ``pipe`` as in the GPipe path);
forward activations hop down the ring by ``ppermute`` into a single
pending slot; cotangents hop up the ring the same way. Non-uniform
``first_fn``/``last_fn`` (embed/head) run inside stages 0/S−1 exactly
as in the GPipe path, and their parameter gradients come out of the
same VJPs.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


class Schedule(NamedTuple):
    """Host-computed 1F1B timetable.

    ``op``/``mb``: [n_slots, S] int32 — what each device does per slot
    (0 idle, 1 forward, 2 backward) and on which microbatch.
    """

    op: np.ndarray
    mb: np.ndarray

    @property
    def n_slots(self) -> int:
        return self.op.shape[0]

    def bubble_fraction(self) -> float:
        """Measured idle fraction of the timetable."""
        total = self.op.size
        return float((self.op == 0).sum()) / total


IDLE, FWD, BWD = 0, 1, 2


def schedule_1f1b(num_stages: int, num_microbatches: int) -> Schedule:
    """Greedy prefer-backward simulation of PipeDream-flush.

    Device d may keep at most S − d microbatches in flight (the 1F1B
    stash cap) and takes a ready backward over a ready forward. The
    simulation also verifies the kernel's transport assumptions (see
    module docstring) so a policy regression fails HERE, loudly, not
    as silently-wrong gradients on device.
    """
    S, M = num_stages, num_microbatches
    F_done = [[None] * S for _ in range(M)]
    B_done = [[None] * S for _ in range(M)]
    next_f, next_b = [0] * S, [0] * S
    ops, mbs = [], []
    t = 0
    while any(nb < M for nb in next_b):
        if t > 4 * (M + S) + 16:
            raise RuntimeError(f"1F1B schedule did not converge (S={S}, M={M})")
        row_op, row_mb = [IDLE] * S, [0] * S
        for d in range(S):
            m_b = next_b[d]
            can_b = m_b < M and (
                (d == S - 1 and F_done[m_b][d] is not None and F_done[m_b][d] < t)
                or (d < S - 1 and B_done[m_b][d + 1] is not None
                    and B_done[m_b][d + 1] < t)
            )
            m_f = next_f[d]
            can_f = (
                m_f < M
                and (d == 0 or (F_done[m_f][d - 1] is not None
                                and F_done[m_f][d - 1] < t))
                and m_f - next_b[d] < S - d
            )
            if can_b:
                row_op[d], row_mb[d] = BWD, m_b
                B_done[m_b][d] = t
                next_b[d] += 1
            elif can_f:
                row_op[d], row_mb[d] = FWD, m_f
                F_done[m_f][d] = t
                next_f[d] += 1
        ops.append(row_op)
        mbs.append(row_mb)
        t += 1
    # Kernel transport invariant 1: cotangents hop exactly one slot.
    for m in range(M):
        for d in range(S - 1):
            assert B_done[m][d] == B_done[m][d + 1] + 1, (
                f"bwd({m},{d}) at {B_done[m][d]} != bwd({m},{d + 1}) "
                f"{B_done[m][d + 1]} + 1"
            )
    # Invariant 2: at most one unconsumed forward activation per device.
    for d in range(1, S):
        pending = []
        for m in range(M):
            arrive = F_done[m][d - 1] + 1
            consume = F_done[m][d]
            pending.append((arrive, consume))
        for (a1, c1), (a2, _) in zip(pending, pending[1:]):
            # Strict: the kernel latches the arrival at the END of slot
            # a2-1, while the pending value is read at the TOP of c1 —
            # a2 == c1 would overwrite one slot early.
            assert a2 > c1, (
                f"device {d}: activation for a later microbatch arrives at "
                f"{a2} before the previous one is consumed at {c1}"
            )
    return Schedule(np.asarray(ops, np.int32), np.asarray(mbs, np.int32))


def spmd_pipeline_1f1b(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,
    labels: jax.Array,
    loss_fn: Callable[[jax.Array, jax.Array], tuple[jax.Array, Any]],
    schedule: Schedule,
    *,
    axis_name: str = "pipe",
    first_fn: Callable[[Any, jax.Array], jax.Array] | None = None,
    first_params: Any = None,
    last_fn: Callable[[Any, jax.Array], jax.Array] | None = None,
    last_params: Any = None,
):
    """Run the combined forward+backward 1F1B timetable.

    Call INSIDE shard_map over ``axis_name``. Arguments mirror
    ``parallel.pipeline.spmd_pipeline`` plus:

      labels: [M, mb_label...] — replicated (tiny); indexed by
        microbatch at the last stage's backward.
      loss_fn: ``(out_mb, label_mb) -> (scalar_loss, aux_scalar)`` —
        the per-microbatch loss (summed over microbatches here) and
        one auxiliary scalar (e.g. correct-prediction count), both
        accumulated.
      schedule: from ``schedule_1f1b(S, M)``.

    Returns ``(loss_sum, aux_sum, g_stage, g_first, g_last)``:
    ``g_stage`` is this device's stage-gradient slice (leading dim 1,
    for ``out_specs=P(axis_name)``); ``g_first``/``g_last``/scalars are
    psum'd (replicated). Gradients are SUMS over microbatches — divide
    by M outside for a mean-of-means loss.
    """
    params = jax.tree.map(lambda p: p[0], stage_params)
    stage = lax.axis_index(axis_name)
    S = lax.psum(1, axis_name)
    if S < 2:
        # With one stage the first/last roles collide and the role
        # switch would route around the loss VJP entirely (silent zero
        # gradients). A 1-stage pipeline is not a pipeline — use the
        # plain step (or the GPipe path, which degrades gracefully).
        raise ValueError("1F1B needs a pipe axis of at least 2 stages")
    local_in = microbatches[:, 0]  # [R, mb, ...]
    R = local_in.shape[0]
    M = R * S
    assert schedule.op.shape[1] == S, (schedule.op.shape, S)

    if first_fn is None:
        first_fn = lambda p, x: x
    if last_fn is None:
        last_fn = lambda p, x: x
    raw_shape = jax.eval_shape(lambda x: x, local_in[0])
    act_shape = jax.eval_shape(first_fn, first_params, local_in[0])

    fwd_shift = [(i, i + 1) for i in range(S - 1)]
    bwd_shift = [(i + 1, i) for i in range(S - 1)]

    op_tab = jnp.asarray(schedule.op)
    mb_tab = jnp.asarray(schedule.mb)

    def zero_grads():
        zg = jax.tree.map(jnp.zeros_like, params)
        zf = jax.tree.map(jnp.zeros_like, first_params)
        zl = jax.tree.map(jnp.zeros_like, last_params)
        return zg, zf, zl

    def slot(carry, xs):
        (pend_act, pend_cot, stash_act,
         g_stage, g_first, g_last, loss_acc, aux_acc) = carry
        op_row, mb_row, m0 = xs
        my_op = op_row[stage]
        my_m = mb_row[stage]

        # Stage-0 input fetch: microbatch m0 (stage 0's scheduled mb,
        # forward OR backward — a stage-0 backward re-fetches its raw
        # input here instead of stashing it) broadcast from its home
        # shard. Uniform collective.
        fresh = lax.psum(
            jnp.where(
                stage == m0 % S,
                lax.dynamic_index_in_dim(
                    local_in, jnp.clip(m0 // S, 0, R - 1), 0, keepdims=False
                ),
                jnp.zeros(raw_shape.shape, raw_shape.dtype),
            ),
            axis_name,
        )

        slot_idx = my_m % S

        def do_idle(args):
            (pend_act, pend_cot, stash_act,
             g_stage, g_first, g_last, loss_acc, aux_acc) = args
            zero_act = jnp.zeros(act_shape.shape, act_shape.dtype)
            return (
                pend_act, pend_cot, stash_act,
                g_stage, g_first, g_last, loss_acc, aux_acc,
                zero_act, zero_act,
            )

        def do_fwd(args):
            (pend_act, pend_cot, stash_act,
             g_stage, g_first, g_last, loss_acc, aux_acc) = args
            # Stage 0 embeds the fetched raw microbatch; others consume
            # the pending ring activation. Stash the stage INPUT (the
            # remat residual) in the microbatch's slot.
            x_in = lax.cond(
                stage == 0,
                lambda: first_fn(first_params, fresh).astype(pend_act.dtype),
                lambda: pend_act,
            )
            stash_act = lax.dynamic_update_index_in_dim(
                stash_act, x_in, slot_idx, 0
            )
            y = stage_fn(params, x_in)
            zero_act = jnp.zeros(act_shape.shape, act_shape.dtype)
            return (
                pend_act, pend_cot, stash_act,
                g_stage, g_first, g_last, loss_acc, aux_acc,
                y, zero_act,
            )

        def do_bwd(args):
            (pend_act, pend_cot, stash_act,
             g_stage, g_first, g_last, loss_acc, aux_acc) = args
            # Stage 0's raw input is re-fetched (``fresh``: m0 == my_m
            # at a stage-0 backward slot) rather than stashed.
            raw_m = fresh
            act_m = lax.dynamic_index_in_dim(
                stash_act, slot_idx, 0, keepdims=False
            )
            lbl_m = lax.dynamic_index_in_dim(
                labels, jnp.clip(my_m, 0, labels.shape[0] - 1), 0,
                keepdims=False,
            )

            # Three stage roles → one uniform grads pytree. Each
            # recomputes its stage from the stashed input (remat) and
            # runs the VJP; only its own entries are nonzero.
            def bwd_first(_):
                def f(sp, fp):
                    return stage_fn(sp, first_fn(fp, raw_m).astype(act_m.dtype))

                _, vjp = jax.vjp(f, params, first_params)
                gs, gf = vjp(pend_cot)
                _, zf, zl = zero_grads()
                zero_act = jnp.zeros(act_shape.shape, act_shape.dtype)
                return gs, gf, zl, zero_act, jnp.float32(0), jnp.float32(0)

            def bwd_mid(_):
                def f(sp, x):
                    return stage_fn(sp, x)

                _, vjp = jax.vjp(f, params, act_m)
                gs, gx = vjp(pend_cot)
                _, zf, zl = zero_grads()
                return gs, zf, zl, gx, jnp.float32(0), jnp.float32(0)

            def bwd_last(_):
                def f(sp, lp, x):
                    out = last_fn(lp, stage_fn(sp, x))
                    loss, aux = loss_fn(out, lbl_m)
                    return loss, aux

                loss, vjp, aux = jax.vjp(
                    f, params, last_params, act_m, has_aux=True
                )
                gs, gl, gx = vjp(jnp.float32(1.0))
                _, zf, _ = zero_grads()
                return (
                    gs, zf, gl, gx,
                    loss.astype(jnp.float32), jnp.asarray(aux, jnp.float32),
                )

            role = jnp.where(stage == 0, 0, jnp.where(stage == S - 1, 2, 1))
            gs, gf, gl, gx, loss, aux = lax.switch(
                role, [bwd_first, bwd_mid, bwd_last], None
            )
            g_stage = jax.tree.map(jnp.add, g_stage, gs)
            g_first = jax.tree.map(jnp.add, g_first, gf)
            g_last = jax.tree.map(jnp.add, g_last, gl)
            zero_act = jnp.zeros(act_shape.shape, act_shape.dtype)
            return (
                pend_act, pend_cot, stash_act,
                g_stage, g_first, g_last,
                loss_acc + loss, aux_acc + aux,
                zero_act, gx,
            )

        out = lax.switch(
            my_op, [do_idle, do_fwd, do_bwd],
            (pend_act, pend_cot, stash_act,
             g_stage, g_first, g_last, loss_acc, aux_acc),
        )
        (pend_act, pend_cot, stash_act,
         g_stage, g_first, g_last, loss_acc, aux_acc,
         act_msg, cot_msg) = out

        # Uniform ring transport; receivers latch only when their
        # neighbor actually produced this slot (known from the table).
        act_arrived = lax.ppermute(act_msg, axis_name, fwd_shift)
        cot_arrived = lax.ppermute(cot_msg, axis_name, bwd_shift)
        up_op = op_row[jnp.clip(stage - 1, 0, S - 1)]
        down_op = op_row[jnp.clip(stage + 1, 0, S - 1)]
        pend_act = jnp.where(
            (stage > 0) & (up_op == FWD), act_arrived, pend_act
        )
        pend_cot = jnp.where(
            (stage < S - 1) & (down_op == BWD), cot_arrived, pend_cot
        )
        return (
            pend_act, pend_cot, stash_act,
            g_stage, g_first, g_last, loss_acc, aux_acc,
        ), None

    zg, zf, zl = zero_grads()
    carry = (
        jnp.zeros(act_shape.shape, act_shape.dtype),
        jnp.zeros(act_shape.shape, act_shape.dtype),
        jnp.zeros((S, *act_shape.shape), act_shape.dtype),
        zg, zf, zl,
        jnp.float32(0.0),
        jnp.float32(0.0),
    )
    m0_seq = jnp.asarray(schedule.mb[:, 0])
    carry, _ = lax.scan(slot, carry, (op_tab, mb_tab, m0_seq))
    (_, _, _, g_stage, g_first, g_last, loss_acc, aux_acc) = carry

    # Loss/aux live on the last stage; first/last grads on their ends.
    loss_sum = lax.psum(loss_acc, axis_name)
    aux_sum = lax.psum(aux_acc, axis_name)
    g_first = lax.psum(g_first, axis_name)
    g_last = lax.psum(g_last, axis_name)
    return (
        loss_sum, aux_sum,
        jax.tree.map(lambda g: g[None], g_stage),
        g_first, g_last,
    )

"""ZeRO-style weight-update sharding on the data-parallel path.

The reference (and our own ``parallel/ddp.py``) ends every step the
same way DDP always has: all-reduce the FULL gradient tree, then let
every replica redundantly run the identical optimizer update over the
identical replicated moments — N copies of the same math and N copies
of the Adam moments. "Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training" (PAPERS.md #3) removes exactly that
redundancy without touching the model math:

    reduce-scatter(grads)  →  each replica owns 1/N of every bucket
    sharded optimizer update  →  moments + update math are 1/N
    all-gather(params)     →  replicas re-converge, bit-for-bit

Params stay replicated at rest (this is ZeRO stage 1, not FSDP — the
``fsdp`` axis already covers stage 3 by annotation); only the
optimizer state and the update compute shard. Total collective payload
is unchanged — a ring all-reduce IS a reduce-scatter + all-gather —
but the *all-reduce* disappears, the moments memory divides by N, and
the two half-collectives become independently schedulable per bucket.

Gradients are packed into size-targeted **buckets** (``--zero_bucket_mb``,
the knob DDP's C++ reducer calls ``bucket_cap_mb``): each bucket is a
flat fp32 vector padded to a multiple of the replica count, so a
parameter count not divisible by the axis size costs padding, never a
wrong answer. Bucketing is what buys comm/compute overlap: each
bucket's reduce-scatter depends only on ITS leaves' gradients, so the
scheduler may dispatch bucket k's scatter while backward compute for
bucket k+1's layers is still in flight — and the all-gathers pipeline
against the sharded updates the same way. ``overlap=False`` builds the
control: an ``optimization_barrier`` fence after the full backward
plus a serial chain through the collectives, which is what bench.py
measures the overlapped step against (prove it, don't assume it).

Two expressions of the same decomposition, per the paper's framing:

- ``make_zero_train_step`` — the explicit-collective ``shard_map``
  step (the DDP image family): ``lax.psum_scatter`` / sharded optax
  update / ``lax.all_gather``, every collective visible.
- ``zero_gspmd_update`` — the in-graph GSPMD expression (the causal
  LM's jit-level step): the same bucket layout pinned with
  ``with_sharding_constraint`` so the SPMD partitioner shards the
  update math and the moments, and derives the parameter all-gather.

The optimizer contract: the update rule must be *elementwise* (sgd,
momentum, adam, adamw, weight decay, schedules) because it runs on
1/N flat shards — transforms that couple elements across the tree
(global-norm clipping, full-shape parameter EMA) are rejected at
construction (train/optim.py ``check_zero_compatible``).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddp_tpu.parallel.common import (
    check_accum_divisible,
    make_loss_fn,
)
from ddp_tpu.parallel.ddp import StepMetrics, TrainState
from ddp_tpu.runtime.mesh import data_axes


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One flat fp32 reduce-scatter unit: a contiguous run of leaves.

    ``padded`` rounds ``total`` up to a multiple of the replica count
    so the scatter tiles evenly; the pad region carries zeros end to
    end (zero grads → zero moments → zero update), so indivisible
    parameter counts are correct by construction.
    """

    leaf_ids: tuple[int, ...]
    sizes: tuple[int, ...]
    total: int
    padded: int
    shard: int


@dataclasses.dataclass(frozen=True)
class BucketLayout:
    """Assignment of every param leaf (flatten order) to a bucket."""

    buckets: tuple[Bucket, ...]
    num_leaves: int
    world: int

    @property
    def padded_total(self) -> int:
        return sum(b.padded for b in self.buckets)


def _opt_key(i: int) -> str:
    return f"b{i:03d}"


def opt_keys(layout: BucketLayout) -> list[str]:
    return [_opt_key(i) for i in range(len(layout.buckets))]


def build_layout(
    params, world: int, *, bucket_mb: float = 4.0
) -> BucketLayout:
    """Greedy size-targeted bucketing over the param leaves.

    ``params`` may be arrays or ``ShapeDtypeStruct``s — only shapes
    matter. Leaves pack in flatten order until a bucket crosses the
    byte target (fp32 accounting — the reduction dtype); a leaf larger
    than the target gets its own bucket rather than being split.
    """
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    if bucket_mb <= 0:
        raise ValueError(f"bucket_mb must be > 0, got {bucket_mb}")
    leaves = jax.tree_util.tree_leaves(params)
    if not leaves:
        raise ValueError("empty parameter tree — nothing to shard")
    target_elems = max(1, int(bucket_mb * 2**20) // 4)
    buckets: list[Bucket] = []
    ids: list[int] = []
    sizes: list[int] = []
    total = 0

    def close():
        nonlocal ids, sizes, total
        if not ids:
            return
        padded = -(-total // world) * world
        buckets.append(
            Bucket(
                leaf_ids=tuple(ids),
                sizes=tuple(sizes),
                total=total,
                padded=padded,
                shard=padded // world,
            )
        )
        ids, sizes, total = [], [], 0

    for i, leaf in enumerate(leaves):
        n = 1
        for s in leaf.shape:
            n *= int(s)
        if n >= target_elems:
            # An oversized leaf gets its OWN bucket: trapping the
            # accumulated small leaves behind it would serialize their
            # scatter on the big transfer.
            close()
            ids, sizes, total = [i], [n], n
            close()
            continue
        ids.append(i)
        sizes.append(n)
        total += n
        if total >= target_elems:
            close()
    close()
    return BucketLayout(
        buckets=tuple(buckets), num_leaves=len(leaves), world=world
    )


def check_zero_mesh(mesh: Mesh) -> None:
    """The sharded update scatters over the DATA axis alone: any other
    populated axis already owns its own optimizer-state story (fsdp IS
    ZeRO-3; tp/expert/seq/pipe shard state by their rule layouts)."""
    bad = {
        a: int(mesh.shape[a])
        for a in ("model", "fsdp", "expert", "seq", "pipe")
        if mesh.shape.get(a, 1) > 1
    }
    if bad:
        raise ValueError(
            f"--parallel zero shards the weight update over the data "
            f"axis only; {bad} already shard optimizer state their own "
            "way — drop the axes or the flag"
        )


def _flatten_buckets(layout: BucketLayout, leaves) -> list[jax.Array]:
    """Leaf list → one flat fp32 ``[padded]`` vector per bucket."""
    flats = []
    for b in layout.buckets:
        parts = [
            leaves[i].astype(jnp.float32).reshape(-1) for i in b.leaf_ids
        ]
        pad = b.padded - b.total
        if pad:
            parts.append(jnp.zeros((pad,), jnp.float32))
        flats.append(parts[0] if len(parts) == 1 else jnp.concatenate(parts))
    return flats


def _unflatten_buckets(layout: BucketLayout, flats, like_leaves):
    """Flat ``[padded]`` vectors → leaf list shaped/typed like
    ``like_leaves`` (static slices; the pad tail is dropped)."""
    out: list[Any] = [None] * layout.num_leaves
    for b, flat in zip(layout.buckets, flats):
        off = 0
        for i, n in zip(b.leaf_ids, b.sizes):
            like = like_leaves[i]
            out[i] = (
                flat[off : off + n].reshape(like.shape).astype(like.dtype)
            )
            off += n
    return out


def _opt_template(optimizer, layout: BucketLayout):
    """abstract optimizer state over the flat buckets + the elementwise
    contract check: every state leaf must be a scalar (schedule/Adam
    counts) or shaped exactly like its bucket — anything else means
    the update couples elements across the tree and cannot run on
    1/N shards. Shape-based, so it catches full-shape STATE (a param
    EMA of the original tree) but not STATELESS cross-element
    transforms (global-norm clipping carries EmptyState) — those are
    rejected at the flag level (train/optim.check_zero_compatible);
    direct-API callers composing their own optax chains own the
    elementwise contract for stateless members."""
    flats = {
        _opt_key(i): jax.ShapeDtypeStruct((b.padded,), jnp.float32)
        for i, b in enumerate(layout.buckets)
    }
    tpl = jax.eval_shape(optimizer.init, flats)
    allowed = {v.shape for v in flats.values()}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tpl)[0]:
        if len(leaf.shape) and leaf.shape not in allowed:
            name = jax.tree_util.keystr(path)
            raise ValueError(
                f"optimizer state leaf {name} has shape {leaf.shape}, "
                "not the flat bucket shape — the zero update runs "
                "elementwise on 1/N shards (sgd/momentum/adam/adamw "
                "compose; global-norm clipping and parameter EMA do "
                "not — train/optim.check_zero_compatible)"
            )
    return tpl


def opt_state_specs(optimizer, layout: BucketLayout):
    """PartitionSpec tree for the resting optimizer state: flat bucket
    leaves shard dim 0 over ``data``, scalars replicate."""
    tpl = _opt_template(optimizer, layout)
    return jax.tree.map(
        lambda x: P("data") if len(x.shape) else P(), tpl
    )


def create_zero_opt_state(params, optimizer, mesh: Mesh, layout: BucketLayout):
    """Initialize the optimizer state directly into the sharded layout.

    State leaves are GLOBAL ``[padded]`` arrays resting sharded over
    ``data`` (1/N per device — the memory win is at rest, not just in
    the step); scalars replicate. Works multi-process: every process
    computes the same init under one jit with explicit out_shardings.
    """
    leaves = jax.tree_util.tree_leaves(params)
    flats = dict(zip(opt_keys(layout), _flatten_buckets(layout, leaves)))
    specs = opt_state_specs(optimizer, layout)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)
    return jax.jit(optimizer.init, out_shardings=shardings)(flats)


def create_zero_state(
    model,
    optimizer: optax.GradientTransformation,
    sample_input,
    mesh: Mesh,
    *,
    seed: int = 0,
    bucket_mb: float = 4.0,
) -> tuple[TrainState, BucketLayout]:
    """Replicated params + step + model_state, data-sharded flat
    optimizer state. The placements ARE the contract (checkpoint
    restores template on them, like the fsdp family)."""
    from ddp_tpu.parallel.common import _train_kwarg

    check_zero_mesh(mesh)
    variables = model.init(
        jax.random.key(seed), sample_input, **_train_kwarg(model, False)
    )
    params = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}
    layout = build_layout(
        params, int(mesh.shape["data"]), bucket_mb=bucket_mb
    )
    rep = NamedSharding(mesh, P())
    put = lambda t: jax.tree.map(lambda x: jax.device_put(x, rep), t)
    params = put(params)
    state = TrainState(
        step=jax.device_put(jnp.zeros((), jnp.int32), rep),
        params=params,
        opt_state=create_zero_opt_state(params, optimizer, mesh, layout),
        model_state=put(model_state),
    )
    return state, layout


def _scatter_buckets(flats, *, sequential: bool = False):
    """Reduce-scatter each bucket over ``data`` (raw SUMS — callers
    divide by the axis size). ``sequential=True`` is the no-overlap
    control: a barrier fences the collectives behind the ENTIRE
    backward, and each scatter chains on its predecessor, so nothing
    can hide under compute."""
    if sequential and len(flats) > 1:
        flats = list(lax.optimization_barrier(tuple(flats)))
    out = []
    prev = None
    for f in flats:
        if sequential and prev is not None:
            f, _ = lax.optimization_barrier((f, prev))
        s = lax.psum_scatter(f, "data", scatter_dimension=0, tiled=True)
        out.append(s)
        prev = s
    return out


def _gather_buckets(shards, *, sequential: bool = False):
    """All-gather each bucket's updated param shard back to ``[padded]``
    (tiled — member i contributes block i, the psum_scatter order)."""
    out = []
    prev = None
    for s in shards:
        if sequential and prev is not None:
            s, _ = lax.optimization_barrier((s, prev))
        g = lax.all_gather(s, "data", axis=0, tiled=True)
        out.append(g)
        prev = g
    return out


def make_zero_train_step(
    model,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    layout: BucketLayout,
    *,
    compute_dtype=jnp.float32,
    donate: bool = True,
    seed: int = 0,
    aux_loss_weight: float = 0.01,
    grad_accum_steps: int = 1,
    augment_fn=None,
    label_smoothing: float = 0.0,
    overlap: bool = True,
) -> Callable[[TrainState, jax.Array, jax.Array], tuple[TrainState, StepMetrics]]:
    """The explicit-collective (shard_map) zero step — ``parallel/ddp.py``
    ``make_train_step``'s contract with the update stage swapped:
    ``pmean(grads) → update`` becomes ``psum_scatter → 1/N update →
    all_gather``. Loss/accuracy semantics are identical (pinned by
    tests/test_zero.py and the 2-process gloo spawn pins).

    ``grad_accum_steps=k`` accumulates into the SCATTERED shards — one
    reduce-scatter per microbatch, accumulator buffers 1/N — so the
    memory win survives accumulation (a full-tree accumulator would
    undo it).
    """
    check_zero_mesh(mesh)
    axes = data_axes(mesh)
    world = int(mesh.shape["data"])
    if world != layout.world:
        raise ValueError(
            f"layout built for world {layout.world}, mesh data axis is "
            f"{world}"
        )
    keys = opt_keys(layout)
    loss_fn = make_loss_fn(
        model, compute_dtype, aux_loss_weight, augment_fn=augment_fn,
        label_smoothing=label_smoothing,
    )

    def per_shard_step(state: TrainState, images, labels):
        mutable = list(state.model_state.keys())
        rng = jax.random.fold_in(jax.random.key(seed), state.step)
        for a in axes:
            rng = jax.random.fold_in(rng, lax.axis_index(a))

        if grad_accum_steps == 1:
            (loss, (logits, new_ms)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params, state.model_state, images, labels, rng, mutable)
            correct = (jnp.argmax(logits.astype(jnp.float32), -1) == labels).sum()
            n_labels = labels.shape[0]
            # THE rework: where ddp.py all-reduces the full tree, each
            # bucket reduce-scatters independently — free to dispatch
            # while backward compute for later buckets is in flight.
            gshards = _scatter_buckets(
                _flatten_buckets(layout, jax.tree_util.tree_leaves(grads)),
                sequential=not overlap,
            )
            scale = 1.0 / world
        else:
            mb = check_accum_divisible(images.shape[0], grad_accum_steps)
            imgs = images.reshape(grad_accum_steps, mb, *images.shape[1:])
            lbls = labels.reshape(grad_accum_steps, mb)

            def micro(carry, xy):
                sh_acc, ms, loss_acc, correct_acc, i = carry
                x, y = xy
                (mloss, (mlogits, mms)), g = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(state.params, ms, x, y, jax.random.fold_in(rng, i), mutable)
                # Accumulate the SCATTERED shard, not the full tree:
                # the accumulator is 1/N per replica by construction.
                sh = _scatter_buckets(
                    _flatten_buckets(layout, jax.tree_util.tree_leaves(g)),
                    sequential=not overlap,
                )
                c = (jnp.argmax(mlogits.astype(jnp.float32), -1) == y).sum()
                return (
                    [a + s for a, s in zip(sh_acc, sh)],
                    mms,
                    loss_acc + mloss,
                    correct_acc + c.astype(jnp.float32),
                    i + 1,
                ), None

            zero_sh = [
                jnp.zeros((b.shard,), jnp.float32) for b in layout.buckets
            ]
            (gshards, new_ms, loss_sum, correct, _), _ = lax.scan(
                micro,
                (
                    zero_sh,
                    state.model_state,
                    jnp.zeros((), jnp.float32),
                    jnp.zeros((), jnp.float32),
                    jnp.zeros((), jnp.int32),
                ),
                (imgs, lbls),
            )
            loss = loss_sum / grad_accum_steps
            n_labels = images.shape[0]
            scale = 1.0 / (world * grad_accum_steps)

        g_tree = {k: s * scale for k, s in zip(keys, gshards)}
        # Global grad norm from disjoint shards: one scalar psum.
        local_sq = sum(jnp.sum(jnp.square(g)) for g in g_tree.values())
        grad_norm = jnp.sqrt(lax.psum(local_sq, axes))
        # This replica's own param block, sliced locally (params are
        # replicated — no comm; block order is psum_scatter's).
        idx = lax.axis_index("data")
        p_leaves = jax.tree_util.tree_leaves(state.params)
        p_flats = _flatten_buckets(layout, p_leaves)
        p_tree = {
            k: lax.dynamic_slice_in_dim(f, idx * b.shard, b.shard)
            for k, f, b in zip(keys, p_flats, layout.buckets)
        }
        # The 1/N update: same elementwise math as the replicated step,
        # restricted to the shard this replica owns.
        updates, opt_state = optimizer.update(g_tree, state.opt_state, p_tree)
        new_p = optax.apply_updates(p_tree, updates)
        gathered = _gather_buckets(
            [new_p[k] for k in keys], sequential=not overlap
        )
        params = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(state.params),
            _unflatten_buckets(layout, gathered, p_leaves),
        )
        # SyncBN-style non-gradient stats averaging, exactly as ddp.py.
        new_ms = jax.tree.map(
            lambda v: lax.pmean(v.astype(jnp.float32), axes), new_ms
        )
        metrics = StepMetrics(
            loss=lax.pmean(loss, axes),
            accuracy=lax.psum(correct, axes) / (n_labels * world),
            grad_norm=grad_norm,
        )
        return TrainState(state.step + 1, params, opt_state, new_ms), metrics

    ospecs = opt_state_specs(optimizer, layout)
    state_specs = TrainState(
        step=P(), params=P(), opt_state=ospecs, model_state=P()
    )
    bspec = P(axes)
    sharded = jax.shard_map(
        per_shard_step,
        mesh=mesh,
        in_specs=(state_specs, bspec, bspec),
        out_specs=(state_specs, P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def zero_gspmd_update(
    optimizer, layout: BucketLayout, mesh: Mesh, grads, opt_state, params
):
    """The in-graph GSPMD expression of the sharded update (used by the
    causal LM's jit-level step, models/lm.py).

    Gradients arrive already reduced (the shard_map transpose psums
    them); constraining the flat buckets to ``P('data')`` is a free
    replicated→sharded reshard, after which the SPMD partitioner runs
    the update math and lays the moments out 1/N per device. The final
    replicated constraint on the new params is the derived all-gather.
    Returns ``(new_params, new_opt_state)``.
    """
    shard = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    keys = opt_keys(layout)
    g_leaves, tdef = jax.tree_util.tree_flatten(grads)
    p_leaves = jax.tree_util.tree_leaves(params)
    g_tree = {
        k: lax.with_sharding_constraint(f, shard)
        for k, f in zip(keys, _flatten_buckets(layout, g_leaves))
    }
    p_tree = {
        k: lax.with_sharding_constraint(f, shard)
        for k, f in zip(keys, _flatten_buckets(layout, p_leaves))
    }
    updates, new_opt = optimizer.update(g_tree, opt_state, p_tree)
    # Moments REST sharded between steps — without the constraint the
    # partitioner may replicate them on output and the memory win
    # silently evaporates.
    new_opt = jax.tree.map(
        lambda x: lax.with_sharding_constraint(x, shard)
        if getattr(x, "ndim", 0)
        else x,
        new_opt,
    )
    new_flats = optax.apply_updates(p_tree, updates)
    new_flats = [
        lax.with_sharding_constraint(new_flats[k], rep) for k in keys
    ]
    new_params = jax.tree_util.tree_unflatten(
        tdef, _unflatten_buckets(layout, new_flats, p_leaves)
    )
    return new_params, new_opt


# ---- elastic restore: re-bucket when the world size changed ---------

_BUCKET_KEY_RE = re.compile(r"^b\d{3}$")


def _path_bucket_key(path) -> str | None:
    """The ``b000``-style bucket key on a tree path, if any (the flat
    state leaves live under dict keys named by ``_opt_key``)."""
    for k in path:
        key = str(getattr(k, "key", k))
        if _BUCKET_KEY_RE.fullmatch(key):
            return key
    return None


@dataclasses.dataclass
class ZeroElasticReshaper:
    """Restore-time RE-BUCKETING for world-shape-agnostic checkpoints.

    Everything else in a zero run reshards on load (params and scalars
    are replicated; Orbax templates them onto the live mesh — the
    tests/test_elastic_shard.py mechanism). The flat optimizer buckets
    cannot: their GLOBAL shapes are world-dependent (``padded`` rounds
    each bucket's ``total`` up to a multiple of the replica count), so
    a checkpoint saved at world 2 literally has different array shapes
    than world 1's layout and no resharding can bridge them. Bucket
    *assignment* (which leaves, in what order, with what totals) is
    world-independent — ``build_layout`` never consults the world for
    it — so the bridge is pure padding arithmetic:

        saved ``[padded_old]`` → strip to ``[total]`` (the pad region
        is zeros end to end, the ``Bucket`` contract) → re-pad to
        ``[padded_new]`` → place 1/N over the live ``data`` axis.

    ``plan`` inspects the checkpoint's opt_state *metadata* (no array
    reads) and returns an abstract restore tree in the SAVED shapes on
    single-device placements — or None when shapes already match (the
    common, non-resized restore pays nothing). ``apply`` then performs
    the re-bucket on the host-restored values. A bucket-STRUCTURE
    mismatch (``--zero_bucket_mb`` or the model changed, not the
    world) is rejected — that state genuinely cannot be reinterpreted.

    Bit-identity contract (pinned by tests/test_elastic.py): the
    re-bucketed state equals a fresh sharding of the merged state —
    zeros in, zeros out, values untouched.
    """

    optimizer: Any
    layout: BucketLayout
    mesh: Mesh

    def _live_padded(self) -> dict[str, int]:
        return {
            _opt_key(i): b.padded
            for i, b in enumerate(self.layout.buckets)
        }

    def plan(self, meta_opt) -> Any | None:
        """Checkpoint opt_state metadata → abstract restore tree in the
        saved bucket shapes, or None when no re-bucket is needed."""
        saved: dict[str, int] = {}

        def visit(path, leaf):
            k = _path_bucket_key(path)
            shape = tuple(getattr(leaf, "shape", ()) or ())
            if k is not None and len(shape) == 1:
                saved[k] = int(shape[0])

        jax.tree_util.tree_map_with_path(visit, meta_opt)
        if not saved:
            return None  # not a bucketed opt_state — nothing to plan
        new = self._live_padded()
        if set(saved) != set(new):
            raise ValueError(
                f"checkpoint opt_state has buckets {sorted(saved)} but "
                f"the live layout has {sorted(new)} — the bucket "
                "STRUCTURE changed (--zero_bucket_mb or the model), "
                "not just the world size; elastic re-bucketing only "
                "absorbs world changes. --reset_opt_state keeps the "
                "weights and drops the moments."
            )
        totals = {
            _opt_key(i): b.total
            for i, b in enumerate(self.layout.buckets)
        }
        short = {k: p for k, p in saved.items() if p < totals[k]}
        if short:
            raise ValueError(
                f"checkpoint buckets {sorted(short)} are smaller than "
                "their live totals — the parameter tree changed since "
                "the save; this is not a world resize"
            )
        if all(saved[k] == new[k] for k in new):
            return None  # same world shape — restore templated as usual
        # REPLICATED placements over the live mesh, not a per-process
        # local device: every rank must hand Orbax the SAME global
        # shardings or a multi-process restore desyncs — and a fully-
        # replicated array is host-readable on every process, which is
        # exactly what ``apply`` needs for the re-pad arithmetic.
        rep = NamedSharding(self.mesh, P())
        tpl = _opt_template(self.optimizer, self.layout)

        def override(path, leaf):
            k = _path_bucket_key(path)
            shape = (
                (saved[k],)
                if k is not None and len(leaf.shape) == 1
                else leaf.shape
            )
            return jax.ShapeDtypeStruct(shape, leaf.dtype, sharding=rep)

        return jax.tree_util.tree_map_with_path(override, tpl)

    def apply(self, restored_opt):
        """Host-restored old-world state → live data-sharded state."""
        shard = NamedSharding(self.mesh, P("data"))
        rep = NamedSharding(self.mesh, P())
        totals = {
            _opt_key(i): b.total
            for i, b in enumerate(self.layout.buckets)
        }
        padded = self._live_padded()

        def fix(path, leaf):
            arr = np.asarray(leaf)
            k = _path_bucket_key(path)
            if k is not None and arr.ndim == 1:
                arr = arr[: totals[k]]
                pad = padded[k] - totals[k]
                if pad:
                    arr = np.concatenate(
                        [arr, np.zeros((pad,), arr.dtype)]
                    )
                sharding = shard
            else:
                sharding = rep
            # make_array_from_callback assembles the global array from
            # addressable shards only, so the same spelling is correct
            # single- and multi-process (each process holds the full
            # host copy — the restore placed it single-device locally).
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx, a=arr: a[idx]
            )

        return jax.tree_util.tree_map_with_path(fix, restored_opt)


# ---- accounting: what the strategy moves and what it holds ----------


def ddp_comm_bytes(params, world: int) -> dict[str, int]:
    """Per-step per-replica collective payload of the ddp baseline,
    ring model: all-reduce = 2·(N−1)/N of the fp32 gradient bytes."""
    n = sum(
        int(jnp.size(leaf)) for leaf in jax.tree_util.tree_leaves(params)
    )
    ar = int(2 * (world - 1) / max(1, world) * n * 4)
    return {
        "all_reduce": ar, "reduce_scatter": 0, "all_gather": 0,
        "total": ar,
    }


def zero_comm_bytes(
    layout: BucketLayout,
    world: int,
    *,
    grad_accum_steps: int = 1,
    gspmd: bool = False,
) -> dict[str, int]:
    """Per-step per-replica collective payload of the zero strategy.

    Explicit (shard_map) path: the all-reduce is GONE — replaced by a
    reduce-scatter per bucket per microbatch ((N−1)/N of the padded
    bytes each) plus one parameter all-gather. Ring-model total equals
    the ddp all-reduce at ``grad_accum_steps=1`` (RS + AG *is* an AR);
    the wins are the vanished redundant update compute, the 1/N
    moments, and the per-bucket scheduling freedom. The in-graph GSPMD
    path keeps the transpose's gradient all-reduce — ONE PER
    MICROBATCH under accumulation, exactly like the explicit path's
    scatters (models/lm.py backs through the shard_map forward inside
    each scan iteration) — and adds the parameter all-gather:
    memory-only win, priced honestly here.
    """
    b4 = layout.padded_total * 4
    frac = (world - 1) / max(1, world)
    if gspmd:
        ar = int(2 * frac * b4) * max(1, grad_accum_steps)
        rs = 0
    else:
        ar = 0
        rs = int(frac * b4) * max(1, grad_accum_steps)
    ag = int(frac * b4)
    return {
        "all_reduce": ar, "reduce_scatter": rs, "all_gather": ag,
        "total": ar + rs + ag,
    }


def opt_bytes_per_device(opt_state) -> int:
    """Optimizer-state memory high-water: max over devices of the
    bytes the state's live buffers actually hold there (per-shard
    accounting over the arrays' real shardings — replicated leaves
    count in full on every device, data-sharded flats count 1/N).
    One convention, one definition: the accounting itself lives in
    ``obs/xprof.max_device_buffer_bytes`` (shared with the device-
    memory sampler's live-buffer fallback)."""
    from ddp_tpu.obs.xprof import max_device_buffer_bytes

    return max_device_buffer_bytes(jax.tree_util.tree_leaves(opt_state))

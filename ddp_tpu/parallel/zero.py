"""ZeRO-style weight-update sharding on the data-parallel path.

The reference (and our own ``parallel/ddp.py``) ends every step the
same way DDP always has: all-reduce the FULL gradient tree, then let
every replica redundantly run the identical optimizer update over the
identical replicated moments — N copies of the same math and N copies
of the Adam moments. "Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training" (PAPERS.md #3) removes exactly that
redundancy without touching the model math:

    reduce-scatter(grads)  →  each replica owns 1/N of every bucket
    sharded optimizer update  →  moments + update math are 1/N
    all-gather(params)     →  replicas re-converge, bit-for-bit

Params stay replicated at rest (this is ZeRO stage 1, not FSDP — the
``fsdp`` axis already covers stage 3 by annotation); only the
optimizer state and the update compute shard. Total collective payload
is unchanged — a ring all-reduce IS a reduce-scatter + all-gather —
but the *all-reduce* disappears, the moments memory divides by N, and
the two half-collectives become independently schedulable per bucket.

Gradients are packed into size-targeted **buckets** (``--zero_bucket_mb``,
the knob DDP's C++ reducer calls ``bucket_cap_mb``): each bucket is a
flat fp32 vector padded to a multiple of the replica count, so a
parameter count not divisible by the axis size costs padding, never a
wrong answer. Bucketing is what buys comm/compute overlap: each
bucket's reduce-scatter depends only on ITS leaves' gradients, so the
scheduler may dispatch bucket k's scatter while backward compute for
bucket k+1's layers is still in flight — and the all-gathers pipeline
against the sharded updates the same way. ``overlap=False`` builds the
control: an ``optimization_barrier`` fence after the full backward
plus a serial chain through the collectives, which is what bench.py
measures the overlapped step against (prove it, don't assume it).

Two expressions of the same decomposition, per the paper's framing:

- ``make_zero_train_step`` — the explicit-collective ``shard_map``
  step (the DDP image family): ``lax.psum_scatter`` / sharded optax
  update / ``lax.all_gather``, every collective visible.
- ``zero_gspmd_update`` — the in-graph GSPMD expression (the causal
  LM's jit-level step): the same bucket layout pinned with
  ``with_sharding_constraint`` so the SPMD partitioner shards the
  update math and the moments, and derives the parameter all-gather.

The optimizer contract: the update rule must be *elementwise* (sgd,
momentum, adam, adamw, weight decay, schedules) because it runs on
1/N flat shards — transforms that couple elements across the tree
(full-shape parameter EMA) are rejected at construction
(train/optim.py ``check_zero_compatible``). Global-norm clipping IS
composable despite being cross-element: the norm is one scalar, and
the scattered shards partition the reduced gradient exactly, so
``psum`` of per-shard squared sums over the shard axis is the global
norm — ``grad_clip_norm`` applies it in-step, parity-pinned against
the ddp path's ``optax.clip_by_global_norm``.

Two pod-scale extensions ride the same bucket layout (ROADMAP item 3):

- ``gather_dtype=bf16`` — the cross-replica sharding paper's headline
  win (PAPERS.md #3): the updated 1/N shards cast ONCE and all-gather
  half-width, while the optimizer math and the fp32 **master shards**
  (kept in ``opt_state['master']``, data-sharded like the moments)
  stay full precision — the forward sees bf16-rounded params, the
  update never does, so rounding cannot compound across steps. The
  fp32 default is bit-identical to the pre-flag path (same opt_state
  schema, same HLO).
- ``hier`` (a mesh with a ``dcn`` axis > 1) — hierarchical collectives,
  the topology the pjit/TPUv4 paper scales on (PAPERS.md #5):
  reduce-scatter within a slice over ICI, all-reduce only the 1/N
  shards across slices over DCN, all-gather within the slice. Cross-
  slice traffic drops from the full gradient to 1/N of it;
  ``zero_comm_bytes`` prices the split per axis and the HLO cross-
  check (obs/xprof.py replica-group attribution) measures it.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddp_tpu.parallel.common import (
    check_accum_divisible,
    make_loss_fn,
)
from ddp_tpu.parallel.ddp import StepMetrics, TrainState
from ddp_tpu.runtime.mesh import data_axes


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One flat fp32 reduce-scatter unit: a contiguous run of leaves.

    ``padded`` rounds ``total`` up to a multiple of the replica count
    so the scatter tiles evenly; the pad region carries zeros end to
    end (zero grads → zero moments → zero update), so indivisible
    parameter counts are correct by construction.
    """

    leaf_ids: tuple[int, ...]
    sizes: tuple[int, ...]
    total: int
    padded: int
    shard: int


@dataclasses.dataclass(frozen=True)
class BucketLayout:
    """Assignment of every param leaf (flatten order) to a bucket."""

    buckets: tuple[Bucket, ...]
    num_leaves: int
    world: int

    @property
    def padded_total(self) -> int:
        return sum(b.padded for b in self.buckets)


def _opt_key(i: int) -> str:
    return f"b{i:03d}"


def opt_keys(layout: BucketLayout) -> list[str]:
    return [_opt_key(i) for i in range(len(layout.buckets))]


def build_layout(
    params, world: int, *, bucket_mb: float = 4.0
) -> BucketLayout:
    """Greedy size-targeted bucketing over the param leaves.

    ``params`` may be arrays or ``ShapeDtypeStruct``s — only shapes
    matter. Leaves pack in flatten order until a bucket crosses the
    byte target (fp32 accounting — the reduction dtype); a leaf larger
    than the target gets its own bucket rather than being split.
    """
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    if bucket_mb <= 0:
        raise ValueError(f"bucket_mb must be > 0, got {bucket_mb}")
    leaves = jax.tree_util.tree_leaves(params)
    if not leaves:
        raise ValueError("empty parameter tree — nothing to shard")
    target_elems = max(1, int(bucket_mb * 2**20) // 4)
    buckets: list[Bucket] = []
    ids: list[int] = []
    sizes: list[int] = []
    total = 0

    def close():
        nonlocal ids, sizes, total
        if not ids:
            return
        padded = -(-total // world) * world
        buckets.append(
            Bucket(
                leaf_ids=tuple(ids),
                sizes=tuple(sizes),
                total=total,
                padded=padded,
                shard=padded // world,
            )
        )
        ids, sizes, total = [], [], 0

    for i, leaf in enumerate(leaves):
        n = 1
        for s in leaf.shape:
            n *= int(s)
        if n >= target_elems:
            # An oversized leaf gets its OWN bucket: trapping the
            # accumulated small leaves behind it would serialize their
            # scatter on the big transfer.
            close()
            ids, sizes, total = [i], [n], n
            close()
            continue
        ids.append(i)
        sizes.append(n)
        total += n
        if total >= target_elems:
            close()
    close()
    return BucketLayout(
        buckets=tuple(buckets), num_leaves=len(leaves), world=world
    )


def check_zero_mesh(mesh: Mesh, *, allow_model_axes: bool = False) -> None:
    """The sharded update scatters over the replica axes (``data``,
    hierarchically ``dcn``×``data``): fsdp/expert/pipe already own
    their own optimizer-state story (fsdp IS ZeRO-3; expert/pipe shard
    state by their rule layouts). ``allow_model_axes=True`` admits
    populated ``model``/``seq`` axes — the GSPMD expression shards the
    buckets over ``data`` while REPLICATING them over model/seq (those
    axes see identical gradients, so the flat buckets are uniform
    across them by construction; the causal-LM composition pins it)."""
    reject = ("fsdp", "expert", "pipe")
    if not allow_model_axes:
        reject = ("model", "seq") + reject
    bad = {
        a: int(mesh.shape[a])
        for a in reject
        if mesh.shape.get(a, 1) > 1
    }
    if bad:
        raise ValueError(
            f"--parallel zero shards the weight update over the data "
            f"axis only; {bad} already shard optimizer state their own "
            "way — drop the axes or the flag"
        )


GATHER_DTYPES = {"fp32": jnp.float32, "bf16": jnp.bfloat16}


def _resolve_gather_dtype(gather_dtype):
    """'fp32'/'bf16' or a jnp dtype → (jnp dtype, master_mode)."""
    if isinstance(gather_dtype, str):
        if gather_dtype not in GATHER_DTYPES:
            raise ValueError(
                f"gather_dtype must be one of {sorted(GATHER_DTYPES)}, "
                f"got {gather_dtype!r}"
            )
        gather_dtype = GATHER_DTYPES[gather_dtype]
    dt = jnp.dtype(gather_dtype)
    if dt not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        raise ValueError(
            f"gather_dtype must be float32 or bfloat16, got {dt}"
        )
    return dt, dt == jnp.dtype(jnp.bfloat16)


def _flatten_buckets(layout: BucketLayout, leaves) -> list[jax.Array]:
    """Leaf list → one flat fp32 ``[padded]`` vector per bucket."""
    flats = []
    for b in layout.buckets:
        parts = [
            leaves[i].astype(jnp.float32).reshape(-1) for i in b.leaf_ids
        ]
        pad = b.padded - b.total
        if pad:
            parts.append(jnp.zeros((pad,), jnp.float32))
        flats.append(parts[0] if len(parts) == 1 else jnp.concatenate(parts))
    return flats


def _unflatten_buckets(layout: BucketLayout, flats, like_leaves):
    """Flat ``[padded]`` vectors → leaf list shaped/typed like
    ``like_leaves`` (static slices; the pad tail is dropped)."""
    out: list[Any] = [None] * layout.num_leaves
    for b, flat in zip(layout.buckets, flats):
        off = 0
        for i, n in zip(b.leaf_ids, b.sizes):
            like = like_leaves[i]
            out[i] = (
                flat[off : off + n].reshape(like.shape).astype(like.dtype)
            )
            off += n
    return out


def _opt_template(optimizer, layout: BucketLayout, *, master: bool = False):
    """abstract optimizer state over the flat buckets + the elementwise
    contract check: every state leaf must be a scalar (schedule/Adam
    counts) or shaped exactly like its bucket — anything else means
    the update couples elements across the tree and cannot run on
    1/N shards. Shape-based, so it catches full-shape STATE (a param
    EMA of the original tree) but not STATELESS cross-element
    transforms: a chained ``optax.clip_by_global_norm`` carries
    EmptyState, slips this check, and would silently clip PER SHARD —
    use the steps' ``grad_clip_norm`` knob (which computes the true
    global norm from the shards) instead of chaining; direct-API
    callers composing their own optax chains own the elementwise
    contract for stateless members.

    ``master=True`` is the bf16-gather layout: the tree grows a
    sibling ``{"base": <optax state>, "master": {b###: [padded]}}``
    level holding the fp32 master shards — bucket-shaped, so the same
    contract (and the elastic re-bucketer's pad arithmetic) covers
    them."""
    flats = {
        _opt_key(i): jax.ShapeDtypeStruct((b.padded,), jnp.float32)
        for i, b in enumerate(layout.buckets)
    }
    tpl = jax.eval_shape(optimizer.init, flats)
    allowed = {v.shape for v in flats.values()}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tpl)[0]:
        if len(leaf.shape) and leaf.shape not in allowed:
            name = jax.tree_util.keystr(path)
            raise ValueError(
                f"optimizer state leaf {name} has shape {leaf.shape}, "
                "not the flat bucket shape — the zero update runs "
                "elementwise on 1/N shards (sgd/momentum/adam/adamw "
                "compose; parameter EMA does not — "
                "train/optim.check_zero_compatible)"
            )
    if master:
        return {"base": tpl, "master": dict(flats)}
    return tpl


def opt_state_specs(
    optimizer,
    layout: BucketLayout,
    *,
    shard_axes: tuple[str, ...] = ("data",),
    gather_dtype=jnp.float32,
):
    """PartitionSpec tree for the resting optimizer state: flat bucket
    leaves shard dim 0 over ``shard_axes`` (the scatter group — just
    ``data`` on flat and hierarchical meshes, ``('dcn','data')`` when
    one flat scatter spans the pod), scalars replicate. bf16-gather
    mode adds the fp32 master shards under ``'master'``, laid out
    exactly like the moments."""
    _, master = _resolve_gather_dtype(gather_dtype)
    tpl = _opt_template(optimizer, layout, master=master)
    return jax.tree.map(
        lambda x: P(shard_axes) if len(x.shape) else P(), tpl
    )


def create_zero_opt_state(
    params,
    optimizer,
    mesh: Mesh,
    layout: BucketLayout,
    *,
    shard_axes: tuple[str, ...] = ("data",),
    gather_dtype=jnp.float32,
):
    """Initialize the optimizer state directly into the sharded layout.

    State leaves are GLOBAL ``[padded]`` arrays resting sharded over
    the scatter axes (1/N per device — the memory win is at rest, not
    just in the step); scalars replicate. bf16-gather mode seeds the
    fp32 master shards from the initial params under ``'master'``.
    Works multi-process: every process computes the same init under
    one jit with explicit out_shardings.
    """
    _, master = _resolve_gather_dtype(gather_dtype)
    leaves = jax.tree_util.tree_leaves(params)
    flats = dict(zip(opt_keys(layout), _flatten_buckets(layout, leaves)))
    specs = opt_state_specs(
        optimizer, layout, shard_axes=shard_axes, gather_dtype=gather_dtype
    )
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)
    init = (
        (lambda f: {"base": optimizer.init(f), "master": f})
        if master
        else optimizer.init
    )
    return jax.jit(init, out_shardings=shardings)(flats)


def _replica_geometry(
    mesh: Mesh, hier: bool | None
) -> tuple[int, bool, tuple[str, ...]]:
    """(slice count, hierarchical?, scatter axes) for a zero mesh.

    ``hier=None`` auto-resolves: a populated ``dcn`` axis means the
    two-level step (scatter within a slice over ``data``, shard
    exchange across slices). ``hier=False`` on a dcn mesh is the flat
    control — ONE scatter spanning ``('dcn', 'data')``, every byte
    riding the slow fabric (what bench.py measures hier against).
    """
    dcn = int(mesh.shape.get("dcn", 1))
    hier = (dcn > 1) if hier is None else (bool(hier) and dcn > 1)
    scatter_axes = ("dcn", "data") if (dcn > 1 and not hier) else ("data",)
    return dcn, hier, scatter_axes


def create_zero_state(
    model,
    optimizer: optax.GradientTransformation,
    sample_input,
    mesh: Mesh,
    *,
    seed: int = 0,
    bucket_mb: float = 4.0,
    gather_dtype=jnp.float32,
    hier: bool | None = None,
) -> tuple[TrainState, BucketLayout]:
    """Replicated params + step + model_state, data-sharded flat
    optimizer state. The placements ARE the contract (checkpoint
    restores template on them, like the fsdp family).

    The layout's ``world`` is the SCATTER group size: the ``data``
    axis alone on flat and hierarchical meshes (hier shards stay
    1/|data| — the dcn exchange reduces them in place, it does not
    re-shard), ``dcn×data`` when a flat scatter spans the pod.
    """
    from ddp_tpu.parallel.common import _train_kwarg

    check_zero_mesh(mesh)
    _, _, scatter_axes = _replica_geometry(mesh, hier)
    variables = model.init(
        jax.random.key(seed), sample_input, **_train_kwarg(model, False)
    )
    params = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}
    shard_world = int(
        np.prod([mesh.shape[a] for a in scatter_axes])
    )
    layout = build_layout(params, shard_world, bucket_mb=bucket_mb)
    rep = NamedSharding(mesh, P())
    put = lambda t: jax.tree.map(lambda x: jax.device_put(x, rep), t)
    params = put(params)
    state = TrainState(
        step=jax.device_put(jnp.zeros((), jnp.int32), rep),
        params=params,
        opt_state=create_zero_opt_state(
            params, optimizer, mesh, layout,
            shard_axes=scatter_axes, gather_dtype=gather_dtype,
        ),
        model_state=put(model_state),
    )
    return state, layout


def _scatter_buckets(
    flats,
    *,
    sequential: bool = False,
    axes: tuple[str, ...] = ("data",),
    dcn_axis: str | None = None,
):
    """Reduce-scatter each bucket over ``axes`` (raw SUMS — callers
    divide by the replica count). ``dcn_axis`` is the hierarchical
    second level: after the within-slice scatter over ICI, all-reduce
    the 1/N shard across slices — the ONLY bytes that touch the slow
    fabric, 1/N of the flat payload. ``sequential=True`` is the
    no-overlap control: a barrier fences the collectives behind the
    ENTIRE backward, and each scatter chains on its predecessor, so
    nothing can hide under compute."""
    if sequential and len(flats) > 1:
        flats = list(lax.optimization_barrier(tuple(flats)))
    out = []
    prev = None
    for f in flats:
        if sequential and prev is not None:
            f, _ = lax.optimization_barrier((f, prev))
        s = lax.psum_scatter(f, axes, scatter_dimension=0, tiled=True)
        if dcn_axis is not None:
            s = lax.psum(s, dcn_axis)
        out.append(s)
        prev = s
    return out


def _gather_buckets(
    shards,
    *,
    sequential: bool = False,
    axes: tuple[str, ...] = ("data",),
    gather_dtype=jnp.float32,
):
    """All-gather each bucket's updated param shard back to ``[padded]``
    (tiled — member i contributes block i, the psum_scatter order).
    ``gather_dtype=bf16`` casts the shard ONCE before the collective,
    so the dominant all-gather moves half the bytes; the caller's
    unflatten casts back to the param dtype. The half-width value
    rides the wire BITCAST to uint16: a bf16 all-gather is re-widened
    to fp32 by XLA:CPU's float-normalization pass (measured — same
    values, double the bytes, and the HLO cross-check caught it),
    while an integer collective is left alone on every backend; the
    bitcasts are free reinterpretations on either side."""
    half = jnp.dtype(gather_dtype) != jnp.dtype(jnp.float32)
    out = []
    prev = None
    for s in shards:
        if sequential and prev is not None:
            s, _ = lax.optimization_barrier((s, prev))
        wire = s.astype(gather_dtype)
        if half:
            wire = lax.bitcast_convert_type(wire, jnp.uint16)
        g = lax.all_gather(wire, axes, axis=0, tiled=True)
        if half:
            g = lax.bitcast_convert_type(g, gather_dtype)
        out.append(g)
        prev = g
    return out


def make_zero_train_step(
    model,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    layout: BucketLayout,
    *,
    compute_dtype=jnp.float32,
    donate: bool = True,
    seed: int = 0,
    aux_loss_weight: float = 0.01,
    grad_accum_steps: int = 1,
    augment_fn=None,
    label_smoothing: float = 0.0,
    overlap: bool = True,
    gather_dtype=jnp.float32,
    grad_clip_norm: float = 0.0,
    hier: bool | None = None,
) -> Callable[[TrainState, jax.Array, jax.Array], tuple[TrainState, StepMetrics]]:
    """The explicit-collective (shard_map) zero step — ``parallel/ddp.py``
    ``make_train_step``'s contract with the update stage swapped:
    ``pmean(grads) → update`` becomes ``psum_scatter → 1/N update →
    all_gather``. Loss/accuracy semantics are identical (pinned by
    tests/test_zero.py and the 2-process gloo spawn pins).

    ``grad_accum_steps=k`` accumulates into the SCATTERED shards — one
    reduce-scatter per microbatch, accumulator buffers 1/N — so the
    memory win survives accumulation (a full-tree accumulator would
    undo it).

    ``gather_dtype=bf16`` halves the parameter all-gather: the update
    runs fp32 on the master shards in ``opt_state['master']`` and only
    the cast result rides the wire (module docstring). ``hier`` (auto
    on a ``dcn`` mesh): within-slice scatter/gather over ICI plus a
    1/N cross-slice shard exchange over DCN. ``grad_clip_norm > 0``
    applies optax's global-norm clip semantics from the scattered
    shards — the psum of per-shard squared sums IS the global norm.
    """
    check_zero_mesh(mesh)
    axes = data_axes(mesh)
    gdtype, master_mode = _resolve_gather_dtype(gather_dtype)
    n_slices, hier, scatter_axes = _replica_geometry(mesh, hier)
    world = int(np.prod([mesh.shape[a] for a in scatter_axes]))
    n_replicas = int(np.prod([mesh.shape[a] for a in axes]))
    dcn_axis = "dcn" if hier else None
    if world != layout.world:
        raise ValueError(
            f"layout built for world {layout.world}, the scatter group "
            f"{scatter_axes} is {world}"
        )
    keys = opt_keys(layout)
    loss_fn = make_loss_fn(
        model, compute_dtype, aux_loss_weight, augment_fn=augment_fn,
        label_smoothing=label_smoothing,
    )

    def per_shard_step(state: TrainState, images, labels):
        mutable = list(state.model_state.keys())
        rng = jax.random.fold_in(jax.random.key(seed), state.step)
        for a in axes:
            rng = jax.random.fold_in(rng, lax.axis_index(a))

        if grad_accum_steps == 1:
            (loss, (logits, new_ms)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params, state.model_state, images, labels, rng, mutable)
            correct = (jnp.argmax(logits.astype(jnp.float32), -1) == labels).sum()
            n_labels = labels.shape[0]
            # THE rework: where ddp.py all-reduces the full tree, each
            # bucket reduce-scatters independently — free to dispatch
            # while backward compute for later buckets is in flight.
            gshards = _scatter_buckets(
                _flatten_buckets(layout, jax.tree_util.tree_leaves(grads)),
                sequential=not overlap,
                axes=scatter_axes, dcn_axis=dcn_axis,
            )
            scale = 1.0 / n_replicas
        else:
            mb = check_accum_divisible(images.shape[0], grad_accum_steps)
            imgs = images.reshape(grad_accum_steps, mb, *images.shape[1:])
            lbls = labels.reshape(grad_accum_steps, mb)

            def micro(carry, xy):
                sh_acc, ms, loss_acc, correct_acc, i = carry
                x, y = xy
                (mloss, (mlogits, mms)), g = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(state.params, ms, x, y, jax.random.fold_in(rng, i), mutable)
                # Accumulate the SCATTERED shard, not the full tree:
                # the accumulator is 1/N per replica by construction.
                sh = _scatter_buckets(
                    _flatten_buckets(layout, jax.tree_util.tree_leaves(g)),
                    sequential=not overlap,
                    axes=scatter_axes, dcn_axis=dcn_axis,
                )
                c = (jnp.argmax(mlogits.astype(jnp.float32), -1) == y).sum()
                return (
                    [a + s for a, s in zip(sh_acc, sh)],
                    mms,
                    loss_acc + mloss,
                    correct_acc + c.astype(jnp.float32),
                    i + 1,
                ), None

            zero_sh = [
                jnp.zeros((b.shard,), jnp.float32) for b in layout.buckets
            ]
            (gshards, new_ms, loss_sum, correct, _), _ = lax.scan(
                micro,
                (
                    zero_sh,
                    state.model_state,
                    jnp.zeros((), jnp.float32),
                    jnp.zeros((), jnp.float32),
                    jnp.zeros((), jnp.int32),
                ),
                (imgs, lbls),
            )
            loss = loss_sum / grad_accum_steps
            n_labels = images.shape[0]
            scale = 1.0 / (n_replicas * grad_accum_steps)

        g_tree = {k: s * scale for k, s in zip(keys, gshards)}
        # Global grad norm from disjoint shards: one scalar psum over
        # the scatter group (hier shards are already globally reduced,
        # so the within-slice psum of disjoint blocks IS the norm —
        # summing over dcn too would count every slice's copy).
        local_sq = sum(jnp.sum(jnp.square(g)) for g in g_tree.values())
        grad_norm = jnp.sqrt(lax.psum(local_sq, scatter_axes))
        if grad_clip_norm:
            # optax.clip_by_global_norm semantics on the scattered
            # shards: one scalar, same (t / norm) * clip scaling —
            # parity-pinned against the ddp path's chained transform.
            g_tree = {
                k: jnp.where(
                    grad_norm < grad_clip_norm,
                    g,
                    (g / grad_norm) * grad_clip_norm,
                )
                for k, g in g_tree.items()
            }
        p_leaves = jax.tree_util.tree_leaves(state.params)
        if master_mode:
            # The fp32 master shards live in opt_state — the bf16
            # round-trip the gathered params took never feeds back
            # into the update math.
            base_opt = state.opt_state["base"]
            p_tree = state.opt_state["master"]
        else:
            # This replica's own param block, sliced locally (params
            # are replicated — no comm; block order is psum_scatter's
            # over the scatter group, slice-major when it spans dcn).
            idx = jnp.int32(0)
            for a in scatter_axes:
                idx = idx * mesh.shape[a] + lax.axis_index(a)
            base_opt = state.opt_state
            p_flats = _flatten_buckets(layout, p_leaves)
            p_tree = {
                k: lax.dynamic_slice_in_dim(f, idx * b.shard, b.shard)
                for k, f, b in zip(keys, p_flats, layout.buckets)
            }
        # The 1/N update: same elementwise math as the replicated step,
        # restricted to the shard this replica owns.
        updates, base_opt = optimizer.update(g_tree, base_opt, p_tree)
        new_p = optax.apply_updates(p_tree, updates)
        opt_state = (
            {"base": base_opt, "master": new_p} if master_mode else base_opt
        )
        gathered = _gather_buckets(
            [new_p[k] for k in keys],
            sequential=not overlap,
            axes=scatter_axes, gather_dtype=gdtype,
        )
        params = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(state.params),
            _unflatten_buckets(layout, gathered, p_leaves),
        )
        # SyncBN-style non-gradient stats averaging, exactly as ddp.py.
        new_ms = jax.tree.map(
            lambda v: lax.pmean(v.astype(jnp.float32), axes), new_ms
        )
        metrics = StepMetrics(
            loss=lax.pmean(loss, axes),
            accuracy=lax.psum(correct, axes) / (n_labels * n_replicas),
            grad_norm=grad_norm,
        )
        return TrainState(state.step + 1, params, opt_state, new_ms), metrics

    ospecs = opt_state_specs(
        optimizer, layout, shard_axes=scatter_axes, gather_dtype=gdtype
    )
    state_specs = TrainState(
        step=P(), params=P(), opt_state=ospecs, model_state=P()
    )
    bspec = P(axes)
    sharded = jax.shard_map(
        per_shard_step,
        mesh=mesh,
        in_specs=(state_specs, bspec, bspec),
        out_specs=(state_specs, P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def zero_gspmd_update(
    optimizer,
    layout: BucketLayout,
    mesh: Mesh,
    grads,
    opt_state,
    params,
    *,
    gather_dtype=jnp.float32,
    grad_clip_norm: float = 0.0,
):
    """The in-graph GSPMD expression of the sharded update (used by the
    causal LM's jit-level step, models/lm.py).

    Gradients arrive already reduced (the shard_map transpose psums
    them); constraining the flat buckets to ``P('data')`` is a free
    replicated→sharded reshard, after which the SPMD partitioner runs
    the update math and lays the moments out 1/N per device. On a mesh
    with populated ``model``/``seq`` axes the same constraint
    REPLICATES the buckets over them (the composition lift — gradients
    are uniform across those axes, so the sharded update is too). The
    final replicated constraint on the new params is the derived
    all-gather; ``gather_dtype=bf16`` casts the sharded result first,
    so the derived gather moves half the bytes while the fp32 master
    shards rest in ``opt_state['master']``. Returns
    ``(new_params, new_opt_state)``.
    """
    gdtype, master_mode = _resolve_gather_dtype(gather_dtype)
    shard = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    keys = opt_keys(layout)
    # Composition guard (measured on jax 0.4.37 / XLA:CPU, the round-6
    # partitioner bug family): on a mesh with populated non-data axes,
    # a `concatenate` feeding a sharded→replicated reshard chain
    # compiles to PARTIAL sums over the extra axis — every value
    # doubled at model=2. Pinning the freshly-concatenated flat to
    # replicated BEFORE the data-shard constraint forces the partition
    # boundary to the safe side; a no-op on pure-data meshes (where it
    # is skipped so the flat-mesh HLO stays byte-identical).
    multi_axis = any(
        s > 1 for a, s in mesh.shape.items() if a != "data"
    )

    def to_shard(f):
        if multi_axis:
            f = lax.with_sharding_constraint(f, rep)
        return lax.with_sharding_constraint(f, shard)

    g_leaves, tdef = jax.tree_util.tree_flatten(grads)
    p_leaves = jax.tree_util.tree_leaves(params)
    g_tree = {
        k: to_shard(f)
        for k, f in zip(keys, _flatten_buckets(layout, g_leaves))
    }
    if grad_clip_norm:
        # Global-norm clip at the jit level: the buckets partition the
        # gradient exactly (pad region is zeros), so the flat sums ARE
        # optax.clip_by_global_norm's norm — same (t / norm) * clip
        # scaling, parity-pinned against the replicated chain.
        g_norm = jnp.sqrt(
            sum(jnp.sum(jnp.square(g)) for g in g_tree.values())
        )
        g_tree = {
            k: jnp.where(
                g_norm < grad_clip_norm, g, (g / g_norm) * grad_clip_norm
            )
            for k, g in g_tree.items()
        }
    if master_mode:
        base_opt = opt_state["base"]
        p_tree = {
            k: lax.with_sharding_constraint(v, shard)
            for k, v in opt_state["master"].items()
        }
    else:
        base_opt = opt_state
        p_tree = {
            k: to_shard(f)
            for k, f in zip(keys, _flatten_buckets(layout, p_leaves))
        }
    updates, new_base = optimizer.update(g_tree, base_opt, p_tree)
    # Moments REST sharded between steps — without the constraint the
    # partitioner may replicate them on output and the memory win
    # silently evaporates.
    new_base = jax.tree.map(
        lambda x: lax.with_sharding_constraint(x, shard)
        if getattr(x, "ndim", 0)
        else x,
        new_base,
    )
    new_flats = optax.apply_updates(p_tree, updates)
    if master_mode:
        new_opt = {
            "base": new_base,
            "master": {
                k: lax.with_sharding_constraint(new_flats[k], shard)
                for k in keys
            },
        }
    else:
        new_opt = new_base
    if master_mode:
        # Half-width derived gather, wire-pinned as in
        # ``_gather_buckets``: cast the SHARDED result once, bitcast
        # to uint16 so no float pass re-widens the collective, and
        # let the replicated constraint derive the u16 all-gather.
        # The barrier pins WHERE the reshard happens — without it the
        # partitioner is free to site the all-gather anywhere along
        # the elementwise cast chain and picks the fp32 end (measured).
        def _half_gather(flat):
            w = lax.with_sharding_constraint(
                lax.bitcast_convert_type(flat.astype(gdtype), jnp.uint16),
                shard,
            )
            w = lax.optimization_barrier(w)
            return lax.bitcast_convert_type(
                lax.with_sharding_constraint(w, rep), gdtype
            )

        rep_flats = [_half_gather(new_flats[k]) for k in keys]
    else:
        rep_flats = [
            lax.with_sharding_constraint(new_flats[k], rep) for k in keys
        ]
    new_params = jax.tree_util.tree_unflatten(
        tdef, _unflatten_buckets(layout, rep_flats, p_leaves)
    )
    return new_params, new_opt


# ---- elastic restore: re-bucket when the world size changed ---------

_BUCKET_KEY_RE = re.compile(r"^b\d{3}$")


def _path_bucket_key(path) -> str | None:
    """The ``b000``-style bucket key on a tree path, if any (the flat
    state leaves live under dict keys named by ``_opt_key``)."""
    for k in path:
        key = str(getattr(k, "key", k))
        if _BUCKET_KEY_RE.fullmatch(key):
            return key
    return None


@dataclasses.dataclass
class ZeroElasticReshaper:
    """Restore-time RE-BUCKETING for world-shape-agnostic checkpoints.

    Everything else in a zero run reshards on load (params and scalars
    are replicated; Orbax templates them onto the live mesh — the
    tests/test_elastic_shard.py mechanism). The flat optimizer buckets
    cannot: their GLOBAL shapes are world-dependent (``padded`` rounds
    each bucket's ``total`` up to a multiple of the replica count), so
    a checkpoint saved at world 2 literally has different array shapes
    than world 1's layout and no resharding can bridge them. Bucket
    *assignment* (which leaves, in what order, with what totals) is
    world-independent — ``build_layout`` never consults the world for
    it — so the bridge is pure padding arithmetic:

        saved ``[padded_old]`` → strip to ``[total]`` (the pad region
        is zeros end to end, the ``Bucket`` contract) → re-pad to
        ``[padded_new]`` → place 1/N over the live ``data`` axis.

    ``plan`` inspects the checkpoint's opt_state *metadata* (no array
    reads) and returns an abstract restore tree in the SAVED shapes on
    single-device placements — or None when shapes already match (the
    common, non-resized restore pays nothing). ``apply`` then performs
    the re-bucket on the host-restored values. A bucket-STRUCTURE
    mismatch (``--zero_bucket_mb`` or the model changed, not the
    world) is rejected — that state genuinely cannot be reinterpreted.

    Bit-identity contract (pinned by tests/test_elastic.py): the
    re-bucketed state equals a fresh sharding of the merged state —
    zeros in, zeros out, values untouched.
    """

    optimizer: Any
    layout: BucketLayout
    mesh: Mesh
    # bf16-gather states carry the fp32 master shards under 'master' —
    # bucket-shaped like the moments, so the same pad arithmetic
    # re-buckets them; the template just has to include them.
    gather_dtype: Any = jnp.float32

    def _live_padded(self) -> dict[str, int]:
        return {
            _opt_key(i): b.padded
            for i, b in enumerate(self.layout.buckets)
        }

    def plan(self, meta_opt) -> Any | None:
        """Checkpoint opt_state metadata → abstract restore tree in the
        saved bucket shapes, or None when no re-bucket is needed."""
        saved: dict[str, int] = {}

        def visit(path, leaf):
            k = _path_bucket_key(path)
            shape = tuple(getattr(leaf, "shape", ()) or ())
            if k is not None and len(shape) == 1:
                saved[k] = int(shape[0])

        jax.tree_util.tree_map_with_path(visit, meta_opt)
        if not saved:
            return None  # not a bucketed opt_state — nothing to plan
        new = self._live_padded()
        if set(saved) != set(new):
            raise ValueError(
                f"checkpoint opt_state has buckets {sorted(saved)} but "
                f"the live layout has {sorted(new)} — the bucket "
                "STRUCTURE changed (--zero_bucket_mb or the model), "
                "not just the world size; elastic re-bucketing only "
                "absorbs world changes. --reset_opt_state keeps the "
                "weights and drops the moments."
            )
        totals = {
            _opt_key(i): b.total
            for i, b in enumerate(self.layout.buckets)
        }
        short = {k: p for k, p in saved.items() if p < totals[k]}
        if short:
            raise ValueError(
                f"checkpoint buckets {sorted(short)} are smaller than "
                "their live totals — the parameter tree changed since "
                "the save; this is not a world resize"
            )
        if all(saved[k] == new[k] for k in new):
            return None  # same world shape — restore templated as usual
        # REPLICATED placements over the live mesh, not a per-process
        # local device: every rank must hand Orbax the SAME global
        # shardings or a multi-process restore desyncs — and a fully-
        # replicated array is host-readable on every process, which is
        # exactly what ``apply`` needs for the re-pad arithmetic.
        rep = NamedSharding(self.mesh, P())
        _, master = _resolve_gather_dtype(self.gather_dtype)
        tpl = _opt_template(self.optimizer, self.layout, master=master)

        def override(path, leaf):
            k = _path_bucket_key(path)
            shape = (
                (saved[k],)
                if k is not None and len(leaf.shape) == 1
                else leaf.shape
            )
            return jax.ShapeDtypeStruct(shape, leaf.dtype, sharding=rep)

        return jax.tree_util.tree_map_with_path(override, tpl)

    def apply(self, restored_opt):
        """Host-restored old-world state → live data-sharded state."""
        shard = NamedSharding(self.mesh, P("data"))
        rep = NamedSharding(self.mesh, P())
        totals = {
            _opt_key(i): b.total
            for i, b in enumerate(self.layout.buckets)
        }
        padded = self._live_padded()

        def fix(path, leaf):
            arr = np.asarray(leaf)
            k = _path_bucket_key(path)
            if k is not None and arr.ndim == 1:
                arr = arr[: totals[k]]
                pad = padded[k] - totals[k]
                if pad:
                    arr = np.concatenate(
                        [arr, np.zeros((pad,), arr.dtype)]
                    )
                sharding = shard
            else:
                sharding = rep
            # make_array_from_callback assembles the global array from
            # addressable shards only, so the same spelling is correct
            # single- and multi-process (each process holds the full
            # host copy — the restore placed it single-device locally).
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx, a=arr: a[idx]
            )

        return jax.tree_util.tree_map_with_path(fix, restored_opt)


# ---- accounting: what the strategy moves and what it holds ----------


def ddp_comm_bytes(params, world: int) -> dict[str, int]:
    """Per-step per-replica collective payload of the ddp baseline,
    ring model: all-reduce = 2·(N−1)/N of the fp32 gradient bytes."""
    n = sum(
        int(jnp.size(leaf)) for leaf in jax.tree_util.tree_leaves(params)
    )
    ar = int(2 * (world - 1) / max(1, world) * n * 4)
    return {
        "all_reduce": ar, "reduce_scatter": 0, "all_gather": 0,
        "total": ar,
    }


def zero_comm_bytes(
    layout: BucketLayout,
    world: int,
    *,
    grad_accum_steps: int = 1,
    gspmd: bool = False,
    dcn: int = 1,
    gather_dtype=jnp.float32,
    hier: bool = True,
) -> dict[str, int]:
    """Per-step per-replica collective payload of the zero strategy.

    Explicit (shard_map) path: the all-reduce is GONE — replaced by a
    reduce-scatter per bucket per microbatch ((N−1)/N of the padded
    bytes each) plus one parameter all-gather. Ring-model total equals
    the ddp all-reduce at ``grad_accum_steps=1`` (RS + AG *is* an AR);
    the wins are the vanished redundant update compute, the 1/N
    moments, and the per-bucket scheduling freedom. The in-graph GSPMD
    path keeps the transpose's gradient all-reduce — ONE PER
    MICROBATCH under accumulation, exactly like the explicit path's
    scatters (models/lm.py backs through the shard_map forward inside
    each scan iteration) — and adds the parameter all-gather:
    memory-only win, priced honestly here.

    ``gather_dtype=bf16`` halves the all-gather term — and nothing
    else: the scatters still move fp32 gradients.

    ``dcn > 1`` prices the pod: ``world`` is the ICI (``data``) axis,
    ``dcn`` the slice count. ``hier=True`` (the two-level step) adds a
    ``by_axis`` split — the within-slice scatter/gather ride ``ici``
    and only the 1/world shard exchange (2·(S−1)/S of ``padded/world``
    bytes per microbatch) rides ``dcn``. ``hier=False`` is the flat
    control: one scatter group spans the pod, and every byte is
    attributed to ``dcn`` — a flat collective over slices is DCN-bound,
    which is exactly the pathology the hierarchy removes.
    """
    gdtype, _ = _resolve_gather_dtype(gather_dtype)
    b4 = layout.padded_total * 4
    bg = layout.padded_total * jnp.dtype(gdtype).itemsize
    k = max(1, grad_accum_steps)

    def _bucket(ar=0, rs=0, ag=0):
        return {
            "all_reduce": int(ar), "reduce_scatter": int(rs),
            "all_gather": int(ag), "total": int(ar) + int(rs) + int(ag),
        }

    if dcn <= 1:
        frac = (world - 1) / max(1, world)
        if gspmd:
            return _bucket(ar=int(2 * frac * b4) * k, ag=int(frac * bg))
        return _bucket(rs=int(frac * b4) * k, ag=int(frac * bg))

    n = world * dcn
    if gspmd or not hier:
        # One flat group spans the slices: all of it crosses DCN.
        frac = (n - 1) / n
        if gspmd:
            dcn_b = _bucket(ar=int(2 * frac * b4) * k, ag=int(frac * bg))
        else:
            dcn_b = _bucket(rs=int(frac * b4) * k, ag=int(frac * bg))
        ici_b = _bucket()
    else:
        ifrac = (world - 1) / world
        dfrac = (dcn - 1) / dcn
        ici_b = _bucket(rs=int(ifrac * b4) * k, ag=int(ifrac * bg))
        dcn_b = _bucket(ar=int(2 * dfrac * (b4 // world)) * k)
    out = {
        key: ici_b[key] + dcn_b[key]
        for key in ("all_reduce", "reduce_scatter", "all_gather", "total")
    }
    out["by_axis"] = {"ici": ici_b, "dcn": dcn_b}
    return out


def opt_bytes_per_device(opt_state) -> int:
    """Optimizer-state memory high-water: max over devices of the
    bytes the state's live buffers actually hold there (per-shard
    accounting over the arrays' real shardings — replicated leaves
    count in full on every device, data-sharded flats count 1/N).
    One convention, one definition: the accounting itself lives in
    ``obs/xprof.max_device_buffer_bytes`` (shared with the device-
    memory sampler's live-buffer fallback)."""
    from ddp_tpu.obs.xprof import max_device_buffer_bytes

    return max_device_buffer_bytes(jax.tree_util.tree_leaves(opt_state))

"""Shared stage-parameter sharding for the pipeline model families.

Every pipelined model (ViT — models/pipeline_vit.py; causal LM —
models/pipeline_lm.py) stacks its uniform stage bodies on a leading
stage dim sharded over ``pipe`` and optionally ZeRO-shards each stage's
leaves over ``fsdp``. The spec computation and the gather/scatter pair
that moves params between their resting layout and the stage program
are family-independent and live here so a fix in one family cannot
miss the other.
"""

from __future__ import annotations

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

FSDP_MIN_SIZE = 2**12  # leaves smaller than this stay replicated


def pipe_batch_axes(mesh) -> tuple:
    """Axes the pipe family shards its batch over (``expert``/``seq``
    never compose with pipe)."""
    return tuple(a for a in ("data", "fsdp") if mesh.shape.get(a, 1) > 1)


def stage_specs(stages, mesh, *, lead: int):
    """Per-leaf PartitionSpec for the stacked stage tree.

    ``lead`` leading dims carry the stage placement (1 for the plain
    [S, …] layout on ``pipe``; 2 for the interleaved [v, S, …] layout
    as P(None, pipe)). With an ``fsdp`` mesh axis, each big-enough
    leaf additionally shards its first evenly-dividing trailing dim —
    ZeRO-style: params and optimizer state REST sharded across the
    batch replicas, and the step all-gathers them transiently
    (``gather_stages``)."""
    fsdp = mesh.shape.get("fsdp", 1)
    lead_axes = ("pipe",) if lead == 1 else (None, "pipe")

    def spec_for(p):
        if fsdp <= 1 or p.size < FSDP_MIN_SIZE:
            return P(*lead_axes)
        spec = list(lead_axes) + [None] * (p.ndim - lead)
        for i in range(lead, p.ndim):
            if p.shape[i] % fsdp == 0:
                spec[i] = "fsdp"
                break
        return P(*spec)

    return jax.tree.map(spec_for, stages)


def stage_specs_megatron(stages, mesh, *, lead: int, tp_size: int):
    """``stage_specs`` plus Megatron TP dims over ``model``.

    With ``tp_size <= 1`` this IS ``stage_specs``. Otherwise the block
    kernels/biases follow parallel/tp.py's suffix rules shifted by the
    ``lead`` stacked dims — column kernels shard their output dim, row
    kernels their input dim, column biases their only dim — and
    ``fsdp``, when present, rides the kernels' *other* dim where it
    divides (the composition seq_param_specs builds). Leaves the rules
    don't name (LayerNorms) keep the base pipe/fsdp spec.
    """
    base = stage_specs(stages, mesh, lead=lead)
    if tp_size <= 1:
        return base

    from ddp_tpu.parallel.seq_fsdp import fsdp_size
    from ddp_tpu.parallel.tp import (
        _COLUMN_BIASES,
        _COLUMN_KERNELS,
        _ROW_KERNELS,
        _check_divides,
        _path_str,
    )

    n = fsdp_size(mesh)
    lead_axes = ("pipe",) if lead == 1 else (None, "pipe")

    def with_model(path, p, s):
        suffix = _path_str(path)
        shape = p.shape[lead:]  # per-stage (global, pre-TP) shape
        if suffix.endswith(_COLUMN_KERNELS):
            _check_divides(suffix, shape[1], tp_size)
            d0 = "fsdp" if n > 1 and shape[0] % n == 0 else None
            return P(*lead_axes, d0, "model")
        if suffix.endswith(_COLUMN_BIASES):
            _check_divides(suffix, shape[0], tp_size)
            return P(*lead_axes, "model")
        if suffix.endswith(_ROW_KERNELS):
            _check_divides(suffix, shape[0], tp_size)
            d1 = "fsdp" if n > 1 and shape[1] % n == 0 else None
            return P(*lead_axes, "model", d1)
        return s

    return jax.tree_util.tree_map_with_path(with_model, stages, base)


def gather_stages(sp, specs):
    """all_gather the fsdp-sharded stage leaves INSIDE the island.

    Under AD (the GPipe path) the transpose of this all_gather is a
    psum_scatter over ``fsdp`` — ZeRO's gradient reduce-scatter falls
    out of the schedule for free; the hand-scheduled paths apply the
    matching ``scatter_stage_grads`` explicitly."""

    def g(p, s):
        for i, ax in enumerate(s):
            if ax == "fsdp":
                return lax.all_gather(p, "fsdp", axis=i, tiled=True)
        return p

    return jax.tree.map(g, sp, specs)


def scatter_stage_grads(gs, specs):
    """Reduce stage grads over ``fsdp``: sum + re-shard for leaves
    that rest sharded (psum_scatter), plain psum for the rest —
    exactly the transpose of ``gather_stages`` plus the batch-axis
    reduction every grad needs (fsdp members see different data)."""

    def s(g, spec):
        for i, ax in enumerate(spec):
            if ax == "fsdp":
                return lax.psum_scatter(
                    g, "fsdp", scatter_dimension=i, tiled=True
                )
        return lax.psum(g, "fsdp")

    return jax.tree.map(s, gs, specs)

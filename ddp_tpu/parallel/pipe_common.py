"""Shared stage-parameter sharding for the pipeline model families.

Every pipelined model (ViT — models/pipeline_vit.py; causal LM —
models/pipeline_lm.py) stacks its uniform stage bodies on a leading
stage dim sharded over ``pipe`` and optionally ZeRO-shards each stage's
leaves over ``fsdp``. The spec computation and the gather/scatter pair
that moves params between their resting layout and the stage program
are family-independent and live here so a fix in one family cannot
miss the other.
"""

from __future__ import annotations

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

FSDP_MIN_SIZE = 2**12  # leaves smaller than this stay replicated


def pipe_batch_axes(mesh) -> tuple:
    """Axes the pipe family shards its batch over. ``expert`` is a
    batch axis exactly as in the flat EP family (runtime/mesh.py
    ``data_axes``): each expert-group member routes its own token
    shard and the all-to-all carries dispatched slots to the expert's
    owner (PP×EP, round 5). ``seq`` composes with pipe too (PP×SP,
    round 5) but shards TOKENS, not batch rows, so it is deliberately
    not a batch axis here — models/pipeline_lm.py puts it on the
    stream spec's trailing token dim and reduces param grads over it
    explicitly."""
    return tuple(
        a for a in ("data", "fsdp", "expert") if mesh.shape.get(a, 1) > 1
    )


def split_microbatch_stream(x, num_microbatches: int, num_stages: int):
    """[B, …] → the [M//S, S, mb, …] pipeline stream, STRIDED.

    Microbatch m takes rows ``m::M`` (the grad-accum idiom,
    models/lm.py): ``stream[r, s, i] = x[i·M + r·S + s]``. The loader
    delivers batches sharded on dim 0 over the batch axes, and the
    trainer's microbatch guard (``mb % data_shards == 0``) makes each
    member's contiguous row block whole i-groups — so the reshape
    below is sharding-LOCAL and the transpose a free tiling
    permutation; the shard_map boundary then only SLICES the
    replicated stream dim onto ``pipe``. A contiguous split would
    demand a dim0-batch → (None, pipe, batch) resharding that XLA's
    SPMD partitioner can only express by involuntary full
    rematerialization (the %reshape warning in MULTICHIP_r04; fixed
    round 5). One definition for both pipe families so the stream,
    label, and output orderings cannot drift."""
    import jax.numpy as jnp

    B, M, S = x.shape[0], num_microbatches, num_stages
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    if M % S:
        raise ValueError(
            f"{M} microbatches not divisible by {S} pipeline stages "
            "(the sharded stream rests microbatch m on device m mod S)"
        )
    x = x.reshape(B // M, M // S, S, *x.shape[1:])
    return jnp.transpose(x, (1, 2, 0) + tuple(range(3, x.ndim)))


def split_microbatch_labels(y, num_microbatches: int):
    """[B, …] → [M, mb, …] with the SAME strided row assignment as
    ``split_microbatch_stream``: ``labels[m, i] = y[i·M + m]``."""
    import jax.numpy as jnp

    B, M = y.shape[0], num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    return jnp.moveaxis(y.reshape(B // M, M, *y.shape[1:]), 1, 0)


def merge_microbatch_stream(out):
    """Invert ``split_microbatch_stream``: [R, S, mb, …] → [B, …]
    (out[r, s, i] is row i·M + r·S + s)."""
    import jax.numpy as jnp

    B = out.shape[0] * out.shape[1] * out.shape[2]
    return jnp.moveaxis(out, 2, 0).reshape(B, *out.shape[3:])


def stage_specs(stages, mesh, *, lead: int):
    """Per-leaf PartitionSpec for the stacked stage tree.

    ``lead`` leading dims carry the stage placement (1 for the plain
    [S, …] layout on ``pipe``; 2 for the interleaved [v, S, …] layout
    as P(None, pipe)). With an ``fsdp`` mesh axis, each big-enough
    leaf additionally shards its first evenly-dividing trailing dim —
    ZeRO-style: params and optimizer state REST sharded across the
    batch replicas, and the step all-gathers them transiently
    (``gather_stages``)."""
    fsdp = mesh.shape.get("fsdp", 1)
    lead_axes = ("pipe",) if lead == 1 else (None, "pipe")

    def spec_for(p):
        if fsdp <= 1 or p.size < FSDP_MIN_SIZE:
            return P(*lead_axes)
        spec = list(lead_axes) + [None] * (p.ndim - lead)
        for i in range(lead, p.ndim):
            if p.shape[i] % fsdp == 0:
                spec[i] = "fsdp"
                break
        return P(*spec)

    return jax.tree.map(spec_for, stages)


def stage_specs_megatron(
    stages, mesh, *, lead: int, tp_size: int, ep_size: int = 1
):
    """``stage_specs`` plus Megatron TP dims over ``model`` and MoE
    expert dims over ``expert``.

    With ``tp_size <= 1`` and ``ep_size <= 1`` this IS ``stage_specs``.
    With TP, the block kernels/biases follow parallel/tp.py's suffix
    rules shifted by the ``lead`` stacked dims — column kernels shard
    their output dim, row kernels their input dim, column biases their
    only dim — and ``fsdp``, when present, rides the kernels' *other*
    dim where it divides (the composition seq_param_specs builds).
    With EP (PP×EP, round 5), MoE expert weights take their leading
    per-stage dim (the expert index) on ``expert`` — the same rule as
    seq_param_specs' ``_EXPERT_LEAVES``, shifted by ``lead`` — with
    ``fsdp`` on the next dim where it divides; the router stays with
    the base rule (identical routing on every member). Leaves no rule
    names (LayerNorms) keep the base pipe/fsdp spec.
    """
    base = stage_specs(stages, mesh, lead=lead)
    if tp_size <= 1 and ep_size <= 1:
        return base

    from ddp_tpu.parallel.seq_fsdp import fsdp_size
    from ddp_tpu.parallel.tp import (
        _COLUMN_BIASES,
        _COLUMN_KERNELS,
        _EXPERT_LEAVES,
        _ROW_KERNELS,
        _check_divides,
        _path_str,
    )

    n = fsdp_size(mesh)
    lead_axes = ("pipe",) if lead == 1 else (None, "pipe")

    def with_model(path, p, s):
        suffix = _path_str(path)
        shape = p.shape[lead:]  # per-stage (global, pre-TP/EP) shape
        if ep_size > 1 and suffix.endswith(_EXPERT_LEAVES):
            _check_divides(suffix, shape[0], ep_size)
            # wi [E, d, mlp] / wo [E, mlp, d]: fsdp rides dim 1 where
            # it divides; biases [E, 1, f] shard the expert dim only.
            if (
                n > 1 and len(shape) > 1 and shape[1] > 1
                and shape[1] % n == 0
            ):
                return P(*lead_axes, "expert", "fsdp")
            return P(*lead_axes, "expert")
        if tp_size > 1:
            if suffix.endswith(_COLUMN_KERNELS):
                _check_divides(suffix, shape[1], tp_size)
                d0 = "fsdp" if n > 1 and shape[0] % n == 0 else None
                return P(*lead_axes, d0, "model")
            if suffix.endswith(_COLUMN_BIASES):
                _check_divides(suffix, shape[0], tp_size)
                return P(*lead_axes, "model")
            if suffix.endswith(_ROW_KERNELS):
                _check_divides(suffix, shape[0], tp_size)
                d1 = "fsdp" if n > 1 and shape[1] % n == 0 else None
                return P(*lead_axes, "model", d1)
        return s

    return jax.tree_util.tree_map_with_path(with_model, stages, base)


def gather_stages(sp, specs):
    """all_gather the fsdp-sharded stage leaves INSIDE the island.

    Under AD (the GPipe path) the transpose of this all_gather is a
    psum_scatter over ``fsdp`` — ZeRO's gradient reduce-scatter falls
    out of the schedule for free; the hand-scheduled paths apply the
    matching ``scatter_stage_grads`` explicitly."""

    def g(p, s):
        for i, ax in enumerate(s):
            if ax == "fsdp":
                return lax.all_gather(p, "fsdp", axis=i, tiled=True)
        return p

    return jax.tree.map(g, sp, specs)


def scatter_stage_grads(gs, specs):
    """Reduce stage grads over ``fsdp``: sum + re-shard for leaves
    that rest sharded (psum_scatter), plain psum for the rest —
    exactly the transpose of ``gather_stages`` plus the batch-axis
    reduction every grad needs (fsdp members see different data)."""

    def s(g, spec):
        for i, ax in enumerate(spec):
            if ax == "fsdp":
                return lax.psum_scatter(
                    g, "fsdp", scatter_dimension=i, tiled=True
                )
        return lax.psum(g, "fsdp")

    return jax.tree.map(s, gs, specs)

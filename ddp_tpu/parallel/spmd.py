"""GSPMD train step: tensor/FSDP/data parallelism from sharding rules.

The reference's only strategy is DDP (SURVEY.md §2c); its stack has no
tensor or parameter sharding at all. This module is the framework's
scale-out past pure data parallelism, built the TPU-native way: instead
of rewriting layers Megatron-style, we *annotate* — pick a mesh, give
every parameter a ``PartitionSpec``, pin activations with
``with_sharding_constraint``, and let XLA's SPMD partitioner insert the
collectives (all-gather for row-parallel inputs, reduce-scatter/psum
for column-parallel outputs, gradient all-reduce over the replicated
``data`` axis). The scaling-book recipe, literally.

Axis semantics (runtime/mesh.py vocabulary):

- ``data``  — batch sharded; params NOT named in specs ⇒ replicated ⇒
              XLA emits the gradient all-reduce (DDP for free).
- ``model`` — tensor parallelism: attention/MLP "column" kernels shard
              their output features, "row" kernels their input features
              (Megatron pairing ⇒ one collective per block, not per
              layer — XLA finds this from the specs alone).
- ``fsdp``  — remaining large params shard their biggest dimension;
              XLA all-gathers them just-in-time per layer and keeps
              optimizer state sharded (ZeRO-3 behavior, zero code).

Optimizer-state sharding falls out of propagation: the jitted init
constrains params only, and GSPMD lays momentum/Adam moments out like
their params — no spec bookkeeping for optax internals.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddp_tpu.obs.health import health_stats, inject_nan
from ddp_tpu.parallel.common import (
    _preprocess,
    _train_kwarg,
    check_accum_divisible,
    grad_accum_scan,
    make_loss_fn,
)
from ddp_tpu.parallel.ddp import StepMetrics, TrainState


MIN_SHARD_SIZE = 2**12  # tensors smaller than this stay replicated


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """Name-pattern → parallel style, matched against the param path.

    ``column`` kernels shard the output (last) dim on ``model``;
    ``row`` kernels shard the input (first) dim. Defaults cover the
    framework's transformer family (models/vit.py): qkv+mlp1 column,
    proj+mlp2 row — the Megatron pairing; the MoE expert FFN
    (models/moe.py wi/wo) gets the same pairing on its trailing dims
    while its leading expert dim shards on ``expert``. Anything else
    big enough is fsdp-sharded on its largest dimension.
    """

    column: tuple[str, ...] = ("qkv", "mlp1", "moe/wi")
    row: tuple[str, ...] = ("proj", "mlp2", "moe/wo")
    expert: tuple[str, ...] = (r"moe/(wi|wo|bi|bo)",)
    # shared "stay replicated below this" threshold (fsdp AND zero1)
    fsdp_min_size: int = MIN_SHARD_SIZE

    def spec_for(self, path: tuple[str, ...], shape: tuple[int, ...], mesh: Mesh) -> P:
        tp = mesh.shape.get("model", 1)
        fsdp = mesh.shape.get("fsdp", 1)
        ep = mesh.shape.get("expert", 1)
        spec: list[Any] = [None] * len(shape)
        name = "/".join(path)
        is_kernel = len(shape) >= 2
        if (
            ep > 1
            and len(shape) >= 1
            and any(re.search(p, name) for p in self.expert)
            and shape[0] % ep == 0
        ):
            spec[0] = "expert"
        if tp > 1 and is_kernel:
            if any(re.search(p, name) for p in self.column) and shape[-1] % tp == 0:
                spec[-1] = "model"
            elif any(re.search(p, name) for p in self.row) and shape[-2] % tp == 0:
                spec[-2] = "model"
        if _size(shape) >= self.fsdp_min_size:
            _shard_largest_dim(spec, shape, "fsdp", fsdp)
        return P(*spec)


def _size(shape) -> int:
    n = 1
    for s in shape:
        n *= s
    return n


def _shard_largest_dim(spec: list, shape, axis_name: str, axis_size: int) -> list:
    """Put ``axis_name`` on the largest still-unsharded dim that divides
    evenly (shared by the fsdp rule and the ZeRO-1 layout)."""
    if axis_size > 1:
        for i in sorted(range(len(shape)), key=lambda j: -shape[j]):
            if spec[i] is None and shape[i] % axis_size == 0:
                spec[i] = axis_name
                break
    return spec


def param_specs(params, mesh: Mesh, rules: ShardingRules | None = None):
    """PartitionSpec pytree for ``params`` (shapes or arrays).

    Works on any tree whose leaf *paths* end in param names — including
    optax state (``trace/…/qkv/kernel``), because the rules match name
    patterns anywhere in the joined path. Scalars get ``P()``.
    """
    rules = rules or ShardingRules()
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: rules.spec_for(
            tuple(getattr(k, "key", str(k)) for k in path), leaf.shape, mesh
        ),
        params,
    )


def constrain_tree(tree, mesh: Mesh, rules: ShardingRules | None = None):
    """with_sharding_constraint every leaf per the name-based rules."""
    specs = param_specs(tree, mesh, rules)
    return jax.tree.map(
        lambda x, s: lax.with_sharding_constraint(x, NamedSharding(mesh, s)),
        tree,
        specs,
    )


def zero1_spec_for(shape, mesh: Mesh, *, min_size: int = MIN_SHARD_SIZE) -> P:
    """ZeRO-1 layout: shard a state tensor's largest fitting dim on the
    DATA axis. Params stay replicated (unlike fsdp); only the optimizer
    math and its memory are partitioned."""
    spec: list = [None] * len(shape)
    if _size(shape) >= min_size:
        _shard_largest_dim(spec, shape, "data", mesh.shape.get("data", 1))
    return P(*spec)


def check_zero1_mesh(mesh: Mesh) -> None:
    """ZeRO-1 swaps out the rule-based opt-state layout entirely, so it
    must not silently undo fsdp/tp/expert sharding of the moments —
    reject the combination (those meshes already shard optimizer state
    their own way; stage 1 is for pure data-parallel meshes)."""
    bad = {
        a: mesh.shape[a]
        for a in ("model", "fsdp", "expert")
        if mesh.shape.get(a, 1) > 1
    }
    if bad:
        raise ValueError(
            f"zero1 only composes with pure data-parallel meshes; "
            f"{bad} already shard optimizer state via the rule layout "
            f"(fsdp IS ZeRO-3) — drop --zero1 or the mesh axes"
        )


def constrain_zero1(opt_state, mesh: Mesh):
    """with_sharding_constraint optimizer-state leaves to ZeRO-1 specs.

    The reference replicates optimizer state on every rank (plain SGD,
    SURVEY.md §2c "ZeRO: No"); with Adam-family optimizers the moments
    are 2× the params — sharding them over data cuts that memory by the
    data-parallel degree while XLA keeps the update math local to each
    shard and all-gathers only the applied updates.
    """
    return jax.tree.map(
        lambda x: lax.with_sharding_constraint(
            x, NamedSharding(mesh, zero1_spec_for(x.shape, mesh))
        ),
        opt_state,
    )


def batch_spec(mesh: Mesh) -> P:
    """Batch dim sharded over every data-parallel axis present.

    ``expert`` is a data axis for everything except the expert weights
    themselves (GShard layout): tokens shard over it, and XLA turns the
    dispatch/combine einsums in models/moe.py into token all-to-alls.
    """
    from ddp_tpu.runtime.mesh import data_axes

    axes = tuple(a for a in data_axes(mesh) if mesh.shape.get(a, 1) > 1)
    return P(axes if axes else None)


def create_spmd_state(
    model,
    optimizer: optax.GradientTransformation,
    sample_input,
    mesh: Mesh,
    *,
    rules: ShardingRules | None = None,
    seed: int = 0,
    zero1: bool = False,
) -> TrainState:
    """Initialize directly into the sharded layout.

    Params get their rule specs; GSPMD propagates those through
    ``optimizer.init`` so optimizer state comes out sharded the same
    way (ZeRO without writing ZeRO). ``zero1=True`` instead shards the
    optimizer state over the DATA axis while params stay replicated
    (ZeRO stage 1). Nothing materializes replicated first — safe for
    models larger than one chip's HBM.
    """
    rules = rules or ShardingRules()
    if zero1:
        check_zero1_mesh(mesh)

    def init_fn():
        variables = model.init(
            jax.random.key(seed), sample_input, **_train_kwarg(model, False)
        )
        params = constrain_tree(variables["params"], mesh, rules)
        model_state = {k: v for k, v in variables.items() if k != "params"}
        opt_state = optimizer.init(params)
        opt_state = (
            constrain_zero1(opt_state, mesh)
            if zero1
            else constrain_tree(opt_state, mesh, rules)
        )
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt_state,
            model_state=model_state,
        )

    return jax.jit(init_fn)()


def make_spmd_train_step(
    model,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    rules: ShardingRules | None = None,
    compute_dtype=jnp.float32,
    donate: bool = True,
    seed: int = 0,
    aux_loss_weight: float = 0.01,
    grad_accum_steps: int = 1,
    augment_fn=None,
    label_smoothing: float = 0.0,
    zero1: bool = False,
    health: bool = False,
    health_inject: tuple[str, int] | None = None,
) -> Callable[[TrainState, jax.Array, jax.Array], tuple[TrainState, StepMetrics]]:
    """``step(state, images, labels) -> (state, metrics)`` under GSPMD.

    Same contract as ``parallel.ddp.make_train_step`` (loss is the
    global-batch mean; metrics replicated), but the state may be
    tensor-/fsdp-sharded per ``rules``. The gradient all-reduce over
    ``data`` is *implied* — params have no ``data`` axis in their
    specs, so XLA partial-sums their grads across it, exactly the DDP
    reducer's contract (SURVEY.md §2b N4) derived rather than written.
    ``grad_accum_steps=k`` accumulates k microbatch gradients
    (``lax.scan``) into one update, like the DDP path.
    """
    rules = rules or ShardingRules()
    if zero1:
        check_zero1_mesh(mesh)
    bspec = batch_spec(mesh)
    loss_fn = make_loss_fn(
        model, compute_dtype, aux_loss_weight, augment_fn=augment_fn,
        label_smoothing=label_smoothing,
    )

    def step(state: TrainState, images, labels):
        images = lax.with_sharding_constraint(images, NamedSharding(mesh, bspec))
        labels = lax.with_sharding_constraint(labels, NamedSharding(mesh, bspec))
        mutable = list(state.model_state.keys())
        rng = jax.random.fold_in(jax.random.key(seed), state.step)

        if grad_accum_steps == 1:
            (loss, (logits, new_ms)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params, state.model_state, images, labels, rng, mutable)
            correct = (
                jnp.argmax(logits.astype(jnp.float32), -1) == labels
            ).mean()
        else:
            mb = check_accum_divisible(images.shape[0], grad_accum_steps)
            # STRIDED microbatches (micro i = rows i::k): with the batch
            # contiguously sharded over the data axes, every device
            # keeps its own rows in every microbatch — a contiguous
            # [k, mb] split would instead reshard the whole batch
            # across devices each step. Semantics are identical (the
            # gradient is the mean over the full batch either way).
            mspec = P(None, *bspec)  # microbatch dim leading
            imgs = lax.with_sharding_constraint(
                images.reshape(mb, grad_accum_steps, *images.shape[1:])
                .swapaxes(0, 1),
                NamedSharding(mesh, mspec),
            )
            lbls = lax.with_sharding_constraint(
                labels.reshape(mb, grad_accum_steps).swapaxes(0, 1),
                NamedSharding(mesh, mspec),
            )
            grads, new_ms, loss, count = grad_accum_scan(
                loss_fn, state.params, state.model_state, imgs, lbls, rng, mutable
            )
            correct = count / images.shape[0]

        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        grads = constrain_tree(grads, mesh, rules)
        if health_inject is not None:
            grads = inject_nan(grads, state.step, health_inject)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        # ZeRO-1: the moment/trace math runs on data-sharded slices;
        # applying the (replicated-constrained) updates below is the
        # implied all-gather.
        opt_state = (
            constrain_zero1(opt_state, mesh)
            if zero1
            else constrain_tree(opt_state, mesh, rules)
        )
        params = constrain_tree(
            optax.apply_updates(state.params, updates), mesh, rules
        )
        metrics = StepMetrics(
            loss=loss,
            accuracy=correct,
            grad_norm=optax.global_norm(grads),
            # GSPMD reduces the per-group sums across whatever sharding
            # the leaves rest in — the [G] outputs are tiny replicated
            # vectors either way.
            health=health_stats(grads, state.params, updates)
            if health
            else None,
        )
        return TrainState(state.step + 1, params, opt_state, new_ms), metrics

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_spmd_eval_step(model, mesh: Mesh, *, compute_dtype=jnp.float32):
    """(params, model_state, images, labels, weights) → (correct, loss_sum)."""
    bspec = batch_spec(mesh)
    train_kw = _train_kwarg(model, False)

    def step(params, model_state, images, labels, weights):
        images = lax.with_sharding_constraint(images, NamedSharding(mesh, bspec))
        x = _preprocess(images, compute_dtype)
        if compute_dtype != jnp.float32:
            params = jax.tree.map(lambda p: p.astype(compute_dtype), params)
        logits = model.apply(
            {"params": params, **model_state}, x, **train_kw
        ).astype(jnp.float32)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        correct = ((jnp.argmax(logits, -1) == labels) * weights).sum()
        return correct, (loss * weights).sum()

    return jax.jit(step)

"""Pipeline parallelism over the ``pipe`` mesh axis.

Absent from the reference (single-stage model, SURVEY.md §2c "Pipeline
parallelism: No"); built here the TPU-native way and, since round 2,
built to SCALE (VERDICT.md round-1 weak #3 flagged the first version's
replicated microbatch buffers and same-shaped-stages-only contract):

- **Sharded streaming buffers.** The microbatch input/output arrays
  are sharded over ``pipe`` on the microbatch dim (microbatch m lives
  on device m mod S). Each device carries one rotating in-flight slot
  for inputs and one for outputs; ``lax.ppermute`` walks a microbatch
  to stage 0 as its turn arrives and walks finished outputs back to
  their home shard. Per-device buffer memory is O(M/S), not O(M) —
  exactly what pipeline parallelism exists to buy.
- **Non-uniform stages.** ``first_fn`` runs INSIDE stage 0 before its
  body blocks and ``last_fn`` inside stage S-1 after its own — so the
  embed front (raw pixels → tokens) and the norm+head back (tokens →
  logits) live in the pipeline, and the raw-input, activation, and
  output shapes may all differ. The uniform body remains a stacked
  parameter tree sharded over ``pipe``; first/last params replicate
  (they are the small ends of the model).
- **Differentiable schedule** — the backward is the reverse schedule
  with the ring running the other way, derived by AD (scan + ppermute
  + cond all have exact transposes). The hand-scheduled 1F1B variant
  with O(S) activation stash lives in ``one_f1b.py``.

Bubble accounting: the GPipe fill+drain idles S-1 of M+S-1 ticks per
device — ``bubble_fraction(S, M)`` reports it, and callers surface it
so the M-vs-bubble tradeoff is visible rather than folklore.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def bubble_fraction(num_stages: int, num_microbatches: int) -> float:
    """Idle fraction of the GPipe schedule: (S-1)/(M+S-1)."""
    S, M = num_stages, num_microbatches
    return (S - 1) / (M + S - 1)


def spmd_pipeline(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,
    *,
    axis_name: str = "pipe",
    first_fn: Callable[[Any, jax.Array], jax.Array] | None = None,
    first_params: Any = None,
    last_fn: Callable[[Any, jax.Array], jax.Array] | None = None,
    last_params: Any = None,
):
    """Run the GPipe schedule. Call INSIDE shard_map over ``axis_name``.

    Args:
      stage_fn: ``(params_for_one_stage, x) -> y`` with ``y.shape ==
        x.shape`` — the uniform body every stage runs.
      stage_params: this device's slice of the stacked body tree —
        leading dim 1 (from ``in_specs=P('pipe', ...)``); squeezed here.
      microbatches: [R, 1, mb, ...] — this device's microbatch shard
        (from ``in_specs=P(None, 'pipe', ...)`` on a [R, S, mb, ...]
        global array; microbatch m = r·S + d rests on device d).
      first_fn/first_params: optional stage-0 front (e.g. patch embed),
        ``(params, raw_mb) -> activation``; params replicated.
      last_fn/last_params: optional stage-(S-1) back (e.g. norm+head),
        ``(params, activation) -> out``; params replicated.

    Returns [R, 1, mb, ...out] — outputs for this device's microbatch
    shard (m = r·S + d at local round r), for ``out_specs=P(None,
    axis_name, ...)``.

    Mechanics: inputs reload into a rotating slot once per S-tick round
    and walk BACKWARD one hop per tick, so device 0 always holds
    microbatch t at tick t; outputs written by the last stage walk
    FORWARD and each device snapshots the slot exactly when its own
    microbatch passes by. All carries are one microbatch wide — the
    O(M) replicated buffers of the round-1 schedule are gone.
    """
    params = jax.tree.map(lambda p: p[0], stage_params)
    stage = lax.axis_index(axis_name)
    S = lax.psum(1, axis_name)  # static under shard_map
    local_in = microbatches[:, 0]  # [R, mb, ...]
    R = local_in.shape[0]
    M = R * S

    if first_fn is None:
        first_fn = lambda p, x: x
    if last_fn is None:
        last_fn = lambda p, x: x
    # Static activation/output shapes via abstract eval (no FLOPs).
    act_shape = jax.eval_shape(first_fn, first_params, local_in[0])
    out_shape = jax.eval_shape(
        last_fn, last_params, jax.eval_shape(stage_fn, params, act_shape)
    )

    fwd = [(i, (i + 1) % S) for i in range(S)]  # with wraparound
    bwd = [((i + 1) % S, i) for i in range(S)]
    shift = [(i, i + 1) for i in range(S - 1)]  # activations: drain off

    def store_round(t, outbuf, local_out):
        """Snapshot the rotating output slot on its home device.

        The output of microbatch m is written at tick w = m + S - 1
        and lands on device 0 by that tick's rotation; it then visits
        device d at tick w + d, living S ticks before the slot cycles
        back to the writer. So at (post-rotation) tick t, device d
        holds the output of m = t - d - (S - 1); it stores it iff
        m ≡ d (mod S) — each m hits its home exactly once.
        """
        m_held = t - (S - 1) - stage
        is_home = (m_held >= 0) & (m_held % S == stage) & (m_held < M)
        r_idx = jnp.clip(m_held // S, 0, R - 1)
        current = lax.dynamic_index_in_dim(local_out, r_idx, 0, keepdims=False)
        return lax.dynamic_update_index_in_dim(
            local_out,
            jnp.where(is_home, outbuf, current),
            r_idx,
            0,
        )

    def tick(carry, t):
        x, inbuf, outbuf, local_out = carry
        # Round reload: at t ≡ 0 (mod S) every device loads its own
        # microbatch of round t//S into the rotating input slot.
        r = jnp.clip(t // S, 0, R - 1)
        fresh = lax.dynamic_index_in_dim(local_in, r, 0, keepdims=False)
        inbuf = jnp.where(t % S == 0, fresh, inbuf)
        # Stage 0 consumes the slot (it holds microbatch t by now) and
        # runs the non-uniform front; other stages use the ring input.
        x_in = lax.cond(
            stage == 0,
            lambda: first_fn(first_params, inbuf).astype(x.dtype),
            lambda: x,
        )
        y = stage_fn(params, x_in)
        # Last stage runs the non-uniform back on its fresh result.
        out_new = lax.cond(
            stage == S - 1,
            lambda: last_fn(last_params, y).astype(outbuf.dtype),
            lambda: outbuf,
        )
        # Rotate: outputs forward (toward their home shard), inputs
        # backward (toward stage 0); activations one hop down, no wrap.
        outbuf = lax.ppermute(out_new, axis_name, fwd)
        local_out = store_round(t, outbuf, local_out)
        inbuf = lax.ppermute(inbuf, axis_name, bwd)
        x_next = lax.ppermute(y, axis_name, shift)
        return (x_next, inbuf, outbuf, local_out), None

    x0 = jnp.zeros(act_shape.shape, act_shape.dtype)
    in0 = jnp.zeros_like(local_in[0])
    out0 = jnp.zeros(out_shape.shape, out_shape.dtype)
    lout0 = jnp.zeros((R, *out_shape.shape), out_shape.dtype)
    carry = (x0, in0, out0, lout0)
    # Main schedule: M + S - 1 ticks (fill + stream + drain of compute).
    carry, _ = lax.scan(tick, carry, jnp.arange(M + S - 1))

    def drain(carry, t):
        outbuf, local_out = carry
        outbuf = lax.ppermute(outbuf, axis_name, fwd)
        local_out = store_round(t, outbuf, local_out)
        return (outbuf, local_out), None

    # Output walk-home drain: pure rotation, no stage compute.
    (_, local_out), _ = lax.scan(
        drain, (carry[2], carry[3]), jnp.arange(M + S - 1, M + 2 * S - 1)
    )
    return local_out[:, None]  # [R, 1, mb, ...]


def make_pipelined_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    mesh: Mesh,
    *,
    num_microbatches: int,
    axis_name: str = "pipe",
    first_fn: Callable[[Any, jax.Array], jax.Array] | None = None,
    last_fn: Callable[[Any, jax.Array], jax.Array] | None = None,
):
    """Jitted ``apply(stacked_params, x[, first_params, last_params])``.

    ``stacked_params``: pytree with leading stage dim S on every leaf.
    ``x``: [B, ...] global batch; split into ``num_microbatches`` along
    dim 0 (padded up to a multiple of S with discarded dummies when
    needed), streamed through, re-assembled. Differentiable.
    """
    S = mesh.shape[axis_name]

    def run(stacked_params, x, first_params=None, last_params=None):
        B = x.shape[0]
        M = num_microbatches
        if B % M:
            raise ValueError(f"batch {B} not divisible by {M} microbatches")
        mb = x.reshape(M, B // M, *x.shape[1:])
        # The sharded streaming layout needs M ≡ 0 (mod S): pad with
        # dummy microbatches whose outputs are sliced away.
        M_pad = -(-M // S) * S
        if M_pad != M:
            pad = jnp.zeros((M_pad - M, *mb.shape[1:]), mb.dtype)
            mb = jnp.concatenate([mb, pad], axis=0)
        mbs = mb.reshape(M_pad // S, S, *mb.shape[1:])

        sharded = jax.shard_map(
            lambda p, m, fp, lp: spmd_pipeline(
                stage_fn, p, m, axis_name=axis_name,
                first_fn=first_fn, first_params=fp,
                last_fn=last_fn, last_params=lp,
            ),
            mesh=mesh,
            in_specs=(P(axis_name), P(None, axis_name), P(), P()),
            out_specs=P(None, axis_name),
            check_vma=False,
        )
        out = sharded(stacked_params, mbs, first_params, last_params)
        out = out.reshape(M_pad, B // M, *out.shape[3:])[:M]
        return out.reshape(B, *out.shape[2:])

    return jax.jit(run)


def stack_stage_params(param_list) -> Any:
    """[tree, tree, …] (one per stage, same shapes) → stacked tree."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_list)

"""Pipeline parallelism: GPipe microbatch schedule over the ``pipe`` axis.

Absent from the reference (single-stage model, SURVEY.md §2c "Pipeline
parallelism: No"); built here the TPU-native way. Stages are
*same-shaped* programs (the transformer-block case): stage s holds its
slice of a parameter tree stacked on a leading stage dimension, sharded
over ``pipe``. The schedule is a ``lax.scan`` over M + S - 1 ticks —
each tick every device runs its stage on the activation it holds, then
``lax.ppermute`` shifts activations one hop down the ring (stage s →
s+1, the classic bubble-fill/drain pattern). XLA overlaps the
neighbor-hop transfer with the next tick's compute on ICI.

The whole schedule is differentiable (scan + ppermute have exact
transposes: the backward pass is the reverse schedule with ppermute
running the ring the other way), so ``jax.grad`` through
``spmd_pipeline`` *is* the 1F1B-equivalent backward — no hand-written
backward schedule.

Composes with the other axes: batch on ``data``, microbatch tokens on
``seq``, stage weights on ``model`` — the stage_fn only ever sees its
local shard.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def spmd_pipeline(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,
    *,
    axis_name: str = "pipe",
):
    """Run the GPipe schedule. Call INSIDE shard_map over ``axis_name``.

    Args:
      stage_fn: ``(params_for_one_stage, x) -> y`` with ``y.shape ==
        x.shape`` (same-shaped stages).
      stage_params: this device's slice of the stacked param tree —
        leading dim 1 (from ``in_specs=P('pipe', ...)``); squeezed here.
      microbatches: [M, mb, ...] — the full microbatched input,
        replicated; only stage 0 reads it.

    Returns [M, mb, ...] outputs, identical on every device.
    """
    params = jax.tree.map(lambda p: p[0], stage_params)
    stage = lax.axis_index(axis_name)
    S = lax.psum(1, axis_name)  # static under shard_map
    M = microbatches.shape[0]
    shift = [(i, i + 1) for i in range(S - 1)]  # no wraparound: drain off the end

    def tick(carry, t):
        x, outputs = carry
        # Fill: stage 0 injects microbatch t (clamped index is harmless
        # past the end — those ticks' stage-0 outputs are never collected).
        inject = microbatches[jnp.minimum(t, M - 1)]
        x = jnp.where(stage == 0, inject, x)
        y = stage_fn(params, x)
        # Drain: the last stage has finished microbatch t-(S-1) at tick t.
        out_idx = jnp.clip(t - (S - 1), 0, M - 1)
        take = (stage == S - 1) & (t >= S - 1)
        current = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(take, y, current), out_idx, 0
        )
        x_next = lax.ppermute(y, axis_name, shift)
        return (x_next, outputs), None

    x0 = jnp.zeros_like(microbatches[0])
    out0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = lax.scan(tick, (x0, out0), jnp.arange(M + S - 1))
    # Outputs live on the last stage only; replicate them so callers
    # (loss on every device, or out_specs P()) see the same values.
    return lax.psum(outputs * (stage == S - 1), axis_name)


def make_pipelined_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    mesh: Mesh,
    *,
    num_microbatches: int,
    axis_name: str = "pipe",
):
    """Jitted ``apply(stacked_params, x) -> y`` over the pipeline mesh.

    ``stacked_params``: pytree with leading stage dim S on every leaf.
    ``x``: [B, ...] global batch; split into ``num_microbatches`` along
    dim 0, streamed through, re-assembled. Differentiable.
    """

    def run(stacked_params, x):
        B = x.shape[0]
        M = num_microbatches
        if B % M:
            raise ValueError(f"batch {B} not divisible by {M} microbatches")
        mb = x.reshape(M, B // M, *x.shape[1:])

        sharded = jax.shard_map(
            lambda p, m: spmd_pipeline(stage_fn, p, m, axis_name=axis_name),
            mesh=mesh,
            in_specs=(P(axis_name), P()),
            out_specs=P(),
            check_vma=False,
        )
        out = sharded(stacked_params, mb)
        return out.reshape(B, *out.shape[2:])

    return jax.jit(run)


def stack_stage_params(param_list) -> Any:
    """[tree, tree, …] (one per stage, same shapes) → stacked tree."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_list)

"""Interleaved 1F1B: virtual pipeline stages cutting the bubble.

Plain 1F1B (parallel/one_f1b.py) keeps the activation stash O(S) but
inherits GPipe's bubble (S−1)/(M+S−1): each device holds ONE
contiguous model slice, so the pipe fills once per step. Interleaving
(the Megatron-LM virtual-stage schedule) cuts the model into
C = S·v chunks placed ROUND-ROBIN — chunk c lives on device c mod S,
so device d holds chunks {d, d+S, …, d+(v−1)S} — and a microbatch
visits every device v times. The pipe now fills with v·M
microbatch-chunks instead of M, shrinking the bubble toward
(S−1)/(v·M+S−1) at the cost of v× the activation-transport volume
(every chunk boundary is a ring hop, including the S−1 → 0 wrap).

The reference stack has no pipeline schedule at all (SURVEY.md §2c —
its parallelism is DDP, train_ddp.py:199); this module is the
framework's own depth, designed for the TPU execution model the same
way one_f1b.py is:

- The timetable is computed ONCE on the host (``schedule_interleaved``)
  by simulating the canonical Megatron per-device op order (warmup
  forwards, 1B1F steady state, cooldown) slot-synchronously with
  explicit transport waits. Unlike the plain-1F1B simulator, which
  asserts its transport invariants after the fact, this one makes
  them unviolable by construction: an op only lands in the table when
  its input message has arrived, its stash slot is free, and the
  downstream pending ring can absorb its output. Buffer depths too
  shallow to keep the order moving retry deeper rather than
  deadlocking; the measured bubble equals the schedule's ideal
  (S−1)/(v·M+S−1) at every tested (S, M, v).
- The device program is one ``lax.scan`` over the slot tables
  (op/microbatch/chunk per device per slot), each slot one
  ``lax.switch`` — forward (stash the chunk input, run the chunk) or
  backward (recompute from the stash, VJP) — exactly as in
  one_f1b.py, plus a chunk index selecting this device's parameter
  slice. All transport is two cyclic ``ppermute`` rings (activations
  +1, cotangents −1); receivers latch from the schedule table, so the
  collectives stay uniform and XLA-friendly.
- Pending buffers are small rings per chunk (slot m mod ring_depth,
  depth 2 in practice): a sender may run a slot ahead of its
  receiver's consumption, which is what keeps the steady-state 1F1B
  ping-pong dense.

Memory per device: stash O(v·Z) chunk inputs (Z ≈ S — reported by the
schedule), pending 4·v activations — independent of M, preserving
1F1B's point while the bubble shrinks with v.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

IDLE, FWD, BWD = 0, 1, 2


class InterleavedSchedule(NamedTuple):
    """Host-computed interleaved-1F1B timetable.

    ``op``/``mb``/``ck``: [n_slots, S] int32 — per device per slot:
    what to run (0 idle, 1 forward, 2 backward), on which microbatch,
    and on which LOCAL chunk k (global chunk c = k·S + d).
    ``stash_depth``: per-chunk stash slots the kernel must allocate
    (slot index = microbatch mod stash_depth; collision-free by
    construction).
    """

    op: np.ndarray
    mb: np.ndarray
    ck: np.ndarray
    stash_depth: int
    ring_depth: int
    virtual_stages: int

    @property
    def n_slots(self) -> int:
        return self.op.shape[0]

    def bubble_fraction(self) -> float:
        """Measured idle fraction of the timetable."""
        return float((self.op == IDLE).sum()) / self.op.size


class _Deadlock(Exception):
    pass


def _device_op_sequence(S: int, M: int, V: int) -> list[list[tuple]]:
    """The canonical Megatron interleaved op order per device.

    Forward order: groups of S microbatches, chunks ascending within a
    group (device d runs S microbatches through its chunk k, then the
    same S through chunk k+1, …). Backward order mirrors it with
    chunks descending. Each device's timetable is W warmup forwards —
    W = 2(S−d−1) + (v−1)S + 1, the depth at which its first backward
    becomes reachable — then strict 1B1F alternation, then cooldown
    backwards. Simulating THIS order with explicit transport waits
    (rather than re-deriving a schedule greedily) is what reproduces
    the schedule's ideal bubble (S−1)/(v·M+S−1); a free-form greedy
    loses density in the steady state (measured: 0.21 vs the ideal
    0.086 at S=4, M=16, v=2).
    """
    fo, bo = [], []
    for g in range(M // S):
        for k in range(V):
            for i in range(S):
                fo.append((g * S + i, k))
        for k in reversed(range(V)):
            for i in range(S):
                bo.append((g * S + i, k))
    seqs = []
    total = M * V
    for d in range(S):
        W = min((S - d - 1) * 2 + (V - 1) * S + 1, total)
        s = [(FWD, *fo[i]) for i in range(W)]
        fi, bi = W, 0
        while fi < total or bi < total:
            if bi < total:
                s.append((BWD, *bo[bi]))
                bi += 1
            if fi < total:
                s.append((FWD, *fo[fi]))
                fi += 1
        seqs.append(s)
    return seqs


def _simulate(S: int, M: int, V: int, Z: int, ring: int) -> InterleavedSchedule:
    """Slot-synchronous simulation of the canonical order with
    transport waits; raises _Deadlock if the buffer depths cannot
    keep the order moving."""
    C = S * V
    seq = _device_op_sequence(S, M, V)
    ptr = [0] * S
    F_done = [[None] * C for _ in range(M)]
    # pend_*[d][k][r]: microbatch occupying ring slot r, or None.
    # Occupied from arrival until the consuming op's slot.
    pend_act = [[[None] * ring for _ in range(V)] for _ in range(S)]
    pend_cot = [[[None] * ring for _ in range(V)] for _ in range(S)]
    # stash[d][k]: occupied slots (m mod Z), chunks c>0 only (chunk
    # 0's backward re-fetches the raw microbatch instead).
    stash = [[set() for _ in range(V)] for _ in range(S)]

    ops, mbs, cks = [], [], []
    t = 0
    max_slots = 8 * (V * M + S) + 64
    while any(ptr[d] < len(seq[d]) for d in range(S)):
        if t > max_slots:
            raise _Deadlock(f"S={S} M={M} V={V} Z={Z} ring={ring}")
        row_op, row_mb, row_ck = [IDLE] * S, [0] * S, [0] * S
        effects = []  # arrivals land after every device chose
        for d in range(S):
            if ptr[d] >= len(seq[d]):
                continue
            opc, m, k = seq[d][ptr[d]]
            c = k * S + d
            if opc == FWD:
                if c > 0 and pend_act[d][k][m % ring] != m:
                    continue  # input not arrived yet
                if c > 0 and m % Z in stash[d][k]:
                    continue  # stash slot not yet freed
                if c < C - 1:
                    rd = (d + 1) % S
                    rk = k if d < S - 1 else k + 1
                    if pend_act[rd][rk][m % ring] is not None:
                        continue  # downstream ring slot still full
            else:
                if c == C - 1:
                    if not (F_done[m][c] is not None and F_done[m][c] < t):
                        continue
                elif pend_cot[d][k][m % ring] != m:
                    continue
                if c > 0:
                    rd = (d - 1) % S
                    rk = k if d > 0 else k - 1
                    if pend_cot[rd][rk][m % ring] is not None:
                        continue
            ptr[d] += 1
            row_op[d], row_mb[d], row_ck[d] = opc, m, k
            if opc == FWD:
                F_done[m][c] = t
                if c > 0:
                    pend_act[d][k][m % ring] = None  # consumed
                    stash[d][k].add(m % Z)
                if c < C - 1:
                    rd = (d + 1) % S
                    rk = k if d < S - 1 else k + 1
                    effects.append((pend_act, rd, rk, m))
            else:
                if c > 0:
                    stash[d][k].discard(m % Z)
                if c < C - 1:
                    pend_cot[d][k][m % ring] = None  # consumed
                if c > 0:
                    rd = (d - 1) % S
                    rk = k if d > 0 else k - 1
                    effects.append((pend_cot, rd, rk, m))
        for buf, rd, rk, m in effects:
            assert buf[rd][rk][m % ring] is None
            buf[rd][rk][m % ring] = m  # arrives end of slot t
        ops.append(row_op)
        mbs.append(row_mb)
        cks.append(row_ck)
        t += 1
    op_a = np.asarray(ops, np.int32)
    mb_a = np.asarray(mbs, np.int32)
    ck_a = np.asarray(cks, np.int32)
    # The lru_cached schedule is shared across callers (startup log,
    # step factory, tests): freeze the arrays so a stray in-place edit
    # raises instead of corrupting every later same-key schedule.
    for a in (op_a, mb_a, ck_a):
        a.setflags(write=False)
    return InterleavedSchedule(op_a, mb_a, ck_a, Z, ring, V)


@functools.lru_cache(maxsize=64)
def schedule_interleaved(
    num_stages: int, num_microbatches: int, virtual_stages: int
) -> InterleavedSchedule:
    """Interleaved-1F1B timetable for S stages × v chunks, M microbatches.

    Tries (stash, ring) depths shallow-first and returns the first
    that completes — measured: (2S, 2) suffices everywhere tested and
    yields exactly the ideal bubble. The returned depths size the
    kernel's buffers. Cached: the trainer computes the same table for
    its startup log and its step factory (pure O(slots·S) Python).
    """
    S, M, V = num_stages, num_microbatches, virtual_stages
    if S < 2:
        raise ValueError("interleaved 1F1B needs a pipe axis of >= 2 stages")
    if V < 1:
        raise ValueError(f"virtual_stages must be >= 1, got {V}")
    if M % S:
        raise ValueError(f"{M} microbatches not divisible by {S} stages")
    last = None
    for Z in (S, 2 * S, 4 * S, max(M, 1)):
        for ring in (2, 4):
            try:
                return _simulate(S, M, V, min(Z, max(M, 1)), min(ring, max(M, 1)))
            except _Deadlock as e:  # deepen the buffers and retry
                last = e
    raise RuntimeError(f"interleaved schedule did not converge: {last}")


def spmd_pipeline_interleaved(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,
    labels: jax.Array,
    loss_fn: Callable[[jax.Array, jax.Array], tuple[jax.Array, Any]],
    schedule: InterleavedSchedule,
    *,
    axis_name: str = "pipe",
    first_fn: Callable[[Any, jax.Array], jax.Array] | None = None,
    first_params: Any = None,
    last_fn: Callable[[Any, jax.Array], jax.Array] | None = None,
    last_params: Any = None,
):
    """Run the combined forward+backward interleaved-1F1B timetable.

    Call INSIDE shard_map over ``axis_name``. Mirrors
    ``one_f1b.spmd_pipeline_1f1b`` except ``stage_params`` carries this
    device's v chunk slices on a leading [v, 1] dim (global layout
    [v, S, …] sharded P(None, pipe): local chunk k is global chunk
    k·S + d). ``first_fn`` runs inside (device 0, chunk 0);
    ``last_fn`` + loss inside (device S−1, chunk v−1).

    Returns ``(loss_sum, aux_sum, g_stage, g_first, g_last)`` with
    ``g_stage`` shaped like the local chunk slices ([v, 1, …] for
    ``out_specs=P(None, axis_name)``). Gradients are SUMS over
    microbatches — divide by the global batch outside.
    """
    params = jax.tree.map(lambda p: p[:, 0], stage_params)  # [v, ...]
    stage = lax.axis_index(axis_name)
    S = lax.psum(1, axis_name)
    if S < 2:
        raise ValueError("interleaved 1F1B needs a pipe axis of >= 2 stages")
    V = schedule.virtual_stages
    Z = schedule.stash_depth
    RD = schedule.ring_depth
    local_in = microbatches[:, 0]  # [R, mb, ...]
    R = local_in.shape[0]
    assert schedule.op.shape[1] == S, (schedule.op.shape, S)

    if first_fn is None:
        first_fn = lambda p, x: x
    if last_fn is None:
        last_fn = lambda p, x: x
    raw_shape = jax.eval_shape(lambda x: x, local_in[0])
    act_shape = jax.eval_shape(first_fn, first_params, local_in[0])

    fwd_shift = [(i, (i + 1) % S) for i in range(S)]
    bwd_shift = [(i, (i - 1) % S) for i in range(S)]

    op_tab = jnp.asarray(schedule.op)
    mb_tab = jnp.asarray(schedule.mb)
    ck_tab = jnp.asarray(schedule.ck)

    def chunk_params(k):
        return jax.tree.map(
            lambda p: lax.dynamic_index_in_dim(p, k, 0, keepdims=False),
            params,
        )

    def zero_grads():
        zg = jax.tree.map(lambda p: jnp.zeros_like(p[0]), params)
        zf = jax.tree.map(jnp.zeros_like, first_params)
        zl = jax.tree.map(jnp.zeros_like, last_params)
        return zg, zf, zl

    def slot(carry, xs):
        (pend_act, pend_cot, stash_act,
         g_stage, g_first, g_last, loss_acc, aux_acc) = carry
        op_row, mb_row, ck_row = xs
        m0 = mb_row[0]  # device 0's microbatch: the raw-fetch index
        my_op = op_row[stage]
        my_m = mb_row[stage]
        my_k = jnp.clip(ck_row[stage], 0, V - 1)
        ring = my_m % RD

        # Device-0 raw-microbatch fetch (chunk-0 forward consumes it,
        # chunk-0 backward re-fetches it instead of stashing) — same
        # masked-psum transport as the plain schedules: microbatch m
        # rests on its home shard m mod S.
        fresh = lax.psum(
            jnp.where(
                stage == m0 % S,
                lax.dynamic_index_in_dim(
                    local_in, jnp.clip(m0 // S, 0, R - 1), 0, keepdims=False
                ),
                jnp.zeros(raw_shape.shape, raw_shape.dtype),
            ),
            axis_name,
        )

        params_k = chunk_params(my_k)
        pend_act_k = pend_act[my_k, ring]
        pend_cot_k = pend_cot[my_k, ring]
        slot_idx = my_m % Z
        is_c0 = (stage == 0) & (my_k == 0)
        is_last = (stage == S - 1) & (my_k == V - 1)
        zero_act = jnp.zeros(act_shape.shape, act_shape.dtype)

        def do_idle(args):
            (pend_act, pend_cot, stash_act,
             g_stage, g_first, g_last, loss_acc, aux_acc) = args
            return (
                pend_act, pend_cot, stash_act,
                g_stage, g_first, g_last, loss_acc, aux_acc,
                zero_act, zero_act,
            )

        def do_fwd(args):
            (pend_act, pend_cot, stash_act,
             g_stage, g_first, g_last, loss_acc, aux_acc) = args
            x_in = lax.cond(
                is_c0,
                lambda: first_fn(first_params, fresh).astype(zero_act.dtype),
                lambda: pend_act_k,
            )
            stash_act = stash_act.at[my_k, slot_idx].set(x_in)
            y = stage_fn(params_k, x_in)
            return (
                pend_act, pend_cot, stash_act,
                g_stage, g_first, g_last, loss_acc, aux_acc,
                y, zero_act,
            )

        def do_bwd(args):
            (pend_act, pend_cot, stash_act,
             g_stage, g_first, g_last, loss_acc, aux_acc) = args
            raw_m = fresh
            act_m = stash_act[my_k, slot_idx]
            lbl_m = lax.dynamic_index_in_dim(
                labels, jnp.clip(my_m, 0, labels.shape[0] - 1), 0,
                keepdims=False,
            )

            def bwd_first(_):
                def f(sp, fp):
                    return stage_fn(sp, first_fn(fp, raw_m).astype(act_m.dtype))

                _, vjp = jax.vjp(f, params_k, first_params)
                gs, gf = vjp(pend_cot_k)
                _, zf, zl = zero_grads()
                return gs, gf, zl, zero_act, jnp.float32(0), jnp.float32(0)

            def bwd_mid(_):
                def f(sp, x):
                    return stage_fn(sp, x)

                _, vjp = jax.vjp(f, params_k, act_m)
                gs, gx = vjp(pend_cot_k)
                _, zf, zl = zero_grads()
                return gs, zf, zl, gx, jnp.float32(0), jnp.float32(0)

            def bwd_last(_):
                def f(sp, lp, x):
                    out = last_fn(lp, stage_fn(sp, x))
                    loss, aux = loss_fn(out, lbl_m)
                    return loss, aux

                loss, vjp, aux = jax.vjp(
                    f, params_k, last_params, act_m, has_aux=True
                )
                gs, gl, gx = vjp(jnp.float32(1.0))
                _, zf, _ = zero_grads()
                return (
                    gs, zf, gl, gx,
                    loss.astype(jnp.float32), jnp.asarray(aux, jnp.float32),
                )

            role = jnp.where(is_c0, 0, jnp.where(is_last, 2, 1))
            gs, gf, gl, gx, loss, aux = lax.switch(
                role, [bwd_first, bwd_mid, bwd_last], None
            )
            g_stage = jax.tree.map(
                lambda G, g: G.at[my_k].add(g), g_stage, gs
            )
            g_first = jax.tree.map(jnp.add, g_first, gf)
            g_last = jax.tree.map(jnp.add, g_last, gl)
            return (
                pend_act, pend_cot, stash_act,
                g_stage, g_first, g_last,
                loss_acc + loss, aux_acc + aux,
                zero_act, gx,
            )

        out = lax.switch(
            my_op, [do_idle, do_fwd, do_bwd],
            (pend_act, pend_cot, stash_act,
             g_stage, g_first, g_last, loss_acc, aux_acc),
        )
        (pend_act, pend_cot, stash_act,
         g_stage, g_first, g_last, loss_acc, aux_acc,
         act_msg, cot_msg) = out

        # Cyclic ring transport; receivers latch per the table. The
        # activation ring wraps S−1 → 0 carrying chunk k → k+1 (the
        # interleaving); the final chunk's forward output has no
        # receiver (its loss runs in its own backward) and the first
        # chunk's backward emits no cotangent — both masked below.
        act_arrived = lax.ppermute(act_msg, axis_name, fwd_shift)
        cot_arrived = lax.ppermute(cot_msg, axis_name, bwd_shift)
        up = (stage - 1) % S
        down = (stage + 1) % S
        up_op, up_ck, up_m = op_row[up], ck_row[up], mb_row[up]
        down_op, down_ck, down_m = op_row[down], ck_row[down], mb_row[down]
        act_k = jnp.clip(jnp.where(stage > 0, up_ck, up_ck + 1), 0, V - 1)
        act_take = (up_op == FWD) & ~((up == S - 1) & (up_ck == V - 1))
        pend_act = jnp.where(
            act_take,
            pend_act.at[act_k, up_m % RD].set(act_arrived),
            pend_act,
        )
        cot_k = jnp.clip(
            jnp.where(stage < S - 1, down_ck, down_ck - 1), 0, V - 1
        )
        cot_take = (down_op == BWD) & ~((down == 0) & (down_ck == 0))
        pend_cot = jnp.where(
            cot_take,
            pend_cot.at[cot_k, down_m % RD].set(cot_arrived),
            pend_cot,
        )
        return (
            pend_act, pend_cot, stash_act,
            g_stage, g_first, g_last, loss_acc, aux_acc,
        ), None

    zg, zf, zl = zero_grads()
    g0 = jax.tree.map(
        lambda z: jnp.zeros((V, *z.shape), z.dtype), zg
    )
    carry = (
        jnp.zeros((V, RD, *act_shape.shape), act_shape.dtype),
        jnp.zeros((V, RD, *act_shape.shape), act_shape.dtype),
        jnp.zeros((V, Z, *act_shape.shape), act_shape.dtype),
        g0, zf, zl,
        jnp.float32(0.0),
        jnp.float32(0.0),
    )
    carry, _ = lax.scan(slot, carry, (op_tab, mb_tab, ck_tab))
    (_, _, _, g_stage, g_first, g_last, loss_acc, aux_acc) = carry

    loss_sum = lax.psum(loss_acc, axis_name)
    aux_sum = lax.psum(aux_acc, axis_name)
    g_first = lax.psum(g_first, axis_name)
    g_last = lax.psum(g_last, axis_name)
    return (
        loss_sum, aux_sum,
        jax.tree.map(lambda g: g[:, None], g_stage),
        g_first, g_last,
    )

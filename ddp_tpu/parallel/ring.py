"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has no attention and no sequence dimension at all
(/root/reference/model.py:8-16 is conv+linear on 28×28 images;
SURVEY.md §2c/§5 "long-context: absent"), but long-context attention is
a first-class capability of this framework: sequences longer than one
chip's HBM are sharded on the ``seq`` mesh axis and attention runs as a
collective program.

Two standard strategies, both implementing the framework's attention
contract ``fn(q, k, v) -> out`` on [B, T_local, H, D] shards (tokens
sharded over ``seq``), exact to fp32 tolerance vs. dense attention on
the gathered sequence:

- **Ring attention** (`ring_attention`): K/V blocks rotate around the
  ring via ``lax.ppermute`` while each device's Q stays put; a running
  online-softmax (same recurrence as
  ``ops.attention.blockwise_attention``) folds each arriving block into
  the accumulator. Memory is O(T_local) per device for any total T;
  each hop's transfer rides one ICI neighbor link and XLA overlaps it
  with the block matmuls. No head-count constraint.
- **Ulysses / all-to-all** (`ulysses_attention`): one
  ``lax.all_to_all`` re-shards from sequence-sharded to head-sharded,
  dense attention runs locally over the full sequence with H/n heads,
  a second all-to-all re-shards back. Two collectives total instead of
  n-1 hops — cheaper when heads divide evenly and T fits in HBM.

``sequence_sharded_attention`` picks between them; both compose with
data parallelism (batch on ``data``, tokens on ``seq``) because they
only ever name the ``seq`` axis.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def ring_attention(q, k, v, *, axis_name: str = "seq", causal: bool = False):
    """Exact attention with K/V rotating around the ``axis_name`` ring.

    Args: q, k, v — [B, T_local, H, D] shards (inside shard_map, tokens
    sharded over ``axis_name``). Matches
    ``ops.attention.dot_product_attention`` over the gathered sequence;
    ``causal=True`` applies the global causal mask — position masking
    uses each hop's GLOBAL block offset, so the triangular structure is
    exact across shard boundaries (the diagonal block arrives at hop 0,
    so every query row is live before any fully-masked block folds in).
    """
    from ddp_tpu.ops.attention import MASK_VALUE

    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    qf = q.astype(jnp.float32)
    scale = D**-0.5
    # Send to the next device, receive from the previous: after hop j,
    # this device holds the K/V block of (my_index - j) mod n.
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    q_pos = my_idx * T + jnp.arange(T)  # global query positions

    def fold(carry, hop):
        acc, row_max, row_sum, kb, vb = carry
        # Rotate first and let XLA overlap the ppermute with the block
        # compute on the *current* kb/vb (no data dependence between them).
        kb_next = lax.ppermute(kb, axis_name, perm)
        vb_next = lax.ppermute(vb, axis_name, perm)
        logits = (
            jnp.einsum("bthd,bshd->bhts", qf, kb.astype(jnp.float32)) * scale
        )  # [B, H, T_local, S_block]
        if causal:
            src = (my_idx - hop) % axis_size  # whose block this is
            k_pos = src * kb.shape[1] + jnp.arange(kb.shape[1])
            mask = q_pos[:, None] >= k_pos[None, :]  # [T_local, S_block]
            logits = jnp.where(mask, logits, MASK_VALUE)
        new_max = jnp.maximum(row_max, logits.max(axis=-1))
        corr = jnp.exp(row_max - new_max)
        p = jnp.exp(logits - new_max[..., None])
        acc = acc * corr[..., None] + jnp.einsum(
            "bhts,bshd->bthd", p, vb.astype(jnp.float32)
        ).transpose(0, 2, 1, 3)
        row_sum = row_sum * corr + p.sum(axis=-1)
        return (acc, new_max, row_sum, kb_next, vb_next), None

    acc0 = jnp.zeros((B, H, T, D), jnp.float32)
    max0 = jnp.full((B, H, T), -jnp.inf, jnp.float32)
    sum0 = jnp.zeros((B, H, T), jnp.float32)
    (acc, _, row_sum, _, _), _ = lax.scan(
        fold, (acc0, max0, sum0, k, v), jnp.arange(axis_size)
    )
    out = acc / row_sum[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ulysses_attention(
    q, k, v, *, axis_name: str = "seq", attention_fn=None,
    causal: bool = False,
):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style).

    Re-shards [B, T/n, H, D] → [B, T, H/n, D] with one ``all_to_all``,
    runs ``attention_fn`` (dense by default; causal dense when
    ``causal``) over the full sequence on the local head subset, then
    re-shards back. Requires H divisible by the axis size.
    """
    from ddp_tpu.ops.attention import dot_product_attention

    if attention_fn is None:
        attention_fn = partial(dot_product_attention, causal=causal)
    elif causal:
        raise ValueError("pass causality through your attention_fn")
    n = lax.psum(1, axis_name)
    H = q.shape[2]
    if H % n:
        raise ValueError(f"{H} heads not divisible by seq axis size {n}")
    # [B, T/n, H, D] → gather tokens, scatter heads → [B, T, H/n, D]
    to_heads = partial(
        lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1,
        tiled=True,
    )
    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = attention_fn(qh, kh, vh)  # [B, T, H/n, D]
    # gather heads, scatter tokens → [B, T/n, H, D]
    return lax.all_to_all(
        out, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def sequence_sharded_attention(
    q, k, v, *, axis_name: str = "seq", strategy: str = "ring",
    causal: bool = False,
):
    """Dispatch: ``strategy`` ∈ {"ring", "ulysses"}."""
    if strategy == "ring":
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal)
    if strategy == "ulysses":
        return ulysses_attention(q, k, v, axis_name=axis_name, causal=causal)
    raise ValueError(f"unknown sequence-parallel strategy {strategy!r}")

"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has no attention and no sequence dimension at all
(/root/reference/model.py:8-16 is conv+linear on 28×28 images;
SURVEY.md §2c/§5 "long-context: absent"), but long-context attention is
a first-class capability of this framework: sequences longer than one
chip's HBM are sharded on the ``seq`` mesh axis and attention runs as a
collective program.

Two standard strategies, both implementing the framework's attention
contract ``fn(q, k, v) -> out`` on [B, T_local, H, D] shards (tokens
sharded over ``seq``), exact to fp32 tolerance vs. dense attention on
the gathered sequence:

- **Ring attention** (`ring_attention`): K/V blocks rotate around the
  ring via ``lax.ppermute`` while each device's Q stays put; a running
  online-softmax (same recurrence as
  ``ops.attention.blockwise_attention``) folds each arriving block into
  the accumulator. Memory is O(T_local) per device for any total T;
  each hop's transfer rides one ICI neighbor link and XLA overlaps it
  with the block matmuls. No head-count constraint.
- **Ulysses / all-to-all** (`ulysses_attention`): one
  ``lax.all_to_all`` re-shards from sequence-sharded to head-sharded,
  dense attention runs locally over the full sequence with H/n heads,
  a second all-to-all re-shards back. Two collectives total instead of
  n-1 hops — cheaper when heads divide evenly and T fits in HBM.

``sequence_sharded_attention`` picks between them; both compose with
data parallelism (batch on ``data``, tokens on ``seq``) because they
only ever name the ``seq`` axis.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _xla_block_with_lse(q, k, v, causal: bool):
    """Dense per-block attention returning (out, lse [B, T, H] fp32).

    The CPU/fallback twin of ``ops.flash.flash_attention_with_lse`` —
    same contract, plain XLA einsums, fp32 accumulation. Only ever sees
    ONE ring block (O(T_local²) logits), not the global sequence.
    """
    scale = q.shape[-1] ** -0.5
    logits = (
        jnp.einsum(
            "bthd,bshd->bhts", q.astype(jnp.float32), k.astype(jnp.float32)
        )
        * scale
    )  # [B, H, T, S]
    if causal:
        T, S = logits.shape[-2:]
        mask = jnp.arange(T)[:, None] >= jnp.arange(S)[None, :]
        logits = jnp.where(mask, logits, -jnp.inf)
    lse = jax.nn.logsumexp(logits, axis=-1)  # [B, H, T]
    out = jnp.einsum(
        "bhts,bshd->bthd", jnp.exp(logits - lse[..., None]),
        v.astype(jnp.float32),
    )
    return out.astype(q.dtype), lse.transpose(0, 2, 1)


def _default_block_fn(q, k, v, causal: bool):
    """Per-hop block attention: Pallas flash kernel on TPU for blocks
    past the crossover length, XLA elsewhere (short blocks lose to one
    fused einsum chain — ops.attention.FLASH_MIN_LEN)."""
    from ddp_tpu.ops.attention import FLASH_MIN_LEN

    if jax.default_backend() == "tpu" and k.shape[1] >= FLASH_MIN_LEN:
        from ddp_tpu.ops.flash import flash_attention_with_lse

        return flash_attention_with_lse(q, k, v, causal, 512, 512, False)
    return _xla_block_with_lse(q, k, v, causal)


def combine_attention_partials(o1, l1, o2, l2):
    """Merge two partial attention results over disjoint key sets.

    Inputs/outputs: ``o`` [B, T, H, D], ``l`` (logsumexp rows)
    [B, T, H]. The identity: softmax over K₁∪K₂ equals the lse-weighted
    average of the per-set softmax outputs. ``l = -inf`` denotes "no
    keys seen yet", so the zero-init carry folds in for free.
    Associative and differentiable — this is how ring attention hops
    and (in tests) independently-computed halves compose exactly.
    """
    m = jnp.maximum(l1, l2)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    w1 = jnp.exp(l1 - m_safe)
    w2 = jnp.exp(l2 - m_safe)
    denom = w1 + w2
    l_new = jnp.where(
        denom > 0.0, m_safe + jnp.log(jnp.maximum(denom, 1e-30)), -jnp.inf
    )
    norm = jnp.maximum(denom, 1e-30)[..., None]
    # Stays fp32: the ring scan carries this accumulator across hops,
    # and rounding to the compute dtype at every hop would compound
    # bf16 error with the axis size. Callers cast once at the end.
    o_new = (
        o1.astype(jnp.float32) * w1[..., None]
        + o2.astype(jnp.float32) * w2[..., None]
    ) / norm
    return o_new, l_new


def ring_attention(
    q, k, v, *, axis_name: str = "seq", causal: bool = False, block_fn=None
):
    """Exact attention with K/V rotating around the ``axis_name`` ring.

    Args: q, k, v — [B, T_local, H, D] shards (inside shard_map, tokens
    sharded over ``axis_name``). Matches
    ``ops.attention.dot_product_attention`` over the gathered sequence;
    ``causal=True`` applies the global causal mask exactly across shard
    boundaries.

    Each hop's block compute is one fused attention call —
    ``block_fn(q, kb, vb, causal) -> (out, lse)`` — defaulting to the
    Pallas flash kernel on TPU (MXU matmuls, O(T_local) memory;
    VERDICT.md weak #5: the round-1 fold was unfused fp32 einsum) and a
    dense XLA block elsewhere. Hop results merge through the
    (out, lse) combine; causality routes per hop on the GLOBAL block
    offset: hop 0 is this device's own (diagonal) block under a
    standard causal mask, hops 1..my_idx are strictly-past blocks with
    no mask, and strictly-future hops are **skipped entirely** under
    ``lax.cond`` — no FLOPs burned producing all-masked logits (the
    round-1 version computed and discarded them). The ``ppermute``
    rotation stays outside the cond (collectives must run uniformly)
    and overlaps with the block compute — no data dependence between
    them.
    """
    if block_fn is None:
        block_fn = _default_block_fn
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    # Send to the next device, receive from the previous: after hop j,
    # this device holds the K/V block of (my_index - j) mod n.
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # Hop 0: the diagonal block (own K/V) — causal iff globally causal.
    # The carry accumulates in fp32 regardless of compute dtype.
    o, l = block_fn(q, k, v, causal)
    o = o.astype(jnp.float32)
    kb = lax.ppermute(k, axis_name, perm)
    vb = lax.ppermute(v, axis_name, perm)

    def fold(carry, hop):
        o, l, kb, vb = carry
        kb_next = lax.ppermute(kb, axis_name, perm)
        vb_next = lax.ppermute(vb, axis_name, perm)

        def live(args):
            o, l = args
            o2, l2 = block_fn(q, kb, vb, False)
            return combine_attention_partials(o, l, o2, l2)

        if causal:
            # Block from src = my_idx - hop; live only when src >= 0
            # (strictly past). Future blocks: skip the compute.
            o, l = lax.cond(hop <= my_idx, live, lambda args: args, (o, l))
        else:
            o, l = live((o, l))
        return (o, l, kb_next, vb_next), None

    (o, l, _, _), _ = lax.scan(
        fold, (o, l, kb, vb), jnp.arange(1, axis_size)
    )
    return o.astype(q.dtype)


def ulysses_attention(
    q, k, v, *, axis_name: str = "seq", attention_fn=None,
    causal: bool = False,
):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style).

    Re-shards [B, T/n, H, D] → [B, T, H/n, D] with one ``all_to_all``,
    runs ``attention_fn`` (dense by default; causal dense when
    ``causal``) over the full sequence on the local head subset, then
    re-shards back. Requires H divisible by the axis size.
    """
    from ddp_tpu.ops.attention import best_attention

    if attention_fn is None:
        # Size-dispatched (flash on TPU past FLASH_MIN_LEN, dense
        # otherwise) — after the all-to-all the local [B, T, H/n, D]
        # tensor is an ordinary full-sequence attention problem.
        attention_fn = best_attention(causal=causal)
    elif causal:
        raise ValueError("pass causality through your attention_fn")
    n = lax.psum(1, axis_name)
    H = q.shape[2]
    if H % n:
        raise ValueError(f"{H} heads not divisible by seq axis size {n}")
    # [B, T/n, H, D] → gather tokens, scatter heads → [B, T, H/n, D]
    to_heads = partial(
        lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1,
        tiled=True,
    )
    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = attention_fn(qh, kh, vh)  # [B, T, H/n, D]
    # gather heads, scatter tokens → [B, T/n, H, D]
    return lax.all_to_all(
        out, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def sequence_sharded_attention(
    q, k, v, *, axis_name: str = "seq", strategy: str = "ring",
    causal: bool = False,
):
    """Dispatch: ``strategy`` ∈ {"ring", "ulysses"}."""
    if strategy == "ring":
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal)
    if strategy == "ulysses":
        return ulysses_attention(q, k, v, axis_name=axis_name, causal=causal)
    raise ValueError(f"unknown sequence-parallel strategy {strategy!r}")

"""Shared train-step machinery for the DDP (shard_map) and GSPMD paths.

Both `parallel.ddp` and `parallel.spmd` compile the same per-batch
computation — preprocess, apply, softmax-xent (+ MoE aux loss), and
optionally a `lax.scan` over gradient-accumulation microbatches — and
differ only in how the result is reduced across the mesh (explicit
`pmean` inside shard_map vs. GSPMD-derived collectives). The common
pieces live here so a fix to one path cannot silently miss the other.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax


def _train_kwarg(model, train: bool) -> dict:
    """``{'train': train}`` if the model's __call__ takes it, else {}.

    SimpleCNN has no train/eval mode distinction (neither does the
    reference's, model.py:18-20); the ResNet/ViT families do (BatchNorm,
    dropout).
    """
    import inspect

    sig = inspect.signature(type(model).__call__)
    return {"train": train} if "train" in sig.parameters else {}


def _preprocess(images: jax.Array, compute_dtype) -> jax.Array:
    """ToTensor parity (data.py:13): uint8 → float / 255, nothing else.

    Runs on-device inside the step so the pipeline ships uint8.
    """
    if images.dtype == jnp.uint8:
        images = images.astype(compute_dtype) / jnp.asarray(255.0, compute_dtype)
    return images.astype(compute_dtype)


def xent(logits32, labels, label_smoothing: float = 0.0):
    """Per-example softmax cross-entropy, optionally α-smoothed.

    The one definition of the smoothing semantics for every step
    family (plain/GSPMD, seq, pipe): α > 0 trains against
    ``(1-α)·one_hot + α/num_classes`` targets. Unreduced — callers
    mean over the batch (or sum per microbatch and divide outside,
    as the 1F1B schedule does).
    """
    if not 0.0 <= label_smoothing < 1.0:
        raise ValueError(
            f"label_smoothing must be in [0, 1), got {label_smoothing}"
        )
    if label_smoothing:
        targets = optax.smooth_labels(
            jax.nn.one_hot(labels, logits32.shape[-1]), label_smoothing
        )
        return optax.softmax_cross_entropy(logits32, targets)
    return optax.softmax_cross_entropy_with_integer_labels(logits32, labels)


def make_loss_fn(
    model,
    compute_dtype,
    aux_loss_weight: float,
    augment_fn=None,
    label_smoothing: float = 0.0,
):
    """``loss_fn(params, model_state, images, labels, rng, mutable)``.

    Returns ``(loss, (logits, new_model_state))`` — mean softmax
    cross-entropy plus the weighted MoE load-balance aux losses when
    the model records a ``losses`` collection (models/moe.py).
    ``augment_fn(rng, images)`` (data/augment.py), when given, runs
    on-device after the uint8→float conversion. ``label_smoothing``
    α > 0 trains against ``(1-α)·one_hot + α/num_classes`` targets
    (the ViT/ResNet recipe staple; the reference trains on hard
    targets only, train_ddp.py:40).
    """
    train_kw = _train_kwarg(model, True)
    if not 0.0 <= label_smoothing < 1.0:
        raise ValueError(f"label_smoothing must be in [0, 1), got {label_smoothing}")

    def loss_fn(params, model_state, images, labels, rng, mutable):
        x = _preprocess(images, compute_dtype)
        if augment_fn is not None:
            x = augment_fn(jax.random.fold_in(rng, 7919), x).astype(x.dtype)
        if compute_dtype != jnp.float32:
            params_c = jax.tree.map(lambda p: p.astype(compute_dtype), params)
        else:
            params_c = params
        variables = {"params": params_c, **model_state}
        if mutable:
            logits, new_ms = model.apply(
                variables, x, mutable=mutable, rngs={"dropout": rng}, **train_kw
            )
        else:
            logits = model.apply(variables, x, rngs={"dropout": rng}, **train_kw)
            new_ms = model_state
        logits32 = logits.astype(jnp.float32)
        loss = xent(logits32, labels, label_smoothing).mean()
        if "losses" in mutable:
            loss = loss + aux_loss_weight * sum(
                jax.tree.leaves(new_ms["losses"])
            )
        return loss, (logits, new_ms)

    return loss_fn


def check_accum_divisible(batch: int, grad_accum_steps: int) -> int:
    """Microbatch size, validated at trace time (shapes are static)."""
    if grad_accum_steps < 1:
        raise ValueError(f"grad_accum_steps must be ≥ 1, got {grad_accum_steps}")
    if batch % grad_accum_steps or batch < grad_accum_steps:
        raise ValueError(
            f"batch of {batch} not divisible into {grad_accum_steps} "
            f"non-empty microbatches"
        )
    return batch // grad_accum_steps


def grad_accum_scan(loss_fn, params, model_state, imgs, lbls, rng, mutable):
    """Accumulate gradients over stacked microbatches ``[k, mb, ...]``.

    One `lax.scan` over k microbatches: model-state (BatchNorm stats,
    MoE aux) chains through the carry; gradients and losses average;
    correct-prediction counts sum. Returns
    ``(mean_grads, new_model_state, mean_loss, correct_count)``.
    """
    k = imgs.shape[0]

    def micro(carry, xy):
        g_acc, ms, loss_acc, correct_acc, i = carry
        x, y = xy
        (loss, (logits, new_ms)), g = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params, ms, x, y, jax.random.fold_in(rng, i), mutable)
        g_acc = jax.tree.map(jnp.add, g_acc, g)
        c = (jnp.argmax(logits.astype(jnp.float32), -1) == y).sum()
        return (
            g_acc,
            new_ms,
            loss_acc + loss,
            correct_acc + c.astype(jnp.float32),
            i + 1,
        ), None

    zero_g = jax.tree.map(jnp.zeros_like, params)
    (g_sum, new_ms, loss_sum, correct, _), _ = lax.scan(
        micro,
        (
            zero_g,
            model_state,
            jnp.zeros((), jnp.float32),
            jnp.zeros((), jnp.float32),
            jnp.zeros((), jnp.int32),
        ),
        (imgs, lbls),
    )
    grads = jax.tree.map(lambda g: g / k, g_sum)
    return grads, new_ms, loss_sum / k, correct

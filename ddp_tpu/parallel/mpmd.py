"""MPMD pipeline runtime: one process per stage, p2p activations.

Every pipeline schedule in this repo so far (``parallel/pipeline.py``,
``one_f1b.py``, ``interleaved.py``, pipe_vit) is in-graph SPMD: all S
stages live in ONE XLA program, every host compiles the whole model,
and a stage re-placement means recompiling the world — which is why
PR 8's ``--elastic`` had to reject the entire pipe family. This module
is the MPMD alternative (PAPERS.md #2, ROADMAP item 2): each stage is
a separate OS process that compiles ONLY its 1/S of the model, and
activations / activation-cotangents cross stage boundaries as
point-to-point messages (``runtime/p2p.py`` — the DPKV wire
discipline applied to activation tensors) instead of in-graph
collectives.

Topology (2-stage; docs/COMPOSITIONS.md has the full diagram)::

    supervisor (no JAX) ── control TCP, JSON-lines ──┐
        │ spawn/classify-exit/backoff                │
        ├── stage 0 process: embed+stage0  ═ p2p ═ stage 1 process:
        │       fwd/bwd/update jits            stage1+LN+tied head
        └── metrics JSONL (shared, line-append atomic)

Per step, every stage walks its own column of the SAME
``schedule_1f1b`` timetable the in-graph schedule uses, so microbatch
``i``'s forward on stage k overlaps microbatch ``i-1``'s backward on
stage k+1 — the schedule is identical, only the transport changed.
The math is parity-pinned against ``make_pipe_lm_1f1b_train_step``:
strided microbatch split, loss inside the last stage, grads summed
over microbatches then divided by ``B*(T-1)``, tied-embed lookup+head
grads combined, per-leaf optimizer update, ``global_norm`` over the
full divided grad tree (assembled across stages via one sync
relay per step).

What restarts vs what recompiles: a SIGKILLed stage is respawned by
the supervisor (``classify_exit`` + backoff, ``runtime/launch.py``
machinery with PR 13's ReplicaManager as the topology template),
restores its OWN stage-sliced checkpoint (plain npz + the
``train/checkpoint.py`` manifest discipline), and recompiles only its
1/S; surviving stages roll back to the common resume step from their
own checkpoints WITHOUT recompiling (their jit caches live on), and
the replayed microbatches are regenerated deterministically from
(seed, step). Elasticity is the same mechanism: shrink/grow is a
supervisor re-placement decision (respawn with a new stage partition),
not a recompile-the-world event — which is what lifts the pipe-family
``--elastic`` rejection for the MPMD path.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import multiprocessing
import os
import queue
import shutil
import socket
import threading
import time
from typing import Dict, Optional

import numpy as np

from ddp_tpu.obs.reqtrace import (
    STEP_CAT,
    derive_span_id,
    derive_trace_id,
    encode_trace_context,
    format_trace_id,
    parse_trace_context,
)
from ddp_tpu.runtime import p2p
from ddp_tpu.runtime.chaos import ChaosEngine, stage_events
from ddp_tpu.runtime.launch import classify_exit
from ddp_tpu.utils.metrics import MetricsWriter

logger = logging.getLogger("ddp_tpu")

# NOTE: no jax / pipeline_lm imports at module top. multiprocessing
# 'spawn' children import this module while unpickling the stage
# entrypoint, BEFORE ``_stage_entry`` can pin JAX_PLATFORMS/XLA_FLAGS
# — every accelerator-touching import stays inside functions (the
# runtime/launch.py lazy-import idiom).


@dataclasses.dataclass(frozen=True)
class MPMDConfig:
    """One MPMD pipeline run: model shape + schedule + supervision.

    ``optimizer`` must be a PER-LEAF transformation (sgd/adam/adamw):
    each stage updates only its partition, so a cross-leaf global
    statistic (e.g. global-norm clipping) would need another sync
    round — rejected rather than silently wrong.
    """

    vocab_size: int = 64
    seq_len: int = 16
    d_model: int = 32
    num_heads: int = 4
    mlp_ratio: int = 4
    num_stages: int = 2
    depth_per_stage: int = 1
    num_microbatches: int = 4
    label_smoothing: float = 0.0
    batch_size: int = 8
    steps: int = 8
    seed: int = 0
    optimizer: str = "sgd"
    lr: float = 0.1
    grad_accum_steps: int = 1
    ckpt_every: int = 1
    keep_ckpts: int = 5
    max_restarts: int = 2
    restart_backoff_s: float = 0.05
    chaos: str = ""
    io_timeout_s: float = 180.0

    def __post_init__(self):
        if self.num_stages < 2:
            raise ValueError("MPMD needs >= 2 stages (else just jit)")
        if self.batch_size % self.num_microbatches:
            raise ValueError(
                f"batch_size {self.batch_size} not divisible by "
                f"num_microbatches {self.num_microbatches}"
            )
        if self.optimizer not in ("sgd", "adam", "adamw"):
            raise ValueError(
                f"optimizer {self.optimizer!r} not per-leaf — MPMD "
                "supports sgd/adam/adamw"
            )
        if self.grad_accum_steps < 1 or self.steps < 1:
            raise ValueError("steps and grad_accum_steps must be >= 1")

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "MPMDConfig":
        return cls(**json.loads(s))


def batch_for_step(
    cfg: MPMDConfig, step: int, accum: int = 0
) -> np.ndarray:
    """The [B, T] int32 token batch for (step, accum chunk) — a pure
    function of the config seed, so a restarted stage replays the
    exact bytes the dead incarnation saw without any data-log."""
    rng = np.random.default_rng([cfg.seed, step, accum])
    return rng.integers(
        0, cfg.vocab_size, (cfg.batch_size, cfg.seq_len)
    ).astype(np.int32)


def _pipe_cfg(cfg: MPMDConfig):
    from ddp_tpu.models.pipeline_lm import PipeLMConfig

    return PipeLMConfig(
        vocab_size=cfg.vocab_size,
        seq_len=cfg.seq_len,
        d_model=cfg.d_model,
        num_heads=cfg.num_heads,
        mlp_ratio=cfg.mlp_ratio,
        num_stages=cfg.num_stages,
        depth_per_stage=cfg.depth_per_stage,
        num_microbatches=cfg.num_microbatches,
        label_smoothing=cfg.label_smoothing,
    )


def _make_optimizer(name: str, lr: float):
    import optax

    return {
        "sgd": optax.sgd,
        "adam": optax.adam,
        "adamw": optax.adamw,
    }[name](lr)


def stage_param_slice(cfg: MPMDConfig, k: int) -> dict:
    """Stage k's parameter partition, derived from the SAME seeded
    full init every stage runs (init is cheap at these scales and
    needs no cross-process handshake; only the slice is KEPT).

    Keys: ``stage`` everywhere; ``front`` (embed + pos) on stage 0;
    ``back`` (final LN) plus an ``embed`` BUFFER (the tied-head mirror
    of front.embed — refreshed from stage 0 each step via sync_down,
    never updated locally) on the last stage.
    """
    import jax

    from ddp_tpu.models.pipeline_lm import init_pipe_lm

    params = init_pipe_lm(_pipe_cfg(cfg), seed=cfg.seed)
    part = {"stage": jax.tree.map(lambda p: p[k], params.stages)}
    if k == 0:
        part["front"] = dict(params.front)
    if k == cfg.num_stages - 1:
        part["back"] = {"ln": params.back["ln"]}
        part["embed"] = params.front["embed"]
    return part


def _trained(part: dict) -> dict:
    """The optimizer-visible subtree: everything but the last stage's
    ``embed`` mirror (stage 0 owns the canonical tied embedding)."""
    return {k: v for k, v in part.items() if k != "embed"}


class _StagePrograms:
    """Stage k's jitted programs — the ONLY XLA this process compiles.

    Bodies are pure jnp; every host sync (np.asarray on activations,
    float() on scalars) happens in the runner's host loop between
    calls. All programs go through the xprof AOT instrumentation so
    the per-stage ledger records compile seconds for the SPMD-control
    comparison.
    """

    def __init__(self, cfg: MPMDConfig, k: int, xprof):
        import jax
        import jax.numpy as jnp
        import optax

        from ddp_tpu.models.pipeline_lm import (
            _first_fn,
            _loss_fn_factory,
            _make_last_fn,
            _stage_module,
        )

        pcfg = _pipe_cfg(cfg)
        S = cfg.num_stages
        stage = _stage_module(pcfg)
        last_fn = _make_last_fn(pcfg)
        loss_fn = _loss_fn_factory(pcfg)
        self.opt = _make_optimizer(cfg.optimizer, cfg.lr)
        opt = self.opt

        def stage_fn(sp, x):
            return stage.apply({"params": sp}, x)

        if k == 0:

            def fwd0(sp, fp, tok_mb):
                return stage_fn(sp, _first_fn(fp, tok_mb))

            def bwd0(sp, fp, tok_mb, cot):
                def f(sp_, fp_):
                    return stage_fn(sp_, _first_fn(fp_, tok_mb))

                _, vjp = jax.vjp(f, sp, fp)
                return vjp(cot)  # (g_stage, g_front)

            self.fwd = xprof.instrument(jax.jit(fwd0), f"stage{k}_fwd")
            self.bwd = xprof.instrument(jax.jit(bwd0), f"stage{k}_bwd")
        elif k < S - 1:

            def bwd_mid(sp, x, cot):
                _, vjp = jax.vjp(stage_fn, sp, x)
                return vjp(cot)  # (g_stage, g_x)

            self.fwd = xprof.instrument(
                jax.jit(stage_fn), f"stage{k}_fwd"
            )
            self.bwd = xprof.instrument(
                jax.jit(bwd_mid), f"stage{k}_bwd"
            )
        else:
            # Last stage: the loss lives INSIDE the backward (same as
            # the in-graph 1F1B kernels — the forward slot only
            # stashes the inbound activation, the vjp recomputes the
            # stage and differentiates stage∘head∘loss in one pass).
            def bwd_last(sp, lp, x, tok_mb):
                def f(sp_, lp_, x_):
                    logits = last_fn(lp_, stage_fn(sp_, x_))
                    return loss_fn(logits, tok_mb)

                (loss, correct), vjp = jax.vjp(f, sp, lp, x)
                gs, gl, gx = vjp(
                    (jnp.ones((), jnp.float32), jnp.zeros((), jnp.float32))
                )
                return loss, correct, gs, gl, gx

            self.fwd = None
            self.bwd = xprof.instrument(
                jax.jit(bwd_last), f"stage{k}_bwd"
            )

        def update(params, opt_state, grads, denom):
            # Parity contract with _apply_update: grads are SUMS over
            # microbatches (and accum chunks); divide once by denom =
            # A*B*(T-1), then per-leaf update. ``sq`` is this
            # partition's share of ||grads/denom||^2 — summed across
            # stages it reproduces the SPMD step's global_norm exactly
            # (the combined tied-embed grad is counted once, on
            # stage 0's side).
            grads = jax.tree.map(
                lambda g: g.astype(jnp.float32) / denom, grads
            )
            sq = optax.global_norm(grads) ** 2
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, sq

        self.update = xprof.instrument(
            jax.jit(update), f"stage{k}_update"
        )


# --------------------------------------------------------------------
# Stage-sliced checkpoints: plain npz per stage + the manifest
# discipline from train/checkpoint.py. ``epoch_<N>`` holds the state
# a run needs to START step N (epoch_0 = the seeded init).
# --------------------------------------------------------------------


def _stage_ckpt_root(workdir: str, k: int) -> str:
    return os.path.join(workdir, "ck", f"stage{k}")


def _save_stage_ckpt(
    workdir: str, k: int, step: int, state_tree, keep: int
) -> None:
    import jax

    from ddp_tpu.train.checkpoint import write_manifest

    root = _stage_ckpt_root(workdir, k)
    step_dir = os.path.join(root, f"epoch_{step}")
    os.makedirs(step_dir, exist_ok=True)
    leaves = jax.tree.leaves(state_tree)
    payload = {
        f"leaf_{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)
    }
    tmp = os.path.join(step_dir, f".state.npz.tmp.{os.getpid()}")
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, os.path.join(step_dir, "state.npz"))
    write_manifest(root, step)
    # prune: keep the newest ``keep`` epochs (rollback after a peer
    # death needs a few steps of history, not all of it)
    kept = sorted(_stage_ckpt_steps(workdir, k), reverse=True)[keep:]
    for old in kept:
        shutil.rmtree(
            os.path.join(root, f"epoch_{old}"), ignore_errors=True
        )
        try:
            os.remove(os.path.join(root, f"epoch_{old}.manifest.json"))
        except OSError:
            pass


def _stage_ckpt_steps(workdir: str, k: int) -> list[int]:
    root = _stage_ckpt_root(workdir, k)
    try:
        names = os.listdir(root)
    except OSError:
        return []
    steps = []
    for name in names:
        if name.startswith("epoch_") and "." not in name:
            try:
                steps.append(int(name[len("epoch_"):]))
            except ValueError:
                continue
    return sorted(steps)


def _load_stage_ckpt(workdir: str, k: int, step: int, template):
    """One specific epoch -> state tree, or None when missing/torn
    (manifest problems or an unreadable npz both disqualify)."""
    import jax

    from ddp_tpu.train.checkpoint import verify_manifest

    root = _stage_ckpt_root(workdir, k)
    problems = verify_manifest(root, step)
    if problems:
        logger.warning(
            "mpmd stage %d: checkpoint epoch_%d fails its manifest "
            "(%s) — skipping", k, step, "; ".join(problems)
        )
        return None
    path = os.path.join(root, f"epoch_{step}", "state.npz")
    treedef = jax.tree.structure(template)
    n = treedef.num_leaves
    try:
        with np.load(path) as data:
            # commit to device arrays: np leaves carry a different
            # jit-cache signature, and a surviving stage that rolls
            # back must NOT recompile (that's the MPMD selling point)
            leaves = [
                jax.numpy.asarray(data[f"leaf_{i}"]) for i in range(n)
            ]
    except (OSError, KeyError, ValueError) as e:
        logger.warning(
            "mpmd stage %d: checkpoint epoch_%d unreadable (%s) — "
            "skipping", k, step, e
        )
        return None
    return jax.tree.unflatten(treedef, leaves)


def _restore_latest(workdir: str, k: int, template):
    """Newest intact checkpoint -> (step, state) — or (0, None)."""
    for step in sorted(_stage_ckpt_steps(workdir, k), reverse=True):
        state = _load_stage_ckpt(workdir, k, step, template)
        if state is not None:
            return step, state
    return 0, None


# --------------------------------------------------------------------
# Stage runner (child process)
# --------------------------------------------------------------------


class _Halt(Exception):
    """Supervisor ordered this generation to stop (or a peer died):
    unwind the step loop, ack, and wait for reconfiguration."""


class _Ctrl:
    """The stage side of the supervisor's JSON-lines control link.

    A reader thread turns inbound commands into a queue and raises
    the abort flag on halt/shutdown so p2p recvs blocked mid-step
    unwind promptly. Supervisor EOF means the whole run is dead —
    the stage exits rather than orphan itself.
    """

    def __init__(self, port: int):
        self._sock = socket.create_connection(
            ("127.0.0.1", port), timeout=60
        )
        self._file = self._sock.makefile("rwb")
        self._lock = threading.Lock()
        self.abort = threading.Event()
        self.cmds: "queue.Queue[dict]" = queue.Queue()
        threading.Thread(
            target=self._read_loop, name="mpmd-ctrl", daemon=True
        ).start()

    def _read_loop(self) -> None:
        try:
            for line in self._file:
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if obj.get("cmd") in ("halt", "shutdown"):
                    self.abort.set()
                self.cmds.put(obj)
        except OSError:
            pass
        self.abort.set()
        self.cmds.put({"cmd": "shutdown", "reason": "supervisor gone"})

    def send(self, obj: dict) -> None:
        with self._lock:
            self._file.write(json.dumps(obj).encode() + b"\n")
            self._file.flush()

    def next_cmd(self, timeout: Optional[float] = None) -> dict:
        return self.cmds.get(timeout=timeout)


class StageRunner:
    """One pipeline stage: compiles 1/S of the model, walks its
    column of the 1F1B timetable, speaks p2p to its neighbors and
    JSON-lines to the supervisor."""

    def __init__(
        self,
        cfg: MPMDConfig,
        k: int,
        workdir: str,
        metrics_path: Optional[str],
        ctrl_port: int,
    ):
        self.cfg = cfg
        self.k = k
        self.workdir = workdir
        self.metrics_path = metrics_path
        self.ctrl_port = ctrl_port
        self.up: Optional[p2p.Channel] = None
        self.down: Optional[p2p.Channel] = None
        self._p2p_wait = 0.0
        # Per-step fleet-trace state (PR 19): stage 0 mints one trace
        # context per optimizer step and every ACT send carries it in
        # the wire ``meta``; downstream stages adopt from their first
        # recv of the step, so all S stages' spans share one async
        # track. (tid, own span, parent span) or None (not adopted /
        # tracing off — the None path sends byte-identical frames).
        self._step_trace: Optional[tuple] = None
        self._trace_seed = 0
        self._ext: Dict[str, tuple] = {}  # phase extents for spans

    # ---- plumbing ----------------------------------------------------

    def _mark(self, key: str, t0: float, t1: float) -> None:
        e = self._ext.get(key)
        self._ext[key] = (
            (min(t0, e[0]), max(t1, e[1])) if e else (t0, t1)
        )

    def _trace_meta(self) -> Optional[dict]:
        """The ACT sends' wire ``meta`` — the step's context line when
        this stage holds one, else None (``meta=None`` serializes as
        the empty dict every untraced frame already carries)."""
        st = self._step_trace
        if st is None or not self.tracer.enabled:
            return None
        return {"trace": encode_trace_context(st[0], st[1], st[2])}

    def _recv(self, ch: p2p.Channel, kind: str, step: int, mb: int):
        t0 = time.perf_counter()
        with self.tracer.span(
            "pipeline.p2p_wait",
            {"stage": self.k, "kind": kind, "step": step, "mb": mb},
        ):
            msg = ch.recv(
                kind, step, mb,
                abort=self.ctrl.abort,
                timeout=self.cfg.io_timeout_s,
            )
        t1 = time.perf_counter()
        self._p2p_wait += t1 - t0
        self._mark("p2p_wait", t0, t1)
        if self.tracer.enabled and self._step_trace is None:
            # Adopt the upstream step context: our own span is salted
            # with the stage index, the sender's span becomes parent.
            ctx = parse_trace_context(
                (getattr(msg, "meta", None) or {}).get("trace")
            )
            if ctx is not None:
                self._step_trace = (
                    ctx[0], derive_span_id(ctx[0], self.k), ctx[1]
                )
        return msg

    def _open_links(self, endpoints: Dict[str, int], gen: int) -> None:
        """Accept the upstream dial first, then dial downstream: the
        chain establishes 0→1→…→S-1 without a thundering herd. Every
        connection opens with a hello carrying (generation, stage) so
        stale dials from a dead generation are rejected, not consumed.
        """
        cfg = self.cfg
        if self.k > 0:
            deadline = time.monotonic() + cfg.io_timeout_s
            while True:
                conn = self.listener.accept(
                    abort=self.ctrl.abort,
                    timeout=max(0.1, deadline - time.monotonic()),
                )
                ch = p2p.Channel(conn)
                try:
                    hello = ch.recv(
                        p2p.KIND_HELLO, 0, p2p.NO_MICROBATCH,
                        abort=self.ctrl.abort, timeout=10.0,
                    )
                except (p2p.P2PWireError, p2p.PeerGone):
                    ch.close()
                    continue
                if (
                    hello.meta.get("generation") == gen
                    and hello.meta.get("stage") == self.k - 1
                ):
                    self.up = ch
                    break
                ch.close()  # stale backlog from an old generation
        if self.k < cfg.num_stages - 1:
            port = int(endpoints[str(self.k + 1)])
            self.down = p2p.Channel(
                p2p.dial(
                    "127.0.0.1", port,
                    abort=self.ctrl.abort, timeout=cfg.io_timeout_s,
                )
            )
            self.down.send(
                p2p.KIND_HELLO, 0, p2p.NO_MICROBATCH, {},
                meta={"generation": gen, "stage": self.k},
            )

    def _close_links(self) -> None:
        for ch in (self.up, self.down):
            if ch is not None:
                ch.close()
        self.up = self.down = None

    # ---- one optimizer step ------------------------------------------

    def _run_step(self, step: int) -> None:
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        k = self.k
        S, M, A = cfg.num_stages, cfg.num_microbatches, cfg.grad_accum_steps
        first, last = k == 0, k == S - 1
        sched = self.sched
        tr = self.tracer
        t_start = time.perf_counter()
        self._p2p_wait = 0.0
        self._ext = {}
        if tr.enabled and first:
            tid = derive_trace_id(self._trace_seed, step)
            self._step_trace = (tid, derive_span_id(tid, 0), 0)
        elif not first:
            self._step_trace = None  # adopted from the first recv
        fwd_s = bwd_s = upd_s = 0.0
        acc: dict = {}
        loss_sum = 0.0
        correct = 0.0

        def add(key, tree):
            acc[key] = (
                tree if key not in acc
                else jax.tree.map(jnp.add, acc[key], tree)
            )

        for a in range(A):
            # distinct wire step id per accum chunk: the out-of-order
            # guard stays exact even when (step, mb) repeats
            ws = step * A + a
            tokens = batch_for_step(cfg, step, a)
            mbs = [
                np.ascontiguousarray(tokens[m::M]) for m in range(M)
            ]
            stash: Dict[int, np.ndarray] = {}
            for t in range(sched.n_slots):
                op = int(sched.op[t, k])
                m = int(sched.mb[t, k])
                if op == _FWD:
                    if first:
                        with tr.span(
                            "pipeline.fwd",
                            {"stage": k, "step": step, "mb": m},
                        ):
                            t0 = time.perf_counter()
                            y = np.asarray(
                                self.progs.fwd(
                                    self.part["stage"],
                                    self.part["front"],
                                    mbs[m],
                                )
                            )
                            t1 = time.perf_counter()
                            fwd_s += t1 - t0
                            self._mark("fwd", t0, t1)
                        self.down.send(
                            p2p.KIND_ACT, ws, m, {"x": y},
                            meta=self._trace_meta(),
                        )
                    elif not last:
                        x = self._recv(
                            self.up, p2p.KIND_ACT, ws, m
                        ).arrays["x"]
                        stash[m] = x
                        with tr.span(
                            "pipeline.fwd",
                            {"stage": k, "step": step, "mb": m},
                        ):
                            t0 = time.perf_counter()
                            y = np.asarray(
                                self.progs.fwd(self.part["stage"], x)
                            )
                            t1 = time.perf_counter()
                            fwd_s += t1 - t0
                            self._mark("fwd", t0, t1)
                        self.down.send(
                            p2p.KIND_ACT, ws, m, {"x": y},
                            meta=self._trace_meta(),
                        )
                    else:
                        # last stage's forward slot is recv+stash only
                        # — the bwd vjp recomputes the stage with the
                        # loss attached (in-graph-1F1B parity)
                        stash[m] = self._recv(
                            self.up, p2p.KIND_ACT, ws, m
                        ).arrays["x"]
                elif op == _BWD:
                    if last:
                        x = stash.pop(m)
                        lp = {
                            "ln": self.part["back"]["ln"],
                            "embed": self.part["embed"],
                        }
                        with tr.span(
                            "pipeline.bwd",
                            {"stage": k, "step": step, "mb": m},
                        ):
                            t0 = time.perf_counter()
                            loss, corr, gs, gl, gx = self.progs.bwd(
                                self.part["stage"], lp, x, mbs[m]
                            )
                            gx = np.asarray(gx)
                            t1 = time.perf_counter()
                            bwd_s += t1 - t0
                            self._mark("bwd", t0, t1)
                        self.up.send(p2p.KIND_COT, ws, m, {"g": gx})
                        loss_sum += float(loss)
                        correct += float(corr)
                        add("stage", gs)
                        add("ln", gl["ln"])
                        add("embed", gl["embed"])
                    elif first:
                        g = self._recv(
                            self.down, p2p.KIND_COT, ws, m
                        ).arrays["g"]
                        with tr.span(
                            "pipeline.bwd",
                            {"stage": k, "step": step, "mb": m},
                        ):
                            t0 = time.perf_counter()
                            gs, gf = self.progs.bwd(
                                self.part["stage"],
                                self.part["front"],
                                mbs[m],
                                g,
                            )
                            jax.block_until_ready(gs)
                            t1 = time.perf_counter()
                            bwd_s += t1 - t0
                            self._mark("bwd", t0, t1)
                        add("stage", gs)
                        add("front", gf)
                    else:
                        g = self._recv(
                            self.down, p2p.KIND_COT, ws, m
                        ).arrays["g"]
                        x = stash.pop(m)
                        with tr.span(
                            "pipeline.bwd",
                            {"stage": k, "step": step, "mb": m},
                        ):
                            t0 = time.perf_counter()
                            gs, gx = self.progs.bwd(
                                self.part["stage"], x, g
                            )
                            gx = np.asarray(gx)
                            t1 = time.perf_counter()
                            bwd_s += t1 - t0
                            self._mark("bwd", t0, t1)
                        self.up.send(p2p.KIND_COT, ws, m, {"g": gx})
                        add("stage", gs)
            if stash:
                raise RuntimeError(
                    f"stage {k}: {len(stash)} unconsumed activations"
                )

        # ---- end-of-step sync relay + update -------------------------
        # sync_up (last → 0): step scalars + tied-embed head grad +
        # accumulated grad-norm share. sync_down (0 → last): the
        # UPDATED embedding (the mirror is replaced, not re-derived —
        # no drift) + the total grad norm.
        denom = np.float32(
            A * cfg.batch_size * (cfg.seq_len - 1)
        )
        f32 = lambda v: np.asarray(v, np.float32)  # noqa: E731
        t0 = time.perf_counter()
        if last:
            grads = {"stage": acc["stage"], "back": {"ln": acc["ln"]}}
            trained = _trained(self.part)
            new_p, self.opt_state, sq = self.progs.update(
                trained, self.opt_state, grads, denom
            )
            sq = float(sq)
            upd_s += time.perf_counter() - t0
            self.up.send(
                p2p.KIND_SYNC_UP, step, p2p.NO_MICROBATCH,
                {
                    "loss_sum": f32(loss_sum),
                    "correct": f32(correct),
                    "sq": f32(sq),
                    "embed_grad": np.asarray(acc["embed"]),
                },
            )
            msg = self._recv(
                self.up, p2p.KIND_SYNC_DOWN, step, p2p.NO_MICROBATCH
            )
            self.part = dict(new_p)
            # commit to a device array: a raw wire ndarray has a
            # different jit-cache signature (sharding=None) and would
            # recompile bwd every generation of the mirror
            self.part["embed"] = jnp.asarray(msg.arrays["embed"])
            grad_norm = float(msg.arrays["grad_norm"])
        elif first:
            msg = self._recv(
                self.down, p2p.KIND_SYNC_UP, step, p2p.NO_MICROBATCH
            )
            loss_sum = float(msg.arrays["loss_sum"])
            correct = float(msg.arrays["correct"])
            gf = dict(acc["front"])
            # tied embedding: lookup grad (here) + head grad (last
            # stage) — combined ONCE, on the canonical copy
            gf["embed"] = acc["front"]["embed"] + msg.arrays[
                "embed_grad"
            ]
            grads = {"stage": acc["stage"], "front": gf}
            t0 = time.perf_counter()
            self.part, self.opt_state, sq = self.progs.update(
                self.part, self.opt_state, grads, denom
            )
            sq = float(sq)
            upd_s += time.perf_counter() - t0
            grad_norm = float(
                np.sqrt(sq + float(msg.arrays["sq"]))
            )
            self.down.send(
                p2p.KIND_SYNC_DOWN, step, p2p.NO_MICROBATCH,
                {
                    "embed": np.asarray(self.part["front"]["embed"]),
                    "grad_norm": f32(grad_norm),
                },
            )
        else:
            up_msg = self._recv(
                self.down, p2p.KIND_SYNC_UP, step, p2p.NO_MICROBATCH
            )
            loss_sum = float(up_msg.arrays["loss_sum"])
            correct = float(up_msg.arrays["correct"])
            grads = {"stage": acc["stage"]}
            t0 = time.perf_counter()
            self.part, self.opt_state, sq = self.progs.update(
                self.part, self.opt_state, grads, denom
            )
            sq = float(sq)
            upd_s += time.perf_counter() - t0
            self.up.send(
                p2p.KIND_SYNC_UP, step, p2p.NO_MICROBATCH,
                {
                    "loss_sum": up_msg.arrays["loss_sum"],
                    "correct": up_msg.arrays["correct"],
                    "sq": f32(float(up_msg.arrays["sq"]) + sq),
                    "embed_grad": up_msg.arrays["embed_grad"],
                },
            )
            down_msg = self._recv(
                self.up, p2p.KIND_SYNC_DOWN, step, p2p.NO_MICROBATCH
            )
            self.down.send(
                p2p.KIND_SYNC_DOWN, step, p2p.NO_MICROBATCH,
                dict(down_msg.arrays),
            )
            grad_norm = float(down_msg.arrays["grad_norm"])

        wall = time.perf_counter() - t_start
        loss = loss_sum / float(denom)
        accuracy = correct / float(denom)
        self.last_metrics = {
            "loss": loss, "accuracy": accuracy, "grad_norm": grad_norm
        }
        self.mw.write(
            "step",
            step=step,
            stage=k,
            loss=loss,
            accuracy=accuracy,
            grad_norm=grad_norm,
            wall_s=round(wall, 6),
            fwd_s=round(fwd_s, 6),
            bwd_s=round(bwd_s, 6),
            update_s=round(upd_s, 6),
            p2p_wait_s=round(self._p2p_wait, 6),
            bubble_s=round(max(0.0, wall - fwd_s - bwd_s - upd_s), 6),
        )
        self._emit_step_trace(step, t_start, wall, fwd_s, bwd_s, upd_s)

    def _emit_step_trace(
        self,
        step: int,
        t_start: float,
        wall: float,
        fwd_s: float,
        bwd_s: float,
        upd_s: float,
    ) -> None:
        """The step's async spans on its fleet trace id (cat="step"):
        the MPMD analogue of the serve-side request timeline. One
        umbrella ``mpmd.step`` span per stage plus fwd/bwd/p2p-wait
        phase spans (extent = first..last occurrence of the phase,
        ``busy_s`` = the actual compute inside it) and a bubble
        instant. Non-zero ``parent`` names the upstream stage's span —
        how the merged trace hangs stage k's work off stage k-1's.
        Skipped entirely when this stage never adopted a context
        (tracing off, or a pre-trace upstream peer)."""
        tr = self.tracer
        st = self._step_trace
        if not tr.enabled or st is None:
            return
        aid = format_trace_id(st[0])
        base = {
            "stage": self.k,
            "step": step,
            "span": f"{st[1]:016x}",
            **({"parent": f"{st[2]:016x}"} if st[2] else {}),
        }
        tr.async_complete(
            "mpmd.step", t_start, wall, aid,
            {
                **base,
                "fwd_s": round(fwd_s, 6),
                "bwd_s": round(bwd_s, 6),
                "update_s": round(upd_s, 6),
                "p2p_wait_s": round(self._p2p_wait, 6),
            },
            cat=STEP_CAT,
        )
        for name, key, busy in (
            ("mpmd.fwd", "fwd", fwd_s),
            ("mpmd.bwd", "bwd", bwd_s),
            ("mpmd.p2p_wait", "p2p_wait", self._p2p_wait),
        ):
            ext = self._ext.get(key)
            if ext is not None:
                tr.async_complete(
                    name, ext[0], ext[1] - ext[0], aid,
                    {**base, "busy_s": round(busy, 6)},
                    cat=STEP_CAT,
                )
        tr.async_instant(
            "mpmd.bubble", t_start + wall, aid,
            {
                **base,
                "seconds": round(
                    max(0.0, wall - fwd_s - bwd_s - upd_s), 6
                ),
            },
            cat=STEP_CAT,
        )

    # ---- lifecycle ---------------------------------------------------

    def _state_tree(self):
        return (self.part, self.opt_state)

    def run(self) -> None:
        import jax  # env pinned by _stage_entry before this import

        from ddp_tpu.obs.tracer import get_tracer, install_from_env
        from ddp_tpu.obs.xprof import Xprof
        from ddp_tpu.parallel.one_f1b import BWD, FWD, schedule_1f1b

        global _FWD, _BWD
        _FWD, _BWD = FWD, BWD
        cfg = self.cfg
        k = self.k
        install_from_env(process_id=k)
        self.tracer = get_tracer()
        # Stage 0's per-step trace-id space: urandom-seeded so two
        # runs (or a restarted stage 0) never collide in a merged doc.
        self._trace_seed = int.from_bytes(os.urandom(8), "little")
        self.mw = MetricsWriter(self.metrics_path)
        self.sched = schedule_1f1b(cfg.num_stages, cfg.num_microbatches)
        self.xprof = Xprof(enabled=True)
        self.progs = _StagePrograms(cfg, k, self.xprof)
        self.part = stage_param_slice(cfg, k)
        self.opt_state = self.progs.opt.init(_trained(self.part))
        self.last_metrics = {}
        self.chaos = ChaosEngine(
            stage_events(cfg.chaos),
            stage=k,
            ledger_path=os.path.join(
                self.workdir, f"chaos_stage{k}.json"
            ),
        )

        committed, restored = _restore_latest(
            self.workdir, k, self._state_tree()
        )
        if restored is not None:
            self.part, self.opt_state = restored
            logger.info(
                "mpmd stage %d: restored checkpoint epoch_%d",
                k, committed,
            )
        else:
            committed = 0
            _save_stage_ckpt(
                self.workdir, k, 0, self._state_tree(), cfg.keep_ckpts
            )

        self.listener = p2p.Listener() if k > 0 else None
        self.ctrl = _Ctrl(self.ctrl_port)
        self.ctrl.send(
            {
                "hello": True,
                "stage": k,
                "pid": os.getpid(),
                "p2p_port": self.listener.port if self.listener else 0,
                "committed": committed,
            }
        )

        while True:
            cmd = self.ctrl.next_cmd()
            if cmd.get("cmd") == "shutdown":
                break
            if cmd.get("cmd") == "halt":
                # halt while idle (e.g. between configure rounds)
                self.ctrl.abort.clear()
                self.ctrl.send({"halted": k, "committed": committed})
                continue
            if cmd.get("cmd") != "configure":
                continue
            gen = int(cmd["generation"])
            resume = int(cmd["resume_step"])
            self.ctrl.abort.clear()
            if resume != committed:
                state = _load_stage_ckpt(
                    self.workdir, k, resume, self._state_tree()
                )
                if state is None:
                    raise RuntimeError(
                        f"stage {k}: told to resume at step {resume} "
                        "but no intact checkpoint for it"
                    )
                self.part, self.opt_state = state
                committed = resume
                logger.info(
                    "mpmd stage %d: rolled back to step %d (gen %d)",
                    k, resume, gen,
                )
            try:
                if committed < cfg.steps:
                    self._open_links(cmd["endpoints"], gen)
                    for step in range(committed, cfg.steps):
                        self.chaos.on_step(step)
                        self._run_step(step)
                        committed = step + 1
                        if (
                            committed % cfg.ckpt_every == 0
                            or committed == cfg.steps
                        ):
                            _save_stage_ckpt(
                                self.workdir, k, committed,
                                self._state_tree(), cfg.keep_ckpts,
                            )
                self._close_links()
                self._finish(k, committed)
                break
            except (p2p.Aborted, p2p.PeerGone) as e:
                logger.warning(
                    "mpmd stage %d: generation %d interrupted at "
                    "step %d (%s)", k, gen, committed, e,
                )
                self._close_links()
                # the supervisor owns what happens next: wait for its
                # halt (may already be queued), ack with the durable
                # step, then loop back for the next configure
                try:
                    while True:
                        nxt = self.ctrl.next_cmd(
                            timeout=cfg.io_timeout_s
                        )
                        if nxt.get("cmd") == "halt":
                            self.ctrl.abort.clear()
                            self.ctrl.send(
                                {"halted": k, "committed": committed}
                            )
                            break
                        if nxt.get("cmd") == "shutdown":
                            return
                except queue.Empty:
                    return
        self.mw.close()

    def _finish(self, k: int, committed: int) -> None:
        compile_s = self.xprof.total_compile_s
        programs = self.xprof.program_count
        ledger = {
            "stage": k,
            "compiled_programs": programs,
            "compile_s": compile_s,
            "records": self.xprof.ledger_records(),
        }
        with open(
            os.path.join(self.workdir, f"stage{k}_xprof.json"), "w"
        ) as f:
            json.dump(ledger, f, indent=1)
        self.mw.write(
            "mpmd_xprof",
            stage=k,
            compiled_programs=programs,
            compile_s=round(compile_s, 6),
        )
        final = dict(self.last_metrics)
        final.update(
            stage=k,
            steps=committed,
            compiled_programs=programs,
            compile_s=compile_s,
        )
        if k == 0:
            final["schedule_bubble_fraction"] = float(
                self.sched.bubble_fraction()
            )
        self.mw.flush()
        self.ctrl.send({"done": k, "final": final})


_FWD, _BWD = 1, 2  # rebound from one_f1b inside run() (lazy import)


def _stage_entry(
    cfg_json: str,
    stage: int,
    ctrl_port: int,
    workdir: str,
    metrics_path: Optional[str],
) -> None:
    """multiprocessing-spawn target: pin the JAX env BEFORE first jax
    use — each stage owns exactly one (CPU) device and must never
    touch a compilation cache another process is writing."""
    os.environ["JAX_PLATFORMS"] = (
        os.environ.get("JAX_PLATFORMS") or "cpu"
    )
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["JAX_ENABLE_COMPILATION_CACHE"] = "false"
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    try:
        jax.config.update("jax_enable_compilation_cache", False)
    except Exception:  # older jax: env var alone covers it
        pass
    logging.basicConfig(level=logging.INFO)
    cfg = MPMDConfig.from_json(cfg_json)
    StageRunner(cfg, stage, workdir, metrics_path, ctrl_port).run()


# --------------------------------------------------------------------
# Supervisor (parent process — deliberately JAX-free)
# --------------------------------------------------------------------


class _Writer:
    def __init__(self, f):
        self._f = f
        self._lock = threading.Lock()

    def send(self, obj: dict) -> bool:
        try:
            with self._lock:
                self._f.write(json.dumps(obj).encode() + b"\n")
                self._f.flush()
            return True
        except OSError:
            return False


class _CtrlServer:
    """Supervisor side of the control plane: accepts stage
    connections, funnels every inbound JSON line into one queue."""

    def __init__(self):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(16)
        self._srv.settimeout(0.2)
        self.port = int(self._srv.getsockname()[1])
        self.events: "queue.Queue[tuple[dict, _Writer]]" = queue.Queue()
        self._closed = False
        threading.Thread(
            target=self._accept_loop, name="mpmd-accept", daemon=True
        ).start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            f = sock.makefile("rwb")
            writer = _Writer(f)
            threading.Thread(
                target=self._read_loop,
                args=(f, writer),
                daemon=True,
            ).start()

    def _read_loop(self, f, writer: "_Writer") -> None:
        try:
            for line in f:
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                self.events.put((obj, writer))
        except OSError:
            pass

    def close(self) -> None:
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass


class PipelineSupervisor:
    """Spawns one process per stage, wires the control plane, and
    owns every placement decision: initial configure, classified-exit
    restarts with backoff, and the common-resume-step rollback that
    keeps a restarted stage and its survivors on one timeline."""

    def __init__(
        self,
        cfg: MPMDConfig,
        workdir: str,
        metrics_path: Optional[str] = None,
    ):
        self.cfg = cfg
        self.workdir = workdir
        self.metrics_path = metrics_path
        os.makedirs(workdir, exist_ok=True)

    def _spawn(self, ctx, k: int, ctrl_port: int):
        proc = ctx.Process(
            target=_stage_entry,
            args=(
                self.cfg.to_json(), k, ctrl_port,
                self.workdir, self.metrics_path,
            ),
            name=f"mpmd-stage{k}",
        )
        proc.start()
        return proc

    def run(self, *, timeout_s: float = 600.0) -> dict:
        cfg = self.cfg
        S = cfg.num_stages
        t_begin = time.monotonic()
        t_end = t_begin + timeout_s
        ctx = multiprocessing.get_context("spawn")
        ctrl = _CtrlServer()
        mw = MetricsWriter(self.metrics_path)
        procs: Dict[int, object] = {}
        writers: Dict[int, _Writer] = {}
        info: Dict[int, dict] = {}
        done: Dict[int, dict] = {}
        restarts = 0
        restart_counts: Dict[int, int] = {}
        restart_log: list[dict] = []
        generation = 0

        def remaining() -> float:
            left = t_end - time.monotonic()
            if left <= 0:
                raise TimeoutError(
                    f"mpmd run exceeded {timeout_s:.0f}s"
                )
            return left

        def await_hellos(expect: set[int]) -> None:
            pending = set(expect)
            while pending:
                try:
                    obj, w = ctrl.events.get(
                        timeout=min(1.0, remaining())
                    )
                except queue.Empty:
                    for k in list(pending):
                        code = procs[k].exitcode
                        if code is not None:
                            raise RuntimeError(
                                f"stage {k} died before hello "
                                f"({classify_exit(code)})"
                            )
                    continue
                if obj.get("hello"):
                    k = int(obj["stage"])
                    writers[k] = w
                    info[k] = {
                        "p2p_port": int(obj["p2p_port"]),
                        "committed": int(obj["committed"]),
                    }
                    pending.discard(k)

        def configure(targets, resume: int, gen: int) -> None:
            endpoints = {
                str(k): info[k]["p2p_port"] for k in range(S)
            }
            for k in targets:
                writers[k].send(
                    {
                        "cmd": "configure",
                        "generation": gen,
                        "resume_step": resume,
                        "endpoints": endpoints,
                    }
                )

        def restart_round(dead: list[int]) -> None:
            nonlocal generation, restarts
            reasons = {}
            for k in dead:
                code = procs[k].exitcode
                reasons[k] = classify_exit(code)
                restarts += 1
                restart_counts[k] = restart_counts.get(k, 0) + 1
                if restarts > cfg.max_restarts:
                    raise RuntimeError(
                        f"stage {k} exceeded the restart budget "
                        f"({cfg.max_restarts}): {reasons[k]}"
                    )
            survivors = [
                k for k in range(S) if k not in dead and k not in done
            ]
            for k in survivors:
                if not writers[k].send(
                    {"cmd": "halt", "generation": generation}
                ):
                    dead.append(k)  # died while we were looking away
                    survivors.remove(k)
            acks: Dict[int, int] = {}
            while len(acks) < len(survivors):
                try:
                    obj, _ = ctrl.events.get(
                        timeout=min(1.0, remaining())
                    )
                except queue.Empty:
                    for k in list(survivors):
                        if procs[k].exitcode is not None:
                            survivors.remove(k)
                            dead.append(k)
                            restarts += 1
                            reasons[k] = classify_exit(
                                procs[k].exitcode
                            )
                            if restarts > cfg.max_restarts:
                                raise RuntimeError(
                                    "restart budget exhausted during "
                                    "halt collection"
                                )
                    continue
                if "halted" in obj:
                    acks[int(obj["halted"])] = int(obj["committed"])
            for k in dead:
                procs[k].join(timeout=10)
                delay = cfg.restart_backoff_s * (
                    2 ** (restart_counts.get(k, 1) - 1)
                )
                time.sleep(min(delay, 5.0))
                procs[k] = self._spawn(ctx, k, ctrl.port)
            await_hellos(set(dead))
            candidates = [acks[k] for k in acks] + [
                info[k]["committed"] for k in dead
            ]
            resume = min(candidates)
            if done and resume < cfg.steps:
                raise RuntimeError(
                    "a stage died after peers completed the run — "
                    f"cannot replay step {resume} without them"
                )
            generation += 1
            for k, c in acks.items():
                info[k]["committed"] = c
            configure(sorted(set(dead) | set(acks)), resume, generation)
            for k in dead:
                restart_log.append(
                    {
                        "stage": k,
                        "exit": reasons[k],
                        "resume_step": resume,
                        "generation": generation,
                    }
                )
                mw.write(
                    "mpmd_restart",
                    stage=k,
                    exit_reason=reasons[k],
                    resume_step=resume,
                    generation=generation,
                )

        try:
            for k in range(S):
                procs[k] = self._spawn(ctx, k, ctrl.port)
            await_hellos(set(range(S)))
            resume = min(info[k]["committed"] for k in range(S))
            generation = 1
            mw.write(
                "mpmd_run_start",
                stages=S,
                steps=cfg.steps,
                resume_step=resume,
                microbatches=cfg.num_microbatches,
                grad_accum_steps=cfg.grad_accum_steps,
            )
            configure(range(S), resume, generation)
            while len(done) < S:
                try:
                    obj, _ = ctrl.events.get(
                        timeout=min(0.25, remaining())
                    )
                except queue.Empty:
                    obj = None
                if obj and "done" in obj:
                    done[int(obj["done"])] = dict(obj.get("final", {}))
                dead = [
                    k for k, p in procs.items()
                    if p.exitcode is not None and k not in done
                ]
                if dead:
                    restart_round(dead)
            wall = time.monotonic() - t_begin
            final0 = done.get(0, {})
            mw.write(
                "mpmd_run",
                stages=S,
                steps=cfg.steps,
                restarts=restarts,
                wall_s=round(wall, 3),
                loss=final0.get("loss"),
                schedule_bubble_fraction=final0.get(
                    "schedule_bubble_fraction"
                ),
            )
            for k in range(S):
                if writers.get(k):
                    writers[k].send({"cmd": "shutdown"})
            return {
                "stages": S,
                "steps": cfg.steps,
                "restarts": restarts,
                "restart_log": restart_log,
                "wall_s": wall,
                "final": {str(k): done[k] for k in sorted(done)},
                "loss": final0.get("loss"),
                "accuracy": final0.get("accuracy"),
                "grad_norm": final0.get("grad_norm"),
                "schedule_bubble_fraction": final0.get(
                    "schedule_bubble_fraction"
                ),
                "workdir": self.workdir,
            }
        finally:
            for proc in procs.values():
                if proc.is_alive():
                    proc.terminate()
            for proc in procs.values():
                proc.join(timeout=10)
                if proc.is_alive():
                    proc.kill()
                    proc.join(timeout=5)
            ctrl.close()
            mw.close()


def train_mpmd(
    cfg: MPMDConfig,
    workdir: str,
    metrics_path: Optional[str] = None,
    *,
    timeout_s: float = 600.0,
) -> dict:
    """Run one MPMD pipeline training job to completion (restarts
    included) and return the supervisor's summary."""
    return PipelineSupervisor(cfg, workdir, metrics_path).run(
        timeout_s=timeout_s
    )


# --------------------------------------------------------------------
# SPMD control: the single-program in-graph 1F1B baseline the MPMD
# runtime is parity-pinned against (same seeds, same batches).
# --------------------------------------------------------------------


def run_spmd_control(cfg: MPMDConfig) -> dict:
    """In-graph 1F1B on ``num_stages`` devices of THIS process —
    loss/accuracy/grad-norm trajectory + the single-program compile
    cost the per-stage ledgers are compared against."""
    if cfg.grad_accum_steps != 1:
        raise ValueError("the SPMD control runs accum=1 only")
    import jax
    import jax.numpy as jnp

    from ddp_tpu.models.pipeline_lm import (
        create_pipe_lm_state,
        make_pipe_lm_1f1b_train_step,
    )
    from ddp_tpu.obs.xprof import Xprof
    from ddp_tpu.runtime.mesh import MeshSpec, make_mesh

    S = cfg.num_stages
    devices = jax.devices()
    if len(devices) < S:
        raise RuntimeError(
            f"SPMD control needs {S} devices, have {len(devices)} "
            "(set --xla_force_host_platform_device_count)"
        )
    mesh = make_mesh(
        MeshSpec(data=1, pipe=S), devices=devices[:S]
    )
    pcfg = _pipe_cfg(cfg)
    opt = _make_optimizer(cfg.optimizer, cfg.lr)
    state = create_pipe_lm_state(pcfg, opt, mesh, seed=cfg.seed)
    step_fn = make_pipe_lm_1f1b_train_step(
        pcfg, opt, mesh, donate=False
    )
    xprof = Xprof(enabled=True)
    fn = xprof.instrument(step_fn, "spmd_1f1b")
    losses, accs, gns, times = [], [], [], []
    for step in range(cfg.steps):
        tokens = jnp.asarray(batch_for_step(cfg, step, 0))
        t0 = time.perf_counter()
        state, metrics = fn(state, tokens)
        losses.append(float(metrics.loss))
        times.append(time.perf_counter() - t0)
        accs.append(float(metrics.accuracy))
        gns.append(float(metrics.grad_norm))
    return {
        "losses": losses,
        "accuracies": accs,
        "grad_norms": gns,
        "step_s": times,
        "compiled_programs": xprof.program_count,
        "compile_s": xprof.total_compile_s,
    }


# --------------------------------------------------------------------
# CLI: supervisor mode by default; --control runs the SPMD baseline
# in-process (bench.py drives both as subprocesses).
# --------------------------------------------------------------------


def _add_cfg_args(ap: argparse.ArgumentParser) -> None:
    d = MPMDConfig()
    ap.add_argument("--vocab_size", type=int, default=d.vocab_size)
    ap.add_argument("--seq_len", type=int, default=d.seq_len)
    ap.add_argument("--d_model", type=int, default=d.d_model)
    ap.add_argument("--num_heads", type=int, default=d.num_heads)
    ap.add_argument("--mlp_ratio", type=int, default=d.mlp_ratio)
    ap.add_argument("--stages", type=int, default=d.num_stages)
    ap.add_argument(
        "--depth_per_stage", type=int, default=d.depth_per_stage
    )
    ap.add_argument(
        "--microbatches", type=int, default=d.num_microbatches
    )
    ap.add_argument("--batch_size", type=int, default=d.batch_size)
    ap.add_argument("--steps", type=int, default=d.steps)
    ap.add_argument("--seed", type=int, default=d.seed)
    ap.add_argument("--optimizer", default=d.optimizer)
    ap.add_argument("--lr", type=float, default=d.lr)
    ap.add_argument(
        "--grad_accum_steps", type=int, default=d.grad_accum_steps
    )
    ap.add_argument("--ckpt_every", type=int, default=d.ckpt_every)
    ap.add_argument(
        "--max_restarts", type=int, default=d.max_restarts
    )
    ap.add_argument("--chaos", default="")


def _cfg_from_args(args) -> MPMDConfig:
    return MPMDConfig(
        vocab_size=args.vocab_size,
        seq_len=args.seq_len,
        d_model=args.d_model,
        num_heads=args.num_heads,
        mlp_ratio=args.mlp_ratio,
        num_stages=args.stages,
        depth_per_stage=args.depth_per_stage,
        num_microbatches=args.microbatches,
        batch_size=args.batch_size,
        steps=args.steps,
        seed=args.seed,
        optimizer=args.optimizer,
        lr=args.lr,
        grad_accum_steps=args.grad_accum_steps,
        ckpt_every=args.ckpt_every,
        max_restarts=args.max_restarts,
        chaos=args.chaos,
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ddp_tpu.parallel.mpmd",
        description="MPMD pipeline runtime (one process per stage)",
    )
    _add_cfg_args(ap)
    ap.add_argument(
        "--workdir", default="/tmp/ddp_tpu_mpmd",
        help="checkpoints + ledgers + chaos state",
    )
    ap.add_argument("--metrics_file", default=None)
    ap.add_argument(
        "--timeout_s", type=float, default=600.0,
        help="supervisor wall-clock budget",
    )
    ap.add_argument(
        "--control", action="store_true",
        help="run the in-graph SPMD 1F1B baseline instead (needs "
        "--stages emulated devices in THIS process)",
    )
    ap.add_argument(
        "--json", default=None, metavar="PATH",
        help="also write the result object to PATH",
    )
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    cfg = _cfg_from_args(args)
    if args.control:
        result = run_spmd_control(cfg)
    else:
        result = train_mpmd(
            cfg,
            args.workdir,
            args.metrics_file,
            timeout_s=args.timeout_s,
        )
    out = json.dumps(result)
    print(out)
    if args.json:
        with open(args.json, "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

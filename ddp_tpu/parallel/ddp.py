"""Data-parallel train step: the reference's DDP capability, compiled.

What torch smears across four runtime systems — the DDP wrapper's
constructor broadcast (train_ddp.py:34), the C++ reducer's bucketed
all-reduce firing inside ``loss.backward()`` (train_ddp.py:199,
SURVEY.md §2b N4), the autograd engine, and the optimizer step
(train_ddp.py:200) — is here ONE jitted SPMD function:

    forward → xent loss → grad → ``lax.pmean(grads, data_axes)`` → SGD

expressed with ``jax.shard_map`` over the mesh so the gradient
all-reduce is an explicit, visible collective that XLA lowers onto ICI
and overlaps with backward compute (the reducer's job, done by the
compiler). Params live replicated on device across steps; the batch
arrives sharded on the ``data``/``fsdp`` axes.

Division semantics match DDP: gradients are *averaged* over the world
(pmean = psum ÷ world_size), so loss scale is independent of device
count.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddp_tpu.obs.health import health_stats, inject_nan
from ddp_tpu.parallel.common import (
    _preprocess,
    _train_kwarg,
    check_accum_divisible,
    grad_accum_scan,
    make_loss_fn,
)
from ddp_tpu.runtime.mesh import data_axes


class TrainState(NamedTuple):
    """Replicated training state (params + optimizer + step counter).

    The analogue of the reference's (model.state_dict(), opt.state_dict())
    pair that its checkpoints carry (train_ddp.py:205-209).
    ``model_state`` holds non-gradient variable collections (e.g.
    BatchNorm ``batch_stats`` for the ResNet family) — torch keeps these
    inside ``state_dict()`` as buffers; here they are an explicit tree,
    empty ``{}`` for buffer-free models like SimpleCNN.
    """

    step: jax.Array  # int32 scalar
    params: Any  # pytree
    opt_state: Any  # optax state pytree
    model_state: Any = {}  # non-gradient collections, e.g. batch_stats


class StepMetrics(NamedTuple):
    loss: jax.Array
    accuracy: jax.Array
    # Global L2 norm of the (already all-reduced) gradient — the
    # standard divergence/clipping dashboard signal. ``None`` (the
    # default, kept by step builders that don't compute it) makes the
    # metrics stream omit the field — a missing norm must not read as
    # a vanished (0.0) gradient.
    grad_norm: jax.Array | float | None = None
    # Per-layer-group health vectors (obs/health.HealthStats) when the
    # step was built with ``health=True``; None (an empty pytree — the
    # disabled graph is byte-identical) otherwise.
    health: Any = None


def create_train_state(
    model, optimizer: optax.GradientTransformation, sample_input, *, seed: int = 0
) -> TrainState:
    """Initialize params identically on every process.

    The same PRNG key everywhere replaces DDP's rank-0 parameter
    broadcast at wrap time (train_ddp.py:34): replicas are identical by
    construction, no collective needed.
    """
    variables = model.init(
        jax.random.key(seed), sample_input, **_train_kwarg(model, False)
    )
    params = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=optimizer.init(params),
        model_state=model_state,
    )


def make_per_shard_step(
    model,
    optimizer: optax.GradientTransformation,
    axes: tuple[str, ...],
    world: int,
    *,
    compute_dtype=jnp.float32,
    seed: int = 0,
    aux_loss_weight: float = 0.01,
    grad_accum_steps: int = 1,
    augment_fn=None,
    label_smoothing: float = 0.0,
    health: bool = False,
    health_inject: tuple[str, int] | None = None,
) -> Callable[[TrainState, jax.Array, jax.Array], tuple[TrainState, StepMetrics]]:
    """The per-device SPMD step body (runs inside shard_map).

    Exposed separately so the compiled-epoch runner (train.fast) can
    ``lax.scan`` it without re-stating the DDP semantics.

    ``grad_accum_steps=k`` splits the incoming batch into k equal
    microbatches, accumulates their mean gradients with ``lax.scan``,
    and applies ONE optimizer update and ONE all-reduce — how large
    effective batches fit in HBM. The reference has no accumulation
    (SURVEY.md §2c: one step per batch, train_ddp.py:196-200).

    ``health=True`` fuses the per-layer-group stats pass
    (obs/health.py) over grads/params/updates into the step; the
    vectors land on ``StepMetrics.health``. ``health_inject`` is the
    NaN fault-injection hook (``(layer_group, step)``). Both default
    off, and off traces the IDENTICAL graph (Python-level branch).
    """

    loss_fn = make_loss_fn(
        model, compute_dtype, aux_loss_weight, augment_fn=augment_fn,
        label_smoothing=label_smoothing,
    )

    def per_shard_step(state: TrainState, images, labels):
        mutable = list(state.model_state.keys())
        # Per-device, per-step dropout key: fold in the linear shard
        # index so masks decorrelate across replicas (each sees
        # different data). Unused rngs are ignored by Flax.
        rng = jax.random.fold_in(jax.random.key(seed), state.step)
        for a in axes:
            rng = jax.random.fold_in(rng, lax.axis_index(a))

        if grad_accum_steps == 1:
            (loss, (logits, new_ms)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params, state.model_state, images, labels, rng, mutable)
            correct = (jnp.argmax(logits.astype(jnp.float32), -1) == labels).sum()
            n_labels = labels.shape[0]
        else:
            mb = check_accum_divisible(images.shape[0], grad_accum_steps)
            # Contiguous per-shard microbatches: data is already local
            # to this device inside shard_map, so no comm is implied.
            imgs = images.reshape(grad_accum_steps, mb, *images.shape[1:])
            lbls = labels.reshape(grad_accum_steps, mb)
            grads, new_ms, loss, correct = grad_accum_scan(
                loss_fn, state.params, state.model_state, imgs, lbls, rng, mutable
            )
            n_labels = images.shape[0]
        # THE all-reduce: the entire job of DDP's C++ reducer
        # (SURVEY.md §2b N4) is this one line. pmean = psum / world.
        grads = lax.pmean(grads, axes)
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        if health_inject is not None:
            grads = inject_nan(grads, state.step, health_inject)
        # SyncBN-style: average non-gradient stats (BatchNorm running
        # mean/var) across replicas so they stay identical. The torch
        # reference keeps per-rank stats and checkpoints rank 0's;
        # averaging is the strictly-more-correct contract.
        new_ms = jax.tree.map(
            lambda v: lax.pmean(v.astype(jnp.float32), axes), new_ms
        )
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = StepMetrics(
            loss=lax.pmean(loss, axes),
            accuracy=lax.psum(correct, axes) / (n_labels * world),
            grad_norm=optax.global_norm(grads),
            # Post-pmean grads (and therefore updates) are replicated,
            # so the [G] vectors come out identical on every shard.
            health=health_stats(grads, state.params, updates)
            if health
            else None,
        )
        return TrainState(state.step + 1, params, opt_state, new_ms), metrics

    return per_shard_step


def make_train_step(
    model,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    compute_dtype=jnp.float32,
    donate: bool = True,
    seed: int = 0,
    aux_loss_weight: float = 0.01,
    grad_accum_steps: int = 1,
    augment_fn=None,
    label_smoothing: float = 0.0,
    health: bool = False,
    health_inject: tuple[str, int] | None = None,
) -> Callable[[TrainState, jax.Array, jax.Array], tuple[TrainState, StepMetrics]]:
    """Build the compiled DDP train step for ``mesh``.

    Returns ``step(state, images, labels) -> (state, metrics)`` where
    ``images``/``labels`` are sharded over the data axes and ``state``
    is replicated. ``compute_dtype=jnp.bfloat16`` gives mixed precision:
    bf16 activations/grads on the MXU, fp32 master params and update.
    ``seed`` keys the per-step dropout stream (independent of the data
    order and init seeds only by convention — pass the run seed).
    """
    axes = data_axes(mesh)
    batch_spec = P(axes)
    per_shard_step = make_per_shard_step(
        model, optimizer, axes, _world(mesh, axes),
        compute_dtype=compute_dtype, seed=seed,
        aux_loss_weight=aux_loss_weight,
        grad_accum_steps=grad_accum_steps,
        augment_fn=augment_fn,
        label_smoothing=label_smoothing,
        health=health,
        health_inject=health_inject,
    )
    sharded = jax.shard_map(
        per_shard_step,
        mesh=mesh,
        in_specs=(P(), batch_spec, batch_spec),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_eval_step(
    model, mesh: Mesh, *, compute_dtype=jnp.float32
) -> Callable[..., tuple[jax.Array, jax.Array]]:
    """Compiled eval step → (weighted correct count, weighted loss sum).

    ``weights`` (0/1 per example) mask the wraparound padding that fills
    the final partial batch, so totals are exact over any split size.
    The reference has no eval loop at all (SURVEY.md §5 metrics); this
    closes that gap so the 99%-accuracy north star is measurable.
    """
    axes = data_axes(mesh)
    batch_spec = P(axes)
    train_kw = _train_kwarg(model, False)

    def per_shard(params, model_state, images, labels, weights):
        x = _preprocess(images, compute_dtype)
        if compute_dtype != jnp.float32:
            params = jax.tree.map(lambda p: p.astype(compute_dtype), params)
        variables = {"params": params, **model_state}
        logits = model.apply(variables, x, **train_kw).astype(jnp.float32)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        correct = ((jnp.argmax(logits, -1) == labels) * weights).sum()
        return lax.psum(correct, axes), lax.psum((loss * weights).sum(), axes)

    sharded = jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(), P(), batch_spec, batch_spec, batch_spec),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded)


def replicate_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Place state on the mesh, replicated — explicit device residency."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sharding), state)


def _world(mesh: Mesh, axes) -> int:
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    return n

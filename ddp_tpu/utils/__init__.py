"""Cross-cutting utilities: logging, timing, profiling."""

from ddp_tpu.utils.logging import setup_logging  # noqa: F401

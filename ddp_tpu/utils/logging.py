"""Process-aware logging — the reference's rank-gated prints, structured.

train_ddp.py logs through bare ``print`` guarded by ``if rank == 0``
(train_ddp.py:201-202 and lifecycle prints). Here every process gets a
logger tagged with its process id; by default only process 0 logs at
INFO (others at WARNING), preserving the observable single-stream
output while keeping per-process debugging one env var away
(DDP_TPU_LOG_ALL=1).
"""

from __future__ import annotations

import logging
import os
import sys


def setup_logging(process_id: int = 0, *, level: int = logging.INFO) -> logging.Logger:
    logger = logging.getLogger("ddp_tpu")
    if not logger.handlers:
        h = logging.StreamHandler(sys.stdout)
        h.setFormatter(
            logging.Formatter(
                f"[p{process_id}] %(asctime)s %(levelname)s %(message)s",
                datefmt="%H:%M:%S",
            )
        )
        logger.addHandler(h)
    if process_id == 0 or os.environ.get("DDP_TPU_LOG_ALL"):
        logger.setLevel(level)
    else:
        logger.setLevel(logging.WARNING)
    logger.propagate = False
    return logger

"""Structured run metrics — the observability the reference lacks.

The reference's only observability is rank-gated prints
(train_ddp.py:201-202 and lifecycle lines; SURVEY.md §5 calls the
subsystem "print-only"). This writer emits machine-readable JSONL from
process 0: one record per logged step and per epoch, each stamped with
wall time, so throughput and loss curves can be plotted or asserted on
without scraping logs. Pair with ``--profile_dir`` (jax.profiler,
Perfetto/TensorBoard traces) for kernel-level views.
"""

from __future__ import annotations

import atexit
import json
import math
import os
import random
import time
from typing import Any, IO


class StatSummary:
    """Streaming scalar summary: count / mean / min / max / percentiles.

    The serving engine (ddp_tpu.serve) feeds per-request latencies
    (TTFT, decode tokens/s) through these; ``snapshot()`` is what the
    server's /stats endpoint and bench.py's serve record publish.
    Memory is bounded — a long-lived server must not grow a float per
    request forever: count/mean/min/max are exact running values, and
    percentiles come from a fixed-size uniform reservoir
    (Vitter's algorithm R; exact until ``max_samples`` requests).
    The server snapshots under the lock that gates the decode loop,
    so ``snapshot()`` sorts the bounded reservoir once, not an
    unbounded list per percentile.
    """

    def __init__(self, *, max_samples: int = 4096, seed: int = 0) -> None:
        self._samples: list[float] = []
        self._max = max_samples
        self._rng = random.Random(seed)
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max_v = -math.inf

    def add(self, value: float) -> None:
        v = float(value)
        if not math.isfinite(v):
            return
        self._count += 1
        self._sum += v
        self._min = min(self._min, v)
        self._max_v = max(self._max_v, v)
        if len(self._samples) < self._max:
            self._samples.append(v)
        else:
            j = self._rng.randrange(self._count)
            if j < self._max:
                self._samples[j] = v

    @property
    def count(self) -> int:
        return self._count

    def percentile(self, q: float) -> float | None:
        """Nearest-rank percentile, q in [0, 100]; None when empty."""
        if not self._samples:
            return None
        s = sorted(self._samples)
        return self._percentile_sorted(s, q)

    @staticmethod
    def _percentile_sorted(s: list, q: float) -> float:
        rank = max(0, min(len(s) - 1, round(q / 100.0 * (len(s) - 1))))
        return s[rank]

    def snapshot(self, *, ndigits: int = 4) -> dict:
        """One JSON-ready dict: {count, mean, sum, min, p50, p95, max}.

        ``sum`` is the EXACT running total (rounded for display, which
        preserves monotonicity) — the Prometheus summary exposition's
        ``_sum`` counter must come from it, not from ``mean × count``:
        a counter reconstructed from the rounded mean can DECREASE
        between scrapes, which scrapers read as a reset.
        """
        if not self._count:
            return {"count": 0}
        s = sorted(self._samples)
        r = lambda v: round(v, ndigits)  # noqa: E731
        return {
            "count": self._count,
            "mean": r(self._sum / self._count),
            "sum": r(self._sum),
            "min": r(self._min),
            "p50": r(self._percentile_sorted(s, 50)),
            "p95": r(self._percentile_sorted(s, 95)),
            "max": r(self._max_v),
        }

    # ---- cross-process merging (scripts/trace_merge.py) -------------

    def to_state(self) -> dict:
        """JSON-ready full state: exact scalars + the reservoir.

        What a per-rank trace file embeds so summaries can be merged
        offline; ``snapshot()`` stays the lossy human-facing view.
        """
        return {
            "count": self._count,
            "sum": self._sum,
            "min": None if self._count == 0 else self._min,
            "max": None if self._count == 0 else self._max_v,
            "max_samples": self._max,
            "samples": list(self._samples),
        }

    @classmethod
    def from_state(cls, state: dict, *, seed: int = 0) -> "StatSummary":
        s = cls(max_samples=int(state.get("max_samples", 4096)), seed=seed)
        s._count = int(state["count"])
        s._sum = float(state["sum"])
        if s._count:
            s._min = float(state["min"])
            s._max_v = float(state["max"])
        s._samples = [float(v) for v in state.get("samples", [])][: s._max]
        return s

    def merge(self, other: "StatSummary") -> "StatSummary":
        """Fold ``other`` into self (per-rank → global summaries).

        count/sum(→mean)/min/max combine EXACTLY (pinned by a property
        test). The percentile reservoir merges by weighted subsampling:
        each side's samples are kept with probability proportional to
        the count it represents, so the merged reservoir stays an
        (approximately) uniform draw from the union stream.
        """
        if other._count == 0:
            return self
        self._sum += other._sum
        self._min = min(self._min, other._min)
        self._max_v = max(self._max_v, other._max_v)
        merged_count = self._count + other._count
        pool = self._samples + other._samples
        if len(pool) > self._max:
            # Weight by represented counts: index < len(self._samples)
            # stands for self's stream, the rest for other's.
            weights = [
                (self._count / max(1, len(self._samples)))
                if i < len(self._samples)
                else (other._count / max(1, len(other._samples)))
                for i in range(len(pool))
            ]
            total = sum(weights)
            picks = []
            # Weighted sampling without replacement (Efraimidis-
            # Spirakis keys): fine at reservoir scale (≤ 2·max_samples).
            keyed = sorted(
                (
                    (self._rng.random() ** (total / (w * len(pool))), v)
                    for w, v in zip(weights, pool)
                ),
                reverse=True,
            )
            picks = [v for _, v in keyed[: self._max]]
            self._samples = picks
        else:
            self._samples = pool
        self._count = merged_count
        return self


class MetricsWriter:
    """Append-only JSONL metrics stream; no-op when disabled.

    Flushes on ``atexit`` as a backstop: line buffering covers the
    normal case, but a short-lived process (scripts/serve.py smoke
    runs, aborted CLIs) must not lose the tail of the stream because
    nobody reached ``close()``. Explicit ``close()`` unregisters the
    hook so writers don't accumulate across many constructions.
    """

    def __init__(self, path: str | None, *, enabled: bool = True):
        self._f: IO[str] | None = None
        if path and enabled:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "a", buffering=1)  # line-buffered
            atexit.register(self.close)

    def write(self, kind: str, **fields: Any) -> None:
        if self._f is None:
            return
        rec = {"kind": kind, "time": round(time.time(), 3), **fields}
        # Strict JSON: NaN/Infinity (e.g. diverged loss, empty-epoch
        # mean) serialize as null, not the bare `NaN` jq/JSON.parse
        # reject — divergence is precisely when the stream gets read.
        rec = {
            k: (
                None
                if isinstance(v, float) and not math.isfinite(v)
                else v
            )
            for k, v in rec.items()
        }
        self._f.write(json.dumps(rec, allow_nan=False) + "\n")

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
            atexit.unregister(self.close)

"""Structured run metrics — the observability the reference lacks.

The reference's only observability is rank-gated prints
(train_ddp.py:201-202 and lifecycle lines; SURVEY.md §5 calls the
subsystem "print-only"). This writer emits machine-readable JSONL from
process 0: one record per logged step and per epoch, each stamped with
wall time, so throughput and loss curves can be plotted or asserted on
without scraping logs. Pair with ``--profile_dir`` (jax.profiler,
Perfetto/TensorBoard traces) for kernel-level views.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, IO


class MetricsWriter:
    """Append-only JSONL metrics stream; no-op when disabled."""

    def __init__(self, path: str | None, *, enabled: bool = True):
        self._f: IO[str] | None = None
        if path and enabled:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "a", buffering=1)  # line-buffered

    def write(self, kind: str, **fields: Any) -> None:
        if self._f is None:
            return
        rec = {"kind": kind, "time": round(time.time(), 3), **fields}
        # Strict JSON: NaN/Infinity (e.g. diverged loss, empty-epoch
        # mean) serialize as null, not the bare `NaN` jq/JSON.parse
        # reject — divergence is precisely when the stream gets read.
        rec = {
            k: (
                None
                if isinstance(v, float) and not math.isfinite(v)
                else v
            )
            for k, v in rec.items()
        }
        self._f.write(json.dumps(rec, allow_nan=False) + "\n")

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

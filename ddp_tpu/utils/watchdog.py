"""Hang detection: turn a stuck collective into a crash.

The reference has no failure detection at all (SURVEY.md §5): a dead
rank leaves every peer blocked in the next all-reduce forever, and
nothing restarts the job. The framework's recovery model is
fail-fast + auto-resume (launcher kills stragglers and reports the
failed rank, runtime/launch.py; checkpoints restore on re-run,
train/checkpoint.py) — which only works if a hang *becomes* a failure.
This watchdog supplies that conversion: a monitor thread observes
per-step progress beats and, when none arrives within the timeout,
runs the abort action (default: log loudly and ``os._exit``) so the
launcher/orchestrator sees a dead process instead of a silent stall.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from typing import Callable

logger = logging.getLogger("ddp_tpu")


def dump_all_stacks(file=None) -> None:
    """Write every thread's Python stack to ``file`` (default stderr).

    ``faulthandler`` works from any thread without touching the (hung)
    main thread's interpreter loop, which is exactly the situation the
    watchdog fires in. Never raises: the dump is best-effort evidence,
    the abort must proceed regardless.
    """
    import faulthandler

    try:
        faulthandler.dump_traceback(
            file=file if file is not None else sys.stderr,
            all_threads=True,
        )
    except Exception:  # noqa: BLE001 — diagnostics must not block abort
        pass


# Forensics callbacks run before the abort: the trainer registers the
# flight-recorder dump and live-trace export here so a HANG leaves the
# same post-mortem artifacts as a crash (os._exit skips atexit and
# every finally — this is their only chance to run). Each callback is
# exception-isolated: evidence collection must never block the abort.
_FORENSICS: list[Callable[[], None]] = []


def register_forensics(fn: Callable[[], None]) -> Callable[[], None]:
    """Add a pre-abort evidence collector; returns ``fn`` (so callers
    can keep the handle for :func:`unregister_forensics`)."""
    _FORENSICS.append(fn)
    return fn


def unregister_forensics(fn: Callable[[], None]) -> None:
    """Remove a collector; missing entries are ignored (a finished
    Trainer must be able to clean up unconditionally)."""
    try:
        _FORENSICS.remove(fn)
    except ValueError:
        pass


def run_forensics() -> None:
    """Run every registered collector, isolating failures."""
    for fn in list(_FORENSICS):
        try:
            fn()
        except Exception:  # noqa: BLE001 — diagnostics must not block abort
            pass


def _default_abort(seconds: float) -> None:
    logger.error(
        "watchdog: no training progress for %.0fs — aborting so the "
        "launcher can detect the hang and a restart can resume from "
        "the latest checkpoint",
        seconds,
    )
    # The one chance to say WHERE it hung: os._exit skips atexit and
    # every finally, so forensics (flight recorder, trace export) and
    # the stack dump must happen first — the logs and these files are
    # all a post-mortem of a reclaimed VM gets to keep.
    run_forensics()
    dump_all_stacks()
    # sys.exit only raises in this thread; a hung main thread never
    # sees it. _exit is the point: make the process observably dead.
    os._exit(124)


class StepWatchdog:
    """Monitor thread that aborts when ``beat()`` stops arriving.

    Usage::

        wd = StepWatchdog(timeout=300.0)
        wd.start()
        for batch in loader:
            step(...)
            wd.beat()
        wd.stop()

    ``timeout <= 0`` disables everything (all methods are no-ops), so
    callers can wire it unconditionally from config.
    """

    def __init__(
        self,
        timeout: float,
        *,
        on_timeout: Callable[[float], None] = _default_abort,
        poll_interval: float | None = None,
    ):
        self.timeout = timeout
        self._on_timeout = on_timeout
        self._poll = poll_interval or max(0.1, min(timeout / 4, 10.0)) if timeout > 0 else 1.0
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self.timeout <= 0 or self._thread is not None:
            return
        self._last = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="ddp-tpu-watchdog", daemon=True
        )
        self._thread.start()

    def beat(self) -> None:
        self._last = time.monotonic()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None

    def __enter__(self) -> "StepWatchdog":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            idle = time.monotonic() - self._last
            if idle > self.timeout:
                self._on_timeout(idle)
                return

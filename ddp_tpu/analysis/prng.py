"""DDP005 — PRNG key reuse without split/fold_in.

The silent-correctness class: JAX keys are pure values, so passing
the same key to two samplers gives two CORRELATED (often identical)
draws — no error, no warning, just dropout masks that equal the init
noise, or every serve lane sampling the same token stream. The serve
sampler threads per-slot ``fold_in`` counters for exactly this
reason.

Model (per function scope, names only):

- a key is born from ``PRNGKey``/``random.key``/``split``/``fold_in``
  (or a parameter named like one: ``key``, ``rng``, ``*_key``,
  ``*_rng``);
- passing a key to any call CONSUMES it — including ``split`` (using
  the parent after splitting it is the classic bug);
- ``fold_in(key, i)`` does NOT consume: deriving per-step keys from a
  base with distinct data is the sanctioned streaming pattern;
- a second consumption without an intervening rebind is a finding.

``if``/``else`` branches merge (one consumption on each side is one
consumption); loop bodies are interpreted twice so a key consumed
once per iteration without a per-iteration ``split``/``fold_in``
rebind is caught on the second pass.
"""

from __future__ import annotations

import ast
import re

from ddp_tpu.analysis.core import Finding, ModuleInfo

_DERIVE_TAILS = (
    "random.PRNGKey",
    "random.key",
    "random.split",
    "random.fold_in",
    "random.clone",
    "random.wrap_key_data",
)
_NONCONSUMING_TAILS = (
    "random.PRNGKey",
    "random.key",
    "random.fold_in",
    "random.clone",
    "random.wrap_key_data",
)
_KEY_PARAM_RE = re.compile(r"(^|_)(key|rng)$")


def _tail_match(resolved: str | None, tails) -> bool:
    if not resolved:
        return False
    return any(
        resolved == t or resolved.endswith("." + t) or resolved == t.split(".")[-1]
        for t in tails
    )


def _is_derivation(mod: ModuleInfo, call: ast.Call) -> bool:
    return _tail_match(mod.resolve(call.func), _DERIVE_TAILS)


def _is_nonconsuming(mod: ModuleInfo, call: ast.Call) -> bool:
    return _tail_match(mod.resolve(call.func), _NONCONSUMING_TAILS)


def _stmt_targets(stmt: ast.stmt) -> list[str]:
    targets: list[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    out: list[str] = []
    for t in targets:
        for node in ast.walk(t):
            if isinstance(node, ast.Name):
                out.append(node.id)
    return out


def _calls_pruned(root: ast.AST) -> list[ast.Call]:
    """Calls in source order, without descending into nested scopes."""
    out: list[ast.Call] = []

    def visit(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(child, ast.Call):
                out.append(child)
            visit(child)

    visit(root)
    out.sort(key=lambda c: (c.lineno, c.col_offset))
    return out


def _key_args(call: ast.Call) -> list[ast.Name]:
    args = list(call.args) + [
        kw.value for kw in call.keywords if kw.value is not None
    ]
    return [a for a in args if isinstance(a, ast.Name)]


class _Scope:
    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.findings: list[Finding] = []
        self._seen: set[tuple[int, int]] = set()

    def _flag(self, name_node: ast.Name) -> None:
        key = (name_node.lineno, name_node.col_offset)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(
            Finding(
                rule="DDP005",
                path=self.mod.path,
                line=name_node.lineno,
                col=name_node.col_offset,
                message=(
                    f"PRNG key `{name_node.id}` consumed again without "
                    "split/fold_in — both consumers draw CORRELATED "
                    "randomness"
                ),
                hint=(
                    "split the key (`k1, k2 = jax.random.split(key)`) "
                    "or fold in a distinct counter per consumer"
                ),
            )
        )

    def _consume_stmt(self, stmt: ast.stmt, state: dict[str, int]) -> None:
        for call in _calls_pruned(stmt):
            if _is_nonconsuming(self.mod, call):
                continue
            for name in _key_args(call):
                if name.id in state:
                    if state[name.id] >= 1:
                        self._flag(name)
                    state[name.id] += 1

    def _apply_stores(self, stmt: ast.stmt, state: dict[str, int]) -> None:
        names = _stmt_targets(stmt)
        if not names:
            return
        value = getattr(stmt, "value", None)
        is_key_value = (
            isinstance(value, ast.Call)
            and _is_derivation(self.mod, value)
        ) or (
            isinstance(value, ast.Name) and value.id in state
        )
        for n in names:
            if is_key_value:
                state[n] = (
                    state.get(value.id, 0)
                    if isinstance(value, ast.Name)
                    else 0
                )
            else:
                state.pop(n, None)

    def run_block(
        self, stmts: list[ast.stmt], state: dict[str, int]
    ) -> bool:
        """Interpret a block; True when it terminates (return/raise/
        break/continue) — a terminated branch never merges into the
        fall-through state, so `if flip: return normal(key)` followed
        by `return uniform(key)` is one consumption per path."""
        terminated = False
        for stmt in stmts:
            if isinstance(
                stmt, (ast.Return, ast.Raise, ast.Break, ast.Continue)
            ):
                self._consume_stmt(stmt, state)
                terminated = True
                break
            if isinstance(stmt, ast.If):
                self._consume_expr(stmt.test, state)
                s1 = dict(state)
                t1 = self.run_block(stmt.body, s1)
                s2 = dict(state)
                t2 = self.run_block(stmt.orelse, s2)
                live = (
                    [s for s, t in ((s1, t1), (s2, t2)) if not t]
                    or [s1]  # both terminated: dead fall-through
                )
                # merge: a key alive in every live branch stays
                # tracked, at the max consumption any path reached
                merged: dict[str, int] = {}
                for k in set().union(*live):
                    if all(k in s for s in live):
                        merged[k] = max(s[k] for s in live)
                state.clear()
                state.update(merged)
                if t1 and t2 and stmt.orelse:
                    terminated = True
                    break
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                if isinstance(stmt, ast.While):
                    self._consume_expr(stmt.test, state)
                else:
                    # the loop target rebinds: not a key anymore
                    for node in ast.walk(stmt.target):
                        if isinstance(node, ast.Name):
                            state.pop(node.id, None)
                    self._consume_expr(stmt.iter, state)
                # two passes: reuse across iterations surfaces on the
                # second (a per-iteration rebind resets the count and
                # stays clean)
                self.run_block(stmt.body, state)
                self.run_block(stmt.body, state)
                self.run_block(stmt.orelse, state)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._consume_expr(item.context_expr, state)
                self.run_block(stmt.body, state)
            elif isinstance(stmt, ast.Try) or (
                stmt.__class__.__name__ == "TryStar"
            ):
                self.run_block(stmt.body, state)
                for h in stmt.handlers:
                    self.run_block(h.body, state)
                self.run_block(stmt.orelse, state)
                self.run_block(stmt.finalbody, state)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue  # separate scope, analyzed on its own
            else:
                self._consume_stmt(stmt, state)
                self._apply_stores(stmt, state)
        return terminated

    def _consume_expr(self, expr: ast.AST, state: dict[str, int]) -> None:
        wrapper = ast.Expr(value=expr)
        ast.copy_location(wrapper, expr)
        self._consume_stmt(wrapper, state)


def check(mod: ModuleInfo, project) -> list[Finding]:
    del project
    findings: list[Finding] = []
    scopes: list[tuple[list[ast.stmt], dict[str, int]]] = [
        (mod.tree.body, {})
    ]
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            state: dict[str, int] = {}
            args = node.args
            for a in (
                args.posonlyargs + args.args + args.kwonlyargs
            ):
                if _KEY_PARAM_RE.search(a.arg):
                    state[a.arg] = 0
            scopes.append((node.body, state))
    for body, state in scopes:
        scope = _Scope(mod)
        scope.run_block(body, state)
        findings.extend(scope.findings)
    return findings

"""DDP003 — donated buffer read after donation.

The serve-cache class: ``donate_argnums`` hands the argument's device
buffer to XLA for in-place reuse — after the call returns, the old
array aliases freed (or overwritten) memory. Reading it again is
use-after-free that XLA sometimes catches (a deleted-buffer error)
and sometimes silently serves garbage from, depending on backend and
timing. The serve engine's ``SlotCache`` lives and dies by this
contract: every ``self._cache`` rebind must consume the previous
reference, never keep it.

Detection (per module, best-effort by name):

- bindings ``f = jax.jit(g, donate_argnums=…)`` and defs decorated
  ``@partial(jax.jit, donate_argnums=…)`` record which positions (or
  argnames, resolved through ``g``'s signature) donate;
- at each call of a donating callable, a plain-Name argument in a
  donated position is DEAD after the call — a later load of that name
  in the same scope (before a rebind) is a finding;
- a donating call inside a loop whose donated Name is never rebound
  in the loop body is a finding at the call site (the next iteration
  re-reads the donated buffer).

``state = step(state, batch)`` — the idiom — is clean: the call
statement's own targets rebind the name.
"""

from __future__ import annotations

import ast

from ddp_tpu.analysis.core import Finding, ModuleInfo

_JIT_TAILS = ("jax.jit", "jax.pjit", "pjit.pjit")


def _is_jit(mod: ModuleInfo, fn: ast.AST) -> bool:
    resolved = mod.resolve(fn)
    return bool(resolved) and any(
        resolved == t or resolved.endswith("." + t) for t in _JIT_TAILS
    )


def _donated_positions(
    mod: ModuleInfo, call: ast.Call, params: list[str] | None
) -> set[int]:
    """Donated positional indices from a jit(...) call's keywords."""
    out: set[int] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            vals = (
                kw.value.elts
                if isinstance(kw.value, (ast.Tuple, ast.List))
                else [kw.value]
            )
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    out.add(v.value)
        elif kw.arg == "donate_argnames" and params:
            vals = (
                kw.value.elts
                if isinstance(kw.value, (ast.Tuple, ast.List))
                else [kw.value]
            )
            for v in vals:
                if (
                    isinstance(v, ast.Constant)
                    and isinstance(v.value, str)
                    and v.value in params
                ):
                    out.add(params.index(v.value))
    return out


def _collect_donating(mod: ModuleInfo) -> dict[str, set[int]]:
    """Name → donated positional indices, for names visible in this
    module (assignment bindings and decorated defs)."""
    defs: dict[str, list[str]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = [a.arg for a in node.args.args]

    donating: dict[str, set[int]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if not _is_jit(mod, call.func):
                continue
            params = None
            if call.args and isinstance(call.args[0], ast.Name):
                params = defs.get(call.args[0].id)
            pos = _donated_positions(mod, call, params)
            if not pos:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    donating[tgt.id] = pos
                elif isinstance(tgt, ast.Attribute):
                    # self._decode = jax.jit(..., donate_argnums=…):
                    # track by attribute name (method-call sites)
                    donating[tgt.attr] = pos
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and (
                    _is_jit(mod, dec.func)
                    or (
                        dec.args
                        and _is_jit(mod, dec.args[0])
                    )
                ):
                    params = [a.arg for a in node.args.args]
                    pos = _donated_positions(mod, dec, params)
                    if pos:
                        donating[node.name] = pos
    return donating


def _call_display(call: ast.Call) -> str:
    if isinstance(call.func, ast.Name):
        return call.func.id
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return "<call>"


def _stmt_targets(stmt: ast.stmt) -> set[str]:
    """Names this statement (re)binds."""
    out: set[str] = set()
    targets: list[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign, ast.For)):
        targets = [stmt.target]
    for t in targets:
        for node in ast.walk(t):
            if isinstance(node, ast.Name):
                out.add(node.id)
    return out


def _loads_in(node: ast.AST, name: str) -> list[ast.Name]:
    return [
        n
        for n in ast.walk(node)
        if isinstance(n, ast.Name)
        and n.id == name
        and isinstance(n.ctx, ast.Load)
    ]


def _stores_name(node: ast.AST, name: str) -> bool:
    return any(
        isinstance(n, ast.Name)
        and n.id == name
        and isinstance(n.ctx, ast.Store)
        for n in ast.walk(node)
    )


def _calls_in_stmt(stmt: ast.stmt) -> list[ast.Call]:
    """Calls within a statement, pruning nested def/lambda subtrees
    (those scopes get their own scan — analyzing their calls against
    THIS block's tail would be the wrong liveness context)."""
    out: list[ast.Call] = []
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return out  # its body is a separate scope, scanned on its own
    # compound statements: only the HEADER expressions belong to this
    # block position — their bodies are recursed into as blocks of
    # their own (analyzing a body call against THIS block's tail
    # would see phantom after-call loads)
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        roots: list[ast.AST] = [stmt.iter]
    elif isinstance(stmt, (ast.While, ast.If)):
        roots = [stmt.test]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        roots = [item.context_expr for item in stmt.items]
    elif isinstance(stmt, ast.Try) or stmt.__class__.__name__ == "TryStar":
        roots = []
    else:
        roots = [stmt]
    stack: list[ast.AST] = list(roots)
    while stack:
        node = stack.pop()
        if node is not stmt and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        if isinstance(node, ast.Call):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _scan_block(
    mod: ModuleInfo,
    stmts: list[ast.stmt],
    donating: dict[str, set[int]],
    findings: list[Finding],
    loop_stack: list[ast.stmt],
) -> None:
    for idx, stmt in enumerate(stmts):
        # donating calls anywhere inside this statement
        for call in _calls_in_stmt(stmt):
            fname = None
            if isinstance(call.func, ast.Name):
                fname = call.func.id
            elif isinstance(call.func, ast.Attribute):
                fname = call.func.attr
            pos = donating.get(fname or "")
            if not pos:
                continue
            rebound = _stmt_targets(stmt)
            for p in sorted(pos):
                if p >= len(call.args):
                    continue
                arg = call.args[p]
                if not isinstance(arg, ast.Name):
                    continue
                if arg.id in rebound:
                    # rebound by this very statement (`s = f(s, …)`,
                    # the idiom): later loads read the NEW buffer, and
                    # the next loop iteration does too. Clean.
                    continue
                # later loads in the tail of this block
                hit = None
                for later in stmts[idx + 1 :]:
                    if _stores_name(later, arg.id) and not _loads_in(
                        later, arg.id
                    ):
                        break  # rebound before any read
                    loads = _loads_in(later, arg.id)
                    if loads:
                        # a statement that both loads and stores
                        # (x = g(x)) still reads the dead buffer
                        hit = loads[0]
                        break
                if hit is not None:
                    findings.append(
                        Finding(
                            rule="DDP003",
                            path=mod.path,
                            line=hit.lineno,
                            col=hit.col_offset,
                            message=(
                                f"`{arg.id}` was donated to "
                                f"`{_call_display(call)}` on line "
                                f"{call.lineno} (donate_argnums={p}) "
                                "and is read again — its buffer is "
                                "dead after the call"
                            ),
                            hint=(
                                "use the call's RETURN value, or drop "
                                "the donation for this argument"
                            ),
                        )
                    )
                elif loop_stack:
                    loop = loop_stack[-1]
                    if not _stores_name(loop, arg.id):
                        findings.append(
                            Finding(
                                rule="DDP003",
                                path=mod.path,
                                line=call.lineno,
                                col=call.col_offset,
                                message=(
                                    f"`{arg.id}` is donated to "
                                    f"`{_call_display(call)}` inside a "
                                    "loop without being rebound — the "
                                    "next iteration re-reads the dead "
                                    "buffer"
                                ),
                                hint=(
                                    "rebind the donated name from the "
                                    "call's return value each iteration"
                                ),
                            )
                        )
        # recurse into nested blocks
        if isinstance(stmt, (ast.For, ast.While)):
            _scan_block(
                mod, stmt.body, donating, findings, loop_stack + [stmt]
            )
            _scan_block(mod, stmt.orelse, donating, findings, loop_stack)
        elif isinstance(stmt, ast.If):
            _scan_block(mod, stmt.body, donating, findings, loop_stack)
            _scan_block(mod, stmt.orelse, donating, findings, loop_stack)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            _scan_block(mod, stmt.body, donating, findings, loop_stack)
        elif isinstance(stmt, ast.Try) or stmt.__class__.__name__ == "TryStar":
            _scan_block(mod, stmt.body, donating, findings, loop_stack)
            for h in stmt.handlers:
                _scan_block(mod, h.body, donating, findings, loop_stack)
            _scan_block(mod, stmt.orelse, donating, findings, loop_stack)
            _scan_block(mod, stmt.finalbody, donating, findings, loop_stack)


def check(mod: ModuleInfo, project) -> list[Finding]:
    del project
    donating = _collect_donating(mod)
    if not donating:
        return []
    findings: list[Finding] = []
    seen: set[tuple[int, int, str]] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_block(mod, node.body, donating, findings, [])
    # top-level statements too (scripts)
    _scan_block(mod, mod.tree.body, donating, findings, [])
    uniq = []
    for f in findings:
        key = (f.line, f.col, f.message)
        if key not in seen:
            seen.add(key)
            uniq.append(f)
    return uniq

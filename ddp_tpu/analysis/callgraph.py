"""Jit-reachability call graph for the hazard checkers.

DDP002 (host-sync) only means something INSIDE code that jit traces:
``float(x)`` in the trainer's host loop is the design (the log-cadence
sync), the same call inside a function the train step calls is a
silent per-step stall (or a trace error waiting for a dynamic value).
So the checkers need "is this function reached from a jit/shard_map
root" — which this module answers with a package-wide best-effort
call graph:

- Roots: functions decorated ``@jax.jit`` / ``@partial(jax.jit, …)``,
  functions passed by name to ``jax.jit`` / ``pjit`` / ``shard_map`` /
  ``grad`` / ``vmap`` / ``pmap`` / ``checkpoint`` or to the ``lax``
  control-flow combinators (``scan``/``cond``/``while_loop``/…) —
  plus the bodies of lambdas passed to any of those. A function whose
  body calls a DEVICE collective (``lax.psum``/``psum_scatter``/
  ``reduce_scatter``/``all_gather``/``ppermute``/``all_to_all``/…) is
  a root too: device collectives are only meaningful under trace, so
  the enclosing function is in-graph by construction — this is what
  puts the zero strategy's scatter/gather helpers under DDP002 even
  when they reach the step through method plumbing the bare-name
  edges cannot chase. (Host-level agreement/multihost utils —
  ``agree_*``, ``process_allgather`` — deliberately do NOT root:
  their callers are host loops where a ``float(loss)`` is the design.)
- Edges: bare-name calls resolved within the module, and cross-module
  through ``from x import y`` when ``x`` is part of the linted tree.
- Closure: nested ``def``s of an in-graph function are in-graph (their
  bodies are traced with the parent).

Best-effort by design: method calls and higher-order plumbing are not
chased (no type inference in a linter), so reachability UNDERapproxi-
mates — a miss means a false negative, never a false positive.
"""

from __future__ import annotations

import ast
import dataclasses

from ddp_tpu.analysis.core import ModuleInfo

# Transforms whose function-valued arguments run under trace. Matched
# on the RESOLVED dotted name's tail so `jax.jit`, `jit` (from-import)
# and aliased spellings all hit.
TRACER_TAILS = (
    "jax.jit",
    "jax.pjit",
    "pjit.pjit",
    "jax.grad",
    "jax.value_and_grad",
    "jax.vmap",
    "jax.pmap",
    "jax.checkpoint",
    "jax.remat",
    "jax.shard_map",
    "shard_map.shard_map",
    "jax.lax.scan",
    "lax.scan",
    "jax.lax.cond",
    "lax.cond",
    "jax.lax.while_loop",
    "lax.while_loop",
    "jax.lax.fori_loop",
    "lax.fori_loop",
    "jax.lax.switch",
    "lax.switch",
    "jax.lax.map",
    "lax.map",
    "jax.lax.associative_scan",
    "lax.associative_scan",
)


# Device-side collectives: a call to one marks the ENCLOSING function
# in-graph (they trace or they crash — there is no host spelling).
# Strictly the device subset of collective.COLLECTIVE_ATTRS: the
# host-level agreement/multihost names must not root, or every host
# loop that agrees-then-logs would start flagging its deliberate syncs.
DEVICE_COLLECTIVE_ATTRS = {
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "all_gather",
    "all_gather_invariant",
    "ppermute",
    "pshuffle",
    "all_to_all",
    "psum_scatter",
    "reduce_scatter",
}


def is_tracer_name(resolved: str | None) -> bool:
    if not resolved:
        return False
    return any(
        resolved == t or resolved.endswith("." + t) for t in TRACER_TAILS
    )


def resolve_partial_target(mod: ModuleInfo, call: ast.Call):
    """``partial(jax.jit, …)`` → the inner transform call, else None."""
    fn = mod.resolve(call.func)
    if fn and (fn == "functools.partial" or fn.endswith(".partial")
               or fn == "partial"):
        if call.args and is_tracer_name(mod.resolve(call.args[0])):
            return call
    return None


@dataclasses.dataclass
class FunctionRecord:
    modname: str
    qualname: str  # "outer.inner" for nested defs
    node: ast.FunctionDef
    parent: str | None  # enclosing function qualname, None at module level
    calls: set[str]  # bare names called in the body (own nested defs excluded)


@dataclasses.dataclass
class Project:
    modules: dict[str, ModuleInfo]
    functions: dict[tuple[str, str], FunctionRecord]  # (modname, qualname)
    ingraph: set[tuple[str, str]]

    def is_ingraph(self, modname: str, qualname: str) -> bool:
        return (modname, qualname) in self.ingraph


def _collect_functions(mod: ModuleInfo) -> dict[str, FunctionRecord]:
    records: dict[str, FunctionRecord] = {}

    def visit(node: ast.AST, prefix: str, parent: str | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}" if prefix else child.name
                calls: set[str] = set()
                for sub in ast.walk(child):
                    if isinstance(sub, ast.Call) and isinstance(
                        sub.func, ast.Name
                    ):
                        calls.add(sub.func.id)
                records[qual] = FunctionRecord(
                    modname=mod.modname,
                    qualname=qual,
                    node=child,
                    parent=parent,
                    calls=calls,
                )
                visit(child, qual + ".", qual)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.", parent)
            else:
                visit(child, prefix, parent)

    visit(mod.tree, "", None)
    return records


def _function_args(call: ast.Call) -> list[ast.AST]:
    args = list(call.args)
    args.extend(kw.value for kw in call.keywords if kw.value is not None)
    return args


def _local_lookup(
    mod: ModuleInfo,
    funcs: dict[str, FunctionRecord],
    name: str,
    scope: str | None,
) -> str | None:
    """Resolve a bare name to a function qualname: innermost enclosing
    scope first (nested helpers shadow module-level ones), then the
    module level."""
    if scope:
        parts = scope.split(".")
        for i in range(len(parts), 0, -1):
            cand = ".".join(parts[:i] + [name])
            if cand in funcs:
                return cand
    # class methods register as "Class.name" — bare-name calls can't
    # reach them, so only true module-level functions match here.
    return name if name in funcs else None


def build_project(modules: list[ModuleInfo]) -> Project:
    mod_index = {m.modname: m for m in modules}
    functions: dict[tuple[str, str], FunctionRecord] = {}
    per_mod_funcs: dict[str, dict[str, FunctionRecord]] = {}
    for m in modules:
        recs = _collect_functions(m)
        per_mod_funcs[m.modname] = recs
        for qual, rec in recs.items():
            functions[(m.modname, qual)] = rec

    roots: set[tuple[str, str]] = set()

    def mark_name_root(mod: ModuleInfo, name: str, scope: str | None):
        funcs = per_mod_funcs[mod.modname]
        local = _local_lookup(mod, funcs, name, scope)
        if local is not None:
            roots.add((mod.modname, local))
            return
        # from-imported project function
        target = mod.aliases.get(name)
        if target and "." in target:
            tmod, tname = target.rsplit(".", 1)
            if tmod in per_mod_funcs and tname in per_mod_funcs[tmod]:
                roots.add((tmod, tname))

    def enclosing_scope(
        node: ast.AST, parents: dict, by_node: dict
    ) -> str | None:
        cur = parents.get(node)
        while cur is not None:
            if cur in by_node:
                return by_node[cur]
            cur = parents.get(cur)
        return None

    for m in modules:
        parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(m.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        funcs = per_mod_funcs[m.modname]
        by_node = {rec.node: qual for qual, rec in funcs.items()}
        for node in ast.walk(m.tree):
            # decorator roots
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec
                    if isinstance(dec, ast.Call):
                        if is_tracer_name(m.resolve(dec.func)) or (
                            resolve_partial_target(m, dec) is not None
                        ):
                            roots.add((m.modname, by_node[node]))
                        continue
                    if is_tracer_name(m.resolve(target)):
                        roots.add((m.modname, by_node[node]))
            # call-site roots: jit(f) / shard_map(f, ...) / scan(f, ...)
            if isinstance(node, ast.Call):
                # device-collective roots: the enclosing function of a
                # psum/psum_scatter/all_gather/... call is traced code.
                attr = None
                if isinstance(node.func, ast.Attribute):
                    attr = node.func.attr
                elif isinstance(node.func, ast.Name):
                    resolved = m.aliases.get(node.func.id, "")
                    if resolved:
                        attr = resolved.rsplit(".", 1)[-1]
                if attr in DEVICE_COLLECTIVE_ATTRS:
                    scope = enclosing_scope(node, parents, by_node)
                    if scope is not None:
                        roots.add((m.modname, scope))
                is_tracer = is_tracer_name(m.resolve(node.func))
                if not is_tracer and resolve_partial_target(m, node):
                    is_tracer = True
                if not is_tracer:
                    continue
                scope = enclosing_scope(node, parents, by_node)
                for arg in _function_args(node):
                    if isinstance(arg, ast.Name):
                        mark_name_root(m, arg.id, scope)
                    elif isinstance(arg, ast.Lambda):
                        # the lambda body is traced: every bare-name
                        # call inside it seeds reachability
                        for sub in ast.walk(arg.body):
                            if isinstance(sub, ast.Call) and isinstance(
                                sub.func, ast.Name
                            ):
                                mark_name_root(m, sub.func.id, scope)

    # BFS: callees of in-graph functions + nested defs of in-graph
    # functions are in-graph.
    ingraph: set[tuple[str, str]] = set()
    frontier = list(roots)
    while frontier:
        key = frontier.pop()
        if key in ingraph or key not in functions:
            continue
        ingraph.add(key)
        modname, qual = key
        mod = mod_index[modname]
        funcs = per_mod_funcs[modname]
        rec = functions[key]
        # nested defs trace with the parent
        prefix = qual + "."
        for other_qual in funcs:
            if other_qual.startswith(prefix):
                frontier.append((modname, other_qual))
        # bare-name callees
        for name in rec.calls:
            local = _local_lookup(mod, funcs, name, qual)
            if local is not None:
                frontier.append((modname, local))
                continue
            target = mod.aliases.get(name)
            if target and "." in target:
                tmod, tname = target.rsplit(".", 1)
                if tmod in per_mod_funcs and tname in per_mod_funcs[tmod]:
                    frontier.append((tmod, tname))

    return Project(
        modules=mod_index, functions=functions, ingraph=ingraph
    )


def ingraph_functions(
    project: Project, mod: ModuleInfo
) -> list[FunctionRecord]:
    return [
        rec
        for (modname, qual), rec in project.functions.items()
        if modname == mod.modname and (modname, qual) in project.ingraph
    ]

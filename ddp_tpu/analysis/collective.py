"""DDP001 — collective call under rank-divergent control flow.

The PR-5 deadlock class: a ``psum``/``all_gather``/checkpoint-save is
a *collective* — every rank must reach it the same number of times in
the same order. A call site guarded by ``process_index() == 0`` /
``ctx.is_main`` / ``rank`` (or reachable only through an ``except``
handler, which one rank can enter alone) lets ranks desync: the rank
that takes the branch blocks in the collective forever while its
peers run past it. PR 5's whole consensus layer
(``runtime/consensus.agree_any``) exists because exactly this bit the
health-checkpoint path.

Detection: walk each function keeping a stack of divergence contexts —

- an ``if``/``while`` whose test mentions a rank-identity signal
  (``process_index``, ``process_id``, ``rank``, ``local_rank``,
  ``is_main``, ``is_coordinator``) and does NOT itself come from an
  agreement (``agree_any``/``agree_all``/``_sync_flags``/…);
- an ``except`` handler body (exception paths are per-rank by nature).

A collective call inside any such context is a finding. Plain data
branches (``if halt:``) are NOT flagged — statically proving their
uniformity is impossible, and the PR-1→5 bugs were all explicit
rank-identity guards, so that is the class this rule pins.
"""

from __future__ import annotations

import ast

from ddp_tpu.analysis.core import Finding, ModuleInfo

# lax/multihost collectives by terminal attribute name.
# ``psum_scatter``/``reduce_scatter`` and the param ``all_gather`` are
# the ZeRO strategy's pair (parallel/zero.py): the same rank-uniformity
# contract as the all-reduce they replace, guarded by the same rule.
COLLECTIVE_ATTRS = {
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "all_gather",
    "all_gather_invariant",
    "ppermute",
    "pshuffle",
    "all_to_all",
    "psum_scatter",
    "reduce_scatter",
    "process_allgather",
    "sync_global_devices",
    "broadcast_one_to_all",
}
# host-level agreement primitives are collectives too
COLLECTIVE_NAMES = {"agree_any", "agree_all"}
# checkpoint manager methods that enter an Orbax (collective) save or
# restore — matched only on receivers that look like a manager.
CKPT_ATTRS = {"save", "restore", "restore_or_init", "wait"}
CKPT_RECEIVERS = {"ckpt", "checkpoint", "checkpointer", "ckpt_mgr"}

RANK_SIGNALS = {
    "process_index",
    "process_id",
    "rank",
    "local_rank",
    "is_main",
    "is_coordinator",
}
AGREEMENT_MARKS = ("agree", "_sync_flags", "consensus")


def _terminal_names(expr: ast.AST):
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute):
            yield node.attr
        elif isinstance(node, ast.Name):
            yield node.id


def _test_divergent(test: ast.AST) -> bool:
    names = set(_terminal_names(test))
    if not (names & RANK_SIGNALS):
        return False
    # an agreement in the same test means the branch is world-uniform
    # by construction (e.g. `if agree_any(self.rank_flag):`)
    for n in names:
        if any(mark in n for mark in AGREEMENT_MARKS):
            return False
    return True


def _collective_call(mod: ModuleInfo, call: ast.Call) -> str | None:
    """Return a display name when ``call`` is a collective, else None."""
    fn = call.func
    if isinstance(fn, ast.Name):
        if fn.id in COLLECTIVE_NAMES:
            return fn.id
        resolved = mod.aliases.get(fn.id, "")
        if resolved.rsplit(".", 1)[-1] in (
            COLLECTIVE_ATTRS | COLLECTIVE_NAMES
        ):
            return fn.id
        return None
    if isinstance(fn, ast.Attribute):
        if fn.attr in COLLECTIVE_ATTRS or fn.attr in COLLECTIVE_NAMES:
            return fn.attr
        if fn.attr in CKPT_ATTRS:
            recv = fn.value
            last = None
            if isinstance(recv, ast.Attribute):
                last = recv.attr
            elif isinstance(recv, ast.Name):
                last = recv.id
            if last is not None and last.lower() in CKPT_RECEIVERS:
                return f"{last}.{fn.attr}"
    return None


class _Walker(ast.NodeVisitor):
    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.context: list[str] = []
        self.findings: list[Finding] = []

    def _enter(self, reason: str | None, bodies: list) -> None:
        if reason is not None:
            self.context.append(reason)
        for body in bodies:
            for stmt in body:
                self.visit(stmt)
        if reason is not None:
            self.context.pop()

    def visit_If(self, node: ast.If) -> None:
        self.visit(node.test)
        reason = (
            "rank-dependent branch" if _test_divergent(node.test) else None
        )
        self._enter(reason, [node.body])
        # the else of a rank guard is JUST as divergent (the other
        # ranks' side of the split)
        self._enter(reason, [node.orelse])

    def visit_While(self, node: ast.While) -> None:
        self.visit(node.test)
        reason = (
            "rank-dependent loop" if _test_divergent(node.test) else None
        )
        self._enter(reason, [node.body, node.orelse])

    def visit_Try(self, node: ast.Try) -> None:
        self._enter(None, [node.body, node.orelse, node.finalbody])
        self._enter("exception path", [h.body for h in node.handlers])

    # TryStar (3.11+) shares Try's shape; getattr keeps 3.10 parsing.
    visit_TryStar = visit_Try

    def visit_Call(self, node: ast.Call) -> None:
        if self.context:
            name = _collective_call(self.mod, node)
            if name is not None:
                self.findings.append(
                    Finding(
                        rule="DDP001",
                        path=self.mod.path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"collective `{name}` under {self.context[-1]}"
                            " — ranks that skip this branch desync and "
                            "deadlock the world"
                        ),
                        hint=(
                            "hoist the collective out of the divergent "
                            "branch, or agree first "
                            "(runtime/consensus.agree_any)"
                        ),
                    )
                )
        self.generic_visit(node)

    # nested functions get their own walk with a fresh context: a
    # callback DEFINED under a rank guard is not itself divergent
    # control flow around a collective call site.
    def visit_FunctionDef(self, node) -> None:
        saved, self.context = self.context, []
        for stmt in node.body:
            self.visit(stmt)
        self.context = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved, self.context = self.context, []
        self.visit(node.body)
        self.context = saved


def check(mod: ModuleInfo, project) -> list[Finding]:
    del project
    w = _Walker(mod)
    w.visit(mod.tree)
    return w.findings

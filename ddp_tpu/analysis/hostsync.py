"""DDP002 — host sync inside jit-reachable code.

The PR-3 class: the serve engine's entire redesign existed to shrink
per-step host traffic to one ``[slots]`` int32 fetch, because every
``.item()`` / ``float(loss)`` / ``np.asarray(x)`` in the hot path is
a device→host round trip that stalls the dispatch pipeline (and on
multi-host, stalls *every* rank behind the slowest). Inside code that
jit actually traces these are worse than slow — a concrete-value pull
on a tracer is a trace error, a ``print`` runs once at trace time and
then silently never again.

Detection: the callgraph walk (``analysis/callgraph.py``) marks every
function reached from a ``jax.jit``/``shard_map``/``lax.*`` root;
within those, flag

- ``x.item()``
- ``np.asarray(...)`` / ``np.array(...)`` (numpy materialization)
- ``jax.device_get(...)``
- ``float(x)`` / ``int(x)`` / ``bool(x)`` on dynamic expressions
  (static shape arithmetic — ``.shape``/``.ndim``/``len()`` — is
  exempt: those are Python ints at trace time by construction)
- ``print(...)`` (trace-time only; ``jax.debug.print`` is the traced
  equivalent)

Host code is never flagged: the trainer's log-cadence ``float(loss)``
is the *design* (and the ``--sanitize`` transfer guard polices the
dynamic half of this rule at runtime).
"""

from __future__ import annotations

import ast

from ddp_tpu.analysis.callgraph import Project, ingraph_functions
from ddp_tpu.analysis.core import Finding, ModuleInfo

_CASTS = {"float", "int", "bool"}
# exact canonical names: `jnp` resolves to "jax.numpy", whose asarray
# is a DEVICE op — only host numpy materializes
_NUMPY_EXACT = {"numpy.asarray", "numpy.array"}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype"}
_STATIC_CALLS = {"len", "range", "enumerate"}


def _is_static_expr(node: ast.AST) -> bool:
    """True when the cast argument is trace-time static by
    construction: constants, shape/dtype attributes, len()-style
    introspection, or arithmetic over those."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        return node.attr in _STATIC_ATTRS or _is_static_expr(node.value)
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value)
    if isinstance(node, ast.Call):
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in _STATIC_CALLS
        ):
            return True
        # method calls on a static chain: mesh.shape.get("fsdp", 1)
        return isinstance(node.func, ast.Attribute) and _is_static_expr(
            node.func.value
        )
    if isinstance(node, ast.BinOp):
        return _is_static_expr(node.left) and _is_static_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand)
    return False


def _sync_kind(mod: ModuleInfo, call: ast.Call) -> tuple[str, str] | None:
    """(display, hint) when the call is a host sync, else None."""
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr == "item" and not call.args:
        return (
            "`.item()`",
            "return the array and read it at the host loop's cadence",
        )
    resolved = mod.resolve(fn)
    if resolved:
        if resolved in _NUMPY_EXACT:
            tail = resolved.rsplit(".", 1)[-1]
            return (
                f"`np.{tail}(...)`",
                "keep the value on device (jnp) or fetch it outside "
                "the traced function",
            )
        if resolved.endswith("jax.device_get") or resolved == "device_get":
            return (
                "`jax.device_get(...)`",
                "fetch outside the traced function",
            )
    if isinstance(fn, ast.Name):
        if fn.id == "print":
            return (
                "`print(...)`",
                "runs at TRACE time only — use jax.debug.print for "
                "per-step output",
            )
        if (
            fn.id in _CASTS
            and fn.id not in mod.aliases
            and len(call.args) == 1
            and not call.keywords
            and not _is_static_expr(call.args[0])
        ):
            return (
                f"`{fn.id}(...)` on a dynamic value",
                "a concrete-value pull on a tracer fails at trace "
                "time; keep the computation in jnp",
            )
    return None


def check(mod: ModuleInfo, project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for rec in ingraph_functions(project, mod):
        # nested in-graph functions are walked via their own record;
        # skip their bodies here so a finding lands exactly once.
        nested = {
            child.node
            for (mn, q), child in project.functions.items()
            if mn == mod.modname and q.startswith(rec.qualname + ".")
        }

        def walk(node: ast.AST):
            for child in ast.iter_child_nodes(node):
                if child in nested:
                    continue
                if isinstance(child, ast.Call):
                    kind = _sync_kind(mod, child)
                    if kind is not None:
                        display, hint = kind
                        findings.append(
                            Finding(
                                rule="DDP002",
                                path=mod.path,
                                line=child.lineno,
                                col=child.col_offset,
                                message=(
                                    f"host sync {display} inside "
                                    "jit-reachable code (reached from a "
                                    "jit/shard_map root via "
                                    f"`{rec.qualname}`)"
                                ),
                                hint=hint,
                            )
                        )
                walk(child)

        for stmt in rec.node.body:
            walk(stmt)
    return findings

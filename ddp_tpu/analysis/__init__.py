"""ddp_tpu.analysis — distributed-JAX hazard linter.

Static analysis over the package's own source for the hazard classes
every hard bug in the PR-1→5 arc belonged to (see docs/ANALYSIS.md
for the rule catalog and the war story motivating each):

  DDP001  collective under rank-divergent control flow (deadlocks)
  DDP002  host sync inside jit-reachable code (stalls / trace errors)
  DDP003  donated buffer read after donation (use-after-free)
  DDP004  recompile hazards (jit-in-loop, unhashable statics, shapes)
  DDP005  PRNG key reuse without split/fold_in (correlated sampling)

CLI: ``python scripts/lint.py [--self] [paths…]``. The runtime half —
``--sanitize`` (transfer guard + desync watchdog) — lives in
``ddp_tpu.runtime.sanitize``: static analysis finds the pattern, the
sanitizer proves the dynamic instance.
"""

from __future__ import annotations

import os

from ddp_tpu.analysis import (
    collective,
    donation,
    hostsync,
    prng,
    recompile,
)
from ddp_tpu.analysis.callgraph import build_project
from ddp_tpu.analysis.core import (  # noqa: F401 (public API)
    Finding,
    LintResult,
    RULE_TITLES,
    iter_py_files,
    load_module,
    run_checks,
)

CHECKS = {
    "DDP001": collective.check,
    "DDP002": hostsync.check,
    "DDP003": donation.check,
    "DDP004": recompile.check,
    "DDP005": prng.check,
}

# What `--self` lints: the package plus every entry point. One list,
# shared by the CLI, the smoke-tier CI gate, and bench.py's
# `lint_clean` field — they must not drift.
SELF_LINT_TARGETS = ("ddp_tpu", "scripts", "train.py", "bench.py")


def lint_paths(
    paths, *, select: set[str] | None = None
) -> LintResult:
    """Lint files/dirs → LintResult (findings sorted, suppressions
    applied). ``select`` restricts to a subset of rule ids (DDP000
    suppression hygiene always runs)."""
    triples = iter_py_files(paths)
    modules = []
    pre_findings = []
    for path, modname, rel in triples:
        loaded = load_module(path, modname, rel)
        if isinstance(loaded, Finding):
            pre_findings.append(loaded)
        else:
            modules.append(loaded)
    project = build_project(modules)
    checks = [
        fn
        for rule, fn in CHECKS.items()
        if select is None or rule in select
    ]
    findings = run_checks(modules, checks, project, pre_findings)
    return LintResult(findings=findings, files=len(triples))


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


def self_lint(*, select: set[str] | None = None) -> LintResult:
    root = repo_root()
    targets = [
        os.path.join(root, t)
        for t in SELF_LINT_TARGETS
        if os.path.exists(os.path.join(root, t))
    ]
    return lint_paths(targets, select=select)


def self_lint_clean() -> bool:
    """True when the tree self-lints with zero unsuppressed findings
    (bench.py stamps this on headline records so a lint regression is
    visible in the perf-trajectory sidecars)."""
    try:
        return not self_lint().unsuppressed
    except Exception:  # never let the linter break a bench record
        return False

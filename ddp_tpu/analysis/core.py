"""Static-analysis engine: findings, suppressions, project walking.

Pure-``ast`` machinery shared by every checker: no jax USE anywhere
in the analysis package (the parent package's import-time compat
shims are the only jax cost), and a syntax error in the tree under
analysis surfaces as a finding, not a crash. The hazard checkers
themselves live in sibling modules
(collective / hostsync / donation / recompile / prng); this module
owns what they share:

- :class:`Finding` — one diagnosed hazard, renderable as the
  golden-pinned ``path:line:col: RULE message [hint: ...]`` line and
  as a JSON object for CI.
- Suppressions — ``# ddp-lint: disable=DDP002 <justification>``
  silences matching rules on that line (or the next line when the
  comment stands alone). The justification is REQUIRED: a bare
  disable is itself reported as DDP000 — every silenced hazard must
  say why it is safe, in the source, where the next reader is.
- :class:`ModuleInfo` / import-alias resolution — ``np.asarray`` and
  ``numpy.asarray`` (or ``from jax.random import split``) resolve to
  one canonical dotted name so checkers match semantics, not
  spelling.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Iterable

# Rule ids are stable API: CI configs, suppression comments, and the
# fixture corpus all pin them.
RULE_TITLES = {
    "DDP000": "suppression without justification",
    "DDP001": "collective under rank-divergent control flow",
    "DDP002": "host sync inside jit-reachable code",
    "DDP003": "donated buffer read after donation",
    "DDP004": "recompile hazard",
    "DDP005": "PRNG key reuse without split/fold_in",
}


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    hint: str = ""
    suppressed: bool = False
    justification: str | None = None

    def render(self) -> str:
        s = f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"
        if self.hint:
            s += f" [hint: {self.hint}]"
        return s

    def to_dict(self) -> dict:
        d = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }
        if self.hint:
            d["hint"] = self.hint
        if self.suppressed:
            d["suppressed"] = True
            d["justification"] = self.justification
        return d


# ---- import-alias resolution ----------------------------------------


@dataclasses.dataclass
class ModuleInfo:
    """One parsed file plus the lookup tables the checkers share."""

    path: str  # as reported in findings (relative to the lint root)
    modname: str  # dotted module name ("ddp_tpu.serve.engine")
    source: str
    tree: ast.Module
    aliases: dict[str, str]  # local name -> canonical dotted prefix
    lines: list[str]

    def resolve(self, node: ast.AST) -> str | None:
        """Canonical dotted name of a Name/Attribute chain, with the
        module's import aliases folded in — or None for anything
        dynamic (subscripts, calls) along the chain."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        head = self.aliases.get(node.id, node.id)
        parts.append(head)
        return ".".join(reversed(parts))


def _collect_aliases(tree: ast.Module) -> dict[str, str]:
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.level:  # relative import — anchor later if needed
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def load_module(path: str, modname: str, rel: str) -> ModuleInfo | Finding:
    """Parse one file → ModuleInfo, or a Finding for a syntax error
    (the lint must report unparseable files, not die on them)."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return Finding(
            rule="DDP000",
            path=rel,
            line=e.lineno or 1,
            col=(e.offset or 1) - 1,
            message=f"syntax error: {e.msg}",
            hint="fix the parse error; no rules ran on this file",
        )
    return ModuleInfo(
        path=rel,
        modname=modname,
        source=source,
        tree=tree,
        aliases=_collect_aliases(tree),
        lines=source.splitlines(),
    )


# ---- suppressions ---------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*ddp-lint:\s*disable=([A-Za-z0-9,]+)[ \t]*(.*?)\s*$"
)


def parse_suppressions(
    mod: ModuleInfo,
) -> tuple[dict[int, tuple[set[str], str]], list[Finding]]:
    """Line → (rule ids, justification), plus DDP000 findings for
    suppressions missing their justification.

    A trailing comment suppresses its own line; a comment-only line
    suppresses the next line (the decorator position). The
    justification (everything after the rule list) is mandatory — the
    suppression still APPLIES either way (so a justification fix
    doesn't un-silence a known-accepted hazard mid-CI), but DDP000 is
    unsuppressable and fails the run until the why is written down.
    """
    supp: dict[int, tuple[set[str], str]] = {}
    problems: list[Finding] = []
    for i, text in enumerate(mod.lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = {r.strip().upper() for r in m.group(1).split(",") if r.strip()}
        justification = m.group(2).strip()
        target = i + 1 if text.strip().startswith("#") else i
        prev_rules, prev_just = supp.get(target, (set(), ""))
        supp[target] = (
            prev_rules | rules,
            justification or prev_just,
        )
        if not justification:
            problems.append(
                Finding(
                    rule="DDP000",
                    path=mod.path,
                    line=i,
                    col=text.index("#"),
                    message=(
                        "suppression without justification: "
                        f"disable={','.join(sorted(rules))} must say WHY "
                        "the hazard is safe here"
                    ),
                    hint=(
                        "write `# ddp-lint: disable=RULE <one-line "
                        "reason>`"
                    ),
                )
            )
    return supp, problems


def apply_suppressions(
    findings: list[Finding],
    supp: dict[int, tuple[set[str], str]],
) -> None:
    for f in findings:
        if f.rule == "DDP000":
            continue  # the meta-rule cannot be suppressed
        entry = supp.get(f.line)
        if entry and f.rule in entry[0]:
            f.suppressed = True
            f.justification = entry[1] or None


# ---- project walking ------------------------------------------------


def iter_py_files(paths: Iterable[str]) -> list[tuple[str, str, str]]:
    """Expand files/dirs → sorted (abspath, modname, relpath) triples.

    modname is dotted relative to the argument's parent (linting
    ``ddp_tpu`` yields ``ddp_tpu.serve.engine``); relpath is what
    findings print — relative to the CWD when inside it, absolute
    otherwise, so report lines are stable across checkouts.
    """
    out: list[tuple[str, str, str]] = []
    cwd = os.getcwd()

    def rel(p: str) -> str:
        r = os.path.relpath(p, cwd)
        return r if not r.startswith("..") else p

    for arg in paths:
        arg = os.path.abspath(arg)
        if os.path.isfile(arg):
            mod = os.path.splitext(os.path.basename(arg))[0]
            out.append((arg, mod, rel(arg)))
            continue
        base = os.path.dirname(arg)
        for dirpath, dirnames, filenames in os.walk(arg):
            dirnames[:] = sorted(
                d for d in dirnames
                if d != "__pycache__" and not d.startswith(".")
            )
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                modparts = os.path.relpath(full, base)[: -len(".py")]
                modname = modparts.replace(os.sep, ".")
                if modname.endswith(".__init__"):
                    modname = modname[: -len(".__init__")]
                out.append((full, modname, rel(full)))
    # de-dup (a file passed twice, or a dir plus a file inside it)
    seen: set[str] = set()
    uniq = []
    for t in out:
        if t[0] not in seen:
            seen.add(t[0])
            uniq.append(t)
    return sorted(uniq, key=lambda t: t[2])


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]
    files: int

    @property
    def unsuppressed(self) -> list[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> list[Finding]:
        return [f for f in self.findings if f.suppressed]

    def render_text(self) -> str:
        lines = [f.render() for f in self.unsuppressed]
        lines.append(
            f"ddp-lint: {len(self.unsuppressed)} finding(s) "
            f"({len(self.suppressed)} suppressed) in {self.files} "
            "file(s)"
        )
        return "\n".join(lines)

    def to_json(self) -> str:
        counts: dict[str, int] = {}
        for f in self.unsuppressed:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return json.dumps(
            {
                "version": 1,
                "files": self.files,
                "counts": dict(sorted(counts.items())),
                "findings": [f.to_dict() for f in self.findings],
            },
            indent=2,
        )


def run_checks(
    modules: list[ModuleInfo],
    checks: list[Callable],
    project,
    pre_findings: list[Finding],
) -> list[Finding]:
    """Run every checker over every module, then fold in suppressions
    and sort for stable (golden-pinnable) output."""
    findings = list(pre_findings)
    for mod in modules:
        mod_findings: list[Finding] = []
        for check in checks:
            mod_findings.extend(check(mod, project))
        supp, supp_problems = parse_suppressions(mod)
        apply_suppressions(mod_findings, supp)
        findings.extend(mod_findings)
        findings.extend(supp_problems)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings

"""DDP004 — recompile hazards.

The PR-2/PR-4 class: the obs layer grew a process-wide compile
counter and a recompile-storm sentry because recompiles are the
silent 100× step-time cliff — and every storm traced back to one of
a few static patterns:

- ``jax.jit(...)`` constructed inside a loop: each iteration builds a
  NEW callable with an empty cache, so every iteration pays a full
  XLA compile (the cache keys on function identity). Building a jit
  once inside a *builder function* — the codebase idiom — is fine;
  building it per loop iteration never is.
- unhashable static args: ``static_argnums``/``static_argnames``
  pointing at a parameter whose default (or call-site value) is a
  ``list``/``dict``/``set`` — jit requires hashable statics, and the
  error surfaces at the first call, far from the definition.
- data-dependent shapes: ``jnp.zeros(int(n * frac))``-style arithmetic
  flowing into a shape position recompiles once per distinct value
  (shape changes are new programs, the PR-3 bucketing lesson).

Each sub-pattern is one findable, fixture-pinned shape — not a
heuristic over the whole program.
"""

from __future__ import annotations

import ast

from ddp_tpu.analysis.core import Finding, ModuleInfo
from ddp_tpu.analysis.donation import _is_jit  # same jit-name matcher

_SHAPE_CTORS = (
    "numpy.zeros",
    "numpy.ones",
    "numpy.full",
    "numpy.empty",
    "jax.numpy.zeros",
    "jax.numpy.ones",
    "jax.numpy.full",
    "jax.numpy.empty",
    "jnp.zeros",
    "jnp.ones",
    "jnp.full",
    "jnp.empty",
)

_MUTABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
            ast.SetComp)


def _jit_call_here(mod: ModuleInfo, node: ast.Call) -> bool:
    if _is_jit(mod, node.func):
        return True
    # partial(jax.jit, ...) builds the same per-iteration callable
    resolved = mod.resolve(node.func)
    if resolved and (
        resolved == "functools.partial" or resolved.endswith(".partial")
        or resolved == "partial"
    ):
        return bool(node.args) and _is_jit(mod, node.args[0])
    return False


def _static_param_names(
    call: ast.Call, params: list[str] | None
) -> list[str]:
    """Parameter names declared static at this jit site."""
    out: list[str] = []
    for kw in call.keywords:
        vals = (
            kw.value.elts
            if isinstance(kw.value, (ast.Tuple, ast.List))
            else [kw.value]
        )
        if kw.arg == "static_argnames":
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    out.append(v.value)
        elif kw.arg == "static_argnums" and params:
            for v in vals:
                if (
                    isinstance(v, ast.Constant)
                    and isinstance(v.value, int)
                    and v.value < len(params)
                ):
                    out.append(params[v.value])
    return out


def _check_jit_in_loop(mod: ModuleInfo, findings: list[Finding]) -> None:
    loop_depth = 0

    def visit(node: ast.AST):
        nonlocal loop_depth
        is_loop = isinstance(node, (ast.For, ast.While, ast.AsyncFor))
        if is_loop:
            loop_depth += 1
        if (
            loop_depth > 0
            and isinstance(node, ast.Call)
            and _jit_call_here(mod, node)
        ):
            findings.append(
                Finding(
                    rule="DDP004",
                    path=mod.path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        "jit-compiled callable constructed inside a "
                        "loop — each iteration builds a fresh cache "
                        "and pays a full XLA compile"
                    ),
                    hint=(
                        "hoist the jax.jit(...) above the loop (the "
                        "compile cache keys on function identity)"
                    ),
                )
            )
        # comprehensions repeat their element expression too
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.DictComp,
                   ast.GeneratorExp)
        ):
            loop_depth += 1
            for child in ast.iter_child_nodes(node):
                visit(child)
            loop_depth -= 1
            return
        for child in ast.iter_child_nodes(node):
            visit(child)
        if is_loop:
            loop_depth -= 1

    visit(mod.tree)


def _check_unhashable_static(
    mod: ModuleInfo, findings: list[Finding]
) -> None:
    defs: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)

    def flag_defaults(fn: ast.FunctionDef, static_names: list[str]):
        args = fn.args
        pos = args.posonlyargs + args.args
        defaults = args.defaults
        offset = len(pos) - len(defaults)
        for i, d in enumerate(defaults):
            name = pos[offset + i].arg
            if name in static_names and isinstance(d, _MUTABLE):
                findings.append(
                    Finding(
                        rule="DDP004",
                        path=mod.path,
                        line=d.lineno,
                        col=d.col_offset,
                        message=(
                            f"static argument `{name}` defaults to an "
                            "unhashable value — jit statics must hash "
                            "(and a mutated default silently changes "
                            "the compile key)"
                        ),
                        hint="use a tuple (or a frozen dataclass)",
                    )
                )
        for kwarg, d in zip(args.kwonlyargs, args.kw_defaults):
            if (
                d is not None
                and kwarg.arg in static_names
                and isinstance(d, _MUTABLE)
            ):
                findings.append(
                    Finding(
                        rule="DDP004",
                        path=mod.path,
                        line=d.lineno,
                        col=d.col_offset,
                        message=(
                            f"static argument `{kwarg.arg}` defaults "
                            "to an unhashable value — jit statics "
                            "must hash"
                        ),
                        hint="use a tuple (or a frozen dataclass)",
                    )
                )

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and _is_jit(mod, node.func):
            params = None
            target: ast.FunctionDef | None = None
            if node.args and isinstance(node.args[0], ast.Name):
                target = defs.get(node.args[0].id)
                if target is not None:
                    params = [
                        a.arg
                        for a in target.args.posonlyargs + target.args.args
                    ]
            static_names = _static_param_names(node, params)
            if static_names and target is not None:
                flag_defaults(target, static_names)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and (
                    _is_jit(mod, dec.func)
                    or (dec.args and _is_jit(mod, dec.args[0]))
                ):
                    params = [
                        a.arg
                        for a in node.args.posonlyargs + node.args.args
                    ]
                    static_names = _static_param_names(dec, params)
                    if static_names:
                        flag_defaults(node, static_names)


def _contains_dynamic_int(node: ast.AST) -> ast.Call | None:
    """An ``int(<arithmetic>)`` buried in a shape expression."""
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Name)
            and sub.func.id == "int"
            and sub.args
            and isinstance(sub.args[0], ast.BinOp)
        ):
            return sub
    return None


def _check_dynamic_shapes(
    mod: ModuleInfo, findings: list[Finding]
) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = mod.resolve(node.func)
        if not resolved or not any(
            resolved == t or resolved.endswith("." + t)
            for t in _SHAPE_CTORS
        ):
            continue
        if not node.args:
            continue
        hit = _contains_dynamic_int(node.args[0])
        if hit is not None:
            findings.append(
                Finding(
                    rule="DDP004",
                    path=mod.path,
                    line=hit.lineno,
                    col=hit.col_offset,
                    message=(
                        "data-dependent `int(...)` arithmetic in a "
                        "shape position — every distinct value is a "
                        "new program (one full recompile each)"
                    ),
                    hint=(
                        "round the size to a bucket (powers of two: "
                        "the serve-engine prefill lesson) or pad to a "
                        "static bound"
                    ),
                )
            )


def check(mod: ModuleInfo, project) -> list[Finding]:
    del project
    findings: list[Finding] = []
    _check_jit_in_loop(mod, findings)
    _check_unhashable_static(mod, findings)
    _check_dynamic_shapes(mod, findings)
    return findings

"""The tuner: enumerate → cost-prune → measure → persist.

One function per site, each returning a JSON-ready report with the
full accounting (proposed / rejected / aliased / pruned fraction /
measured count / search wall-clock) so downstream surfaces (bench
records, ``scripts/autotune.py`` output, docs) never have to guess
what the search did. A warm cache short-circuits the whole pipeline:
``cache_hit=True, measured=0`` — zero search cost, the property
bench.py's ``tune`` entry asserts.

Winner selection is measured, not modeled: the default config is
ALWAYS in the measured set, and the winner is the measured-p50
argmin — so the tuned config is **no worse than the default by
construction** (equality when the default wins), and every measured
candidate's token streams must equal the default's before it is
eligible (identity asserted in :func:`tune_serve`, the
speed-not-results contract).
"""

from __future__ import annotations

import time
from typing import Any, Optional

from ddp_tpu.tune import cache as tcache
from ddp_tpu.tune import costmodel, measure, space


def _report_base(site: str, key: str) -> dict:
    return {
        "site": site,
        "key": key,
        "cache_hit": False,
        "measured": 0,
        "search_wall_s": 0.0,
    }


def _cache_hit_report(site: str, key: str, ent: dict) -> dict:
    rep = _report_base(site, key)
    rep.update(
        cache_hit=True,
        config=dict(ent["config"]),
        provenance=dict(ent.get("provenance", {})),
    )
    return rep


def tune_serve(
    spec,
    params,
    *,
    cache: Optional[tcache.TuningCache] = None,
    slots: int = 4,
    prefill_len: Optional[int] = None,
    draft_spec=None,
    draft_params=None,
    spec_tokens_grid: tuple[int, ...] = (0,),
    page_sizes: tuple[int, ...] = (0,),
    trace: Optional[list[dict]] = None,
    max_measure: int = 4,
    force: bool = False,
) -> dict:
    """Tune the serve scheduler site for one (model shape, hardware).

    ``max_measure`` bounds wall-clock (survivors beyond it, ordered by
    modeled cost, are deferred) — the deferral is REPORTED
    (``measure_deferred``), never silent.
    """
    key = tcache.cache_key("serve", tcache.model_signature(spec))
    if cache is not None and not force:
        ent = cache.lookup(key)
        if ent is not None:
            return _cache_hit_report("serve", key, ent)
    t0 = time.perf_counter()
    rep = _report_base("serve", key)
    sp = space.serve_space(
        spec,
        slots=slots,
        prefill_len=prefill_len,
        spec_tokens=spec_tokens_grid,
        page_sizes=page_sizes,
        draft_spec=draft_spec,
    )
    rep.update(proposed=sp.proposed, rejected=sp.rejected, aliased=sp.aliased)
    resolved_pl = prefill_len or max(1, spec.total_len // 2)
    if trace is None:
        trace = measure.canonical_trace(
            vocab_size=spec.vocab_size, prefill_len=resolved_pl
        )
    prompt_lens = [len(r["prompt"]) for r in trace]
    new_tokens = max(r["max_new_tokens"] for r in trace)
    entries, price_meta = costmodel.price_serve_candidates(
        spec,
        params,
        sp,
        slots=slots,
        prompt_lens=prompt_lens,
        new_tokens=new_tokens,
    )
    survivors, pruned = costmodel.prune_dominated(entries)
    rep.update(
        priced=sum(1 for e in entries if e.priced),
        pruned=len(pruned),
        pruned_fraction=(
            round(len(pruned) / len(entries), 4) if entries else 0.0
        ),
        cost_compiles=price_meta["compiles"],
    )
    # Measured set: the default config first (the baseline every
    # candidate must beat AND match token-for-token), then survivors
    # in modeled-cost order up to the budget.
    by_key = {c.key(): c for c in sp.candidates}
    order = sorted(
        survivors,
        key=lambda e: (
            e.flops if e.flops is not None else float("inf"),
            e.key,
        ),
    )
    deferred = max(0, len(order) - max_measure)
    if deferred:
        rep["measure_deferred"] = deferred
    order = order[:max_measure]
    default = measure.measure_serve(
        spec,
        params,
        {},
        trace=trace,
        slots=slots,
        prefill_len=prefill_len,
        draft_spec=draft_spec,
        draft_params=draft_params,
    )
    measured: dict[str, dict] = {"default": default}
    for e in order:
        cand = by_key[e.key]
        m = measure.measure_serve(
            spec,
            params,
            cand.knobs,
            trace=trace,
            slots=slots,
            prefill_len=prefill_len,
            draft_spec=draft_spec,
            draft_params=draft_params,
        )
        assert m["tokens"] == default["tokens"], (
            f"candidate {cand.knobs} changed token streams — tuning "
            "must change speed, never results"
        )
        measured[e.key] = m
    rep["measured"] = len(measured)
    winner_key = min(measured, key=lambda k: measured[k]["p50"])
    winner_knobs = (
        {} if winner_key == "default" else by_key[winner_key].knobs
    )
    rep.update(
        default_p50=default["p50"],
        tuned_p50=measured[winner_key]["p50"],
        winner=winner_key,
        config=winner_knobs,
        search_wall_s=round(time.perf_counter() - t0, 3),
    )
    if cache is not None:
        cache.store(
            key,
            winner_knobs,
            provenance={
                "winner": winner_key,
                "default_p50": default["p50"],
                "tuned_p50": measured[winner_key]["p50"],
                "pruned_fraction": rep["pruned_fraction"],
                "measured": rep["measured"],
                "search_wall_s": rep["search_wall_s"],
            },
        )
        cache.save()
    return rep


def tune_zero(
    params,
    world: int,
    *,
    cache: Optional[tcache.TuningCache] = None,
    model_sig: str,
    dcn: int = 1,
    grad_accum_steps: int = 1,
    force: bool = False,
) -> dict:
    """Tune the zero site: analytic comm pricing + measured
    pack/unpack round-trip (the honest CPU wall-clock).

    Winner: fewest collective bytes among survivors, pack-p50
    tie-break — on-fabric bytes dominate; the pack cost separates
    bucket counts at equal bytes.
    """
    key = tcache.cache_key("zero", model_sig)
    if cache is not None and not force:
        ent = cache.lookup(key)
        if ent is not None:
            return _cache_hit_report("zero", key, ent)
    t0 = time.perf_counter()
    rep = _report_base("zero", key)
    sp = space.zero_space(params, world, dcn=dcn)
    rep.update(proposed=sp.proposed, rejected=sp.rejected, aliased=sp.aliased)
    entries = costmodel.price_zero_candidates(
        params, world, sp, dcn=dcn, grad_accum_steps=grad_accum_steps
    )
    survivors, pruned = costmodel.prune_dominated(entries)
    rep.update(
        priced=sum(1 for e in entries if e.priced),
        pruned=len(pruned),
        pruned_fraction=(
            round(len(pruned) / len(entries), 4) if entries else 0.0
        ),
    )
    by_key = {c.key(): c for c in sp.candidates}
    pack_memo: dict[float, dict] = {}
    scored = []
    for e in survivors:
        mb = by_key[e.key].knobs["zero_bucket_mb"]
        if mb not in pack_memo:
            pack_memo[mb] = measure.measure_zero_pack(params, world, mb)
        scored.append((e.bytes_accessed, pack_memo[mb]["p50"], e.key))
    rep["measured"] = len(pack_memo)
    scored.sort()
    winner_key = scored[0][2]
    winner = by_key[winner_key].knobs
    rep.update(
        winner=winner_key,
        config=dict(winner),
        pack_p50={str(k): round(v["p50"], 6) for k, v in pack_memo.items()},
        search_wall_s=round(time.perf_counter() - t0, 3),
    )
    if cache is not None:
        cache.store(
            key,
            winner,
            provenance={
                "winner": winner_key,
                "pruned_fraction": rep["pruned_fraction"],
                "measured": rep["measured"],
                "search_wall_s": rep["search_wall_s"],
            },
        )
        cache.save()
    return rep

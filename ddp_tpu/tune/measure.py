"""Measurement stage: time the cost-model survivors for real.

The arbiter. Each surviving candidate is run through the SAME
harness bench.py's serve benches use — build the engine, ``warmup()``
under the compile-budget assert, drive a deterministic greedy
traffic trace, read step-latency p50/p99 off the engine's own
``StatSummary`` — **with the transfer guard armed**
(``sanitize=True``: the runtime sanitizer around the steady-state
decode dispatch) and with every candidate's token streams captured,
so a tuned config is correctness-checked in the same run that times
it: the tuner asserts each candidate's streams are identical to the
default config's before it may win. Tuning changes speed, never
results.

The zero site's wall-clock is the bucketed pack/unpack round-trip —
the host+dispatch overhead that scales with bucket count (the
collective cost is priced analytically upstream; on a single-host
CPU a timed collective would be a dishonest null anyway).
"""

from __future__ import annotations

import time
from typing import Any, Optional

from ddp_tpu.utils.metrics import StatSummary


def canonical_trace(
    *,
    vocab_size: int,
    prefill_len: int,
    requests: int = 6,
    new_tokens: int = 8,
    seed: int = 0,
) -> list[dict]:
    """Deterministic greedy traffic: prompt lengths sweep the bucket
    range (1 → prefill_len) so every candidate's bucket geometry is
    exercised, including the edges where a bad min_bucket would
    show."""
    import random

    rng = random.Random(seed)
    out = []
    for i in range(requests):
        # Even coverage of short/medium/full prompts, deterministic.
        plen = max(1, round((i + 1) * prefill_len / requests))
        out.append(
            {
                "prompt": [rng.randrange(vocab_size) for _ in range(plen)],
                "max_new_tokens": new_tokens,
                "seed": i,
            }
        )
    return out


def measure_serve(
    spec,
    params,
    knobs: dict[str, Any],
    *,
    trace: list[dict],
    slots: int = 4,
    prefill_len: Optional[int] = None,
    draft_spec=None,
    draft_params=None,
    sanitize: bool = True,
) -> dict:
    """Build + time one engine config → {p50, p99, wall_s, tokens,
    compile_programs}.

    ``tokens`` maps rid → the completed stream (the identity surface);
    the compile-budget and no-steady-state-recompile asserts are the
    bench harness's, verbatim — a candidate that recompiles mid-run
    is a broken candidate, not a slow one.
    """
    from ddp_tpu.serve.engine import ServeEngine

    eng = ServeEngine(
        spec,
        params,
        slots=slots,
        prefill_len=prefill_len,
        prefill_chunk=knobs.get("prefill_chunk"),
        min_bucket=knobs.get("min_bucket"),
        step_token_budget=knobs.get("step_token_budget"),
        page_size=knobs.get("page_size", 0) or 0,
        kv_pages=knobs.get("kv_pages"),
        spec_tokens=knobs.get("spec_tokens", 0) or 0,
        draft_spec=draft_spec if knobs.get("spec_tokens") else None,
        draft_params=draft_params if knobs.get("spec_tokens") else None,
        sanitize=sanitize,
        trace_seed=0,
    )
    counts = eng.warmup()
    assert sum(counts.values()) <= eng.compile_budget(), (
        f"candidate {knobs} exceeded the compile budget: {counts}"
    )
    for r in trace:
        adm = eng.submit(
            r["prompt"], r["max_new_tokens"], seed=r.get("seed", 0)
        )
        assert adm.accepted, (
            f"canonical trace rejected under {knobs}: {adm.reason}"
        )
    if eng.pending:
        eng.step()  # settle: first step pays dispatch warm-up
    eng.step_latency = StatSummary()
    t0 = time.perf_counter()
    while eng.pending:
        eng.step()
    wall = time.perf_counter() - t0
    assert eng.compile_counts() == counts, (
        f"candidate {knobs} recompiled in steady state: "
        f"{eng.compile_counts()} vs {counts}"
    )
    tokens = {
        rid: list(c.tokens) for rid, c in sorted(eng._completed.items())
    }
    return {
        "p50": eng.step_latency.percentile(50),
        "p99": eng.step_latency.percentile(99),
        "steps": eng.step_latency.count,
        "wall_s": wall,
        "tokens": tokens,
        "compile_programs": sum(counts.values()),
    }


def measure_zero_pack(
    params,
    world: int,
    bucket_mb: float,
    *,
    iters: int = 5,
) -> dict:
    """Wall-clock of the bucketed flatten→unflatten round-trip.

    The bucket_mb knob's host-visible cost: more buckets = more
    per-bucket dispatches and pad/slice work. Collective time is NOT
    in here (priced analytically; single-host CPU collectives are a
    null) — honest about what a CPU can measure.
    """
    import jax

    from ddp_tpu.parallel import zero as zmod

    layout = zmod.build_layout(params, world, bucket_mb=bucket_mb)
    leaves = jax.tree_util.tree_leaves(params)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        buckets = zmod._flatten_buckets(layout, leaves)
        out = zmod._unflatten_buckets(layout, buckets, leaves)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    times.sort()
    return {
        "p50": times[len(times) // 2],
        "best": times[0],
        "buckets": len(layout.buckets),
        "iters": iters,
    }

"""Cost-model pruning: pay XLA introspection, not wall-clock.

The tuner's middle stage. Each priceable candidate gets a
:class:`CostEntry` — XLA-counted FLOPs, bytes-accessed, and peak temp
bytes for the program mix the candidate would actually run — and
**dominated** entries (another candidate at least as good on every
priced axis and strictly better on one) are discarded before any
measurement. The pruned fraction is reported, never a silent cap.

Pricing goes through ``Xprof.instrument``'s existing
``lower().compile()`` path (:class:`ProgramCoster`), so the numbers
are the SAME ledger numbers every other surface reads — one compile
per distinct program shape, memoized, shared across all candidates
that use it. Serve candidates multiply those per-width program costs
by a **host-side chunk-plan simulation** driven by the real
``Scheduler.chunk_width`` (no scheduler re-implementation to drift)
over a canonical prompt trace.

Honesty rules:

- A knob the model can't price (spec γ: acceptance-rate-dependent;
  paged layouts: reuse-dependent) yields an entry with **no priced
  axes** — such entries are never pruned and always graduate to
  measurement. Dominance only ever prunes on information the model
  actually has.
- The zero site is priced analytically by the strategy's own
  ``zero_comm_bytes`` (per-replica collective payload) plus the
  layout's padding overhead — the same model
  ``tests/test_zero.py`` cross-checks against parsed HLO.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ddp_tpu.tune.space import SpaceReport


@dataclass
class CostEntry:
    """One candidate's priced axes (None = model has no information)."""

    key: str
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    memory_bytes: Optional[float] = None
    detail: dict = field(default_factory=dict)

    @property
    def priced(self) -> bool:
        return any(
            v is not None
            for v in (self.flops, self.bytes_accessed, self.memory_bytes)
        )


_AXES = ("flops", "bytes_accessed", "memory_bytes")


def dominates(a: CostEntry, b: CostEntry) -> bool:
    """True when ``a`` makes ``b`` not worth measuring.

    ``a`` must be at least as good on EVERY axis ``b`` has
    information on (an axis ``a`` can't price blocks the claim) and
    strictly better on at least one. Entries with no priced axes are
    never dominated — the model must not prune what it cannot see.
    """
    strict = False
    compared = False
    for ax in _AXES:
        bv = getattr(b, ax)
        if bv is None:
            continue
        av = getattr(a, ax)
        if av is None or av > bv:
            return False
        compared = True
        if av < bv:
            strict = True
    return compared and strict


def prune_dominated(
    entries: list[CostEntry],
) -> tuple[list[CostEntry], list[CostEntry]]:
    """(survivors, pruned) under pairwise dominance — order-stable."""
    survivors: list[CostEntry] = []
    pruned: list[CostEntry] = []
    for e in entries:
        if any(dominates(o, e) for o in entries if o is not e):
            pruned.append(e)
        else:
            survivors.append(e)
    return survivors, pruned


class ProgramCoster:
    """Prices programs through the xprof compile ledger, memoized.

    ``price(label, fn, *args)`` routes ``fn`` (a jit wrapper) through
    ``Xprof.instrument`` — the exact ``lower().compile()`` +
    ``cost_analysis()``/``memory_analysis()`` path PR 9 built — and
    returns the ledger entry's numbers. One compile per distinct
    (label, signature); every candidate sharing a program shape
    shares the price.
    """

    def __init__(self, xprof=None):
        from ddp_tpu.obs.xprof import Xprof

        self.xprof = xprof if xprof is not None else Xprof(enabled=True)
        self._inst: dict[str, Callable] = {}
        self._memo: dict[tuple, dict] = {}

    def price(self, label: str, fn: Callable, *args) -> dict:
        from ddp_tpu.obs.xprof import shape_signature

        key = (label, shape_signature(args))
        hit = self._memo.get(key)
        if hit is not None:
            return hit
        inst = self._inst.get(label)
        if inst is None:
            inst = self.xprof.instrument(fn, label)
            self._inst[label] = inst
        inst(*args)  # compile (ledgered) + one dispatch
        rec = next(
            r
            for r in reversed(self.xprof.ledger_records())
            if r["label"] == label
        )
        mem = rec.get("memory", {})
        out = {
            "flops": rec.get("flops"),
            "bytes_accessed": rec.get("bytes_accessed"),
            "memory_bytes": (
                mem.get("temp_bytes", 0) + mem.get("output_bytes", 0)
            )
            or None,
            "compiles": 1,
        }
        self._memo[key] = out
        return out


# ---- serve site -----------------------------------------------------


def plan_chunk_counts(
    resolved: dict,
    prompt_lens: list[int],
    *,
    slots: int,
    prefill_len: int,
    total_len: int,
) -> dict[int, int]:
    """Chunk-program invocations per width for one candidate, via the
    REAL scheduler's ``chunk_width`` over the canonical trace.

    Steady state assumed: each step's prefill budget is the token
    budget minus a full complement of decoding lanes (the
    conservative case — prefill is slowest exactly when decode is
    saturated)."""
    from ddp_tpu.serve.scheduler import Scheduler

    sch = Scheduler(
        max_queue=len(prompt_lens) + 1,
        prefill_len=prefill_len,
        total_len=total_len,
        chunk=resolved["chunk"],
        min_bucket=resolved["min_bucket"],
        token_budget=resolved["step_token_budget"],
    )
    per_step_budget = max(
        resolved["min_bucket"],
        resolved["step_token_budget"]
        - slots * resolved["tokens_per_decode"],
    )
    counts: dict[int, int] = {}
    for plen in prompt_lens:
        start, remaining = 0, plen
        while remaining > 0:
            w = sch.chunk_width(start, remaining, per_step_budget)
            if w is None:  # pragma: no cover — validation floor holds
                break
            counts[w] = counts.get(w, 0) + 1
            consumed = min(w, remaining)
            start += consumed
            remaining -= consumed
    return counts


def price_serve_candidates(
    spec,
    params,
    report: SpaceReport,
    *,
    slots: int,
    prompt_lens: list[int],
    new_tokens: int,
    coster: Optional[ProgramCoster] = None,
) -> tuple[list[CostEntry], dict]:
    """CostEntry per candidate; γ/paged candidates come back unpriced
    (measure-only) by the honesty rule above.

    The chunk program is proxied by the LM forward at ``[1, width]``
    (the chunk's dominant work; the cache-write epilogue is
    width-independent), the decode program by the forward at
    ``[slots, 1]`` — both priced once per distinct shape and shared
    across every candidate.
    """
    import functools

    import jax
    import jax.numpy as jnp

    from ddp_tpu.models.lm import dense_lm_apply

    coster = coster or ProgramCoster()
    fwd = jax.jit(functools.partial(dense_lm_apply, spec))
    width_price: dict[int, dict] = {}

    def price_width(w: int) -> dict:
        if w not in width_price:
            width_price[w] = coster.price(
                "tune.chunk_forward", fwd, params,
                jnp.zeros((1, w), jnp.int32),
            )
        return width_price[w]

    decode_price = coster.price(
        "tune.decode_forward", fwd, params,
        jnp.zeros((slots, 1), jnp.int32),
    )
    total_new = new_tokens * len(prompt_lens)
    entries: list[CostEntry] = []
    for cand in report.candidates:
        eff = report.resolved[cand.key()]
        if eff.get("spec_tokens") or eff.get("page_size"):
            entries.append(
                CostEntry(key=cand.key(), detail={"measure_only": True})
            )
            continue
        counts = plan_chunk_counts(
            eff,
            prompt_lens,
            slots=slots,
            prefill_len=max(prompt_lens),
            total_len=spec.total_len,
        )
        flops = bytes_acc = 0.0
        mem = 0.0
        known = True
        for w, n in counts.items():
            p = price_width(w)
            if p["flops"] is None or p["bytes_accessed"] is None:
                known = False
                break
            flops += n * p["flops"]
            bytes_acc += n * p["bytes_accessed"]
            mem = max(mem, p["memory_bytes"] or 0)
        # Decode work is candidate-independent on this subspace but
        # keeps the totals in real units.
        decode_steps = -(-total_new // max(1, min(slots, len(prompt_lens))))
        if known and decode_price["flops"] is not None:
            flops += decode_steps * decode_price["flops"]
            bytes_acc += decode_steps * (decode_price["bytes_accessed"] or 0)
            mem = max(mem, decode_price["memory_bytes"] or 0)
        entries.append(
            CostEntry(
                key=cand.key(),
                flops=flops if known else None,
                bytes_accessed=bytes_acc if known else None,
                memory_bytes=mem if known and mem else None,
                detail={
                    "chunk_counts": {str(k): v for k, v in counts.items()},
                    "decode_steps": decode_steps,
                },
            )
        )
    meta = {
        "priced_widths": sorted(width_price),
        "compiles": len(width_price) + 1,
    }
    return entries, meta


# ---- zero site ------------------------------------------------------


def price_zero_candidates(
    params,
    world: int,
    report: SpaceReport,
    *,
    dcn: int = 1,
    grad_accum_steps: int = 1,
) -> list[CostEntry]:
    """Analytic pricing via the strategy's own ``zero_comm_bytes``:
    bytes = per-step collective payload, memory = bucket padding
    overhead. FLOPs are knob-independent here (same update math), so
    that axis stays unpriced."""
    import jax.numpy as jnp

    from ddp_tpu.parallel.zero import build_layout, zero_comm_bytes

    entries: list[CostEntry] = []
    for cand in report.candidates:
        knobs = cand.knobs
        layout = build_layout(
            params, world, bucket_mb=knobs["zero_bucket_mb"]
        )
        gd = (
            jnp.bfloat16
            if knobs["zero_gather_dtype"] == "bf16"
            else jnp.float32
        )
        comm = zero_comm_bytes(
            layout,
            world,
            grad_accum_steps=grad_accum_steps,
            dcn=dcn,
            gather_dtype=gd,
            hier=bool(knobs.get("hier")),
        )
        total = comm.get("total")
        if total is None:
            total = sum(
                v for v in comm.values() if isinstance(v, (int, float))
            )
        entries.append(
            CostEntry(
                key=cand.key(),
                bytes_accessed=float(total),
                memory_bytes=float(layout.padded_total * 4),
                detail={
                    "buckets": len(layout.buckets),
                    "comm": {
                        k: v
                        for k, v in comm.items()
                        if isinstance(v, (int, float))
                    },
                },
            )
        )
    return entries

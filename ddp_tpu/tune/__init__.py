"""Self-tuning performance: the measure→tune→load loop (ROADMAP 5).

Four stages, one module each:

- :mod:`ddp_tpu.tune.space` — declarative per-site knob grids whose
  validity predicates ARE the engines' own construction validation
  (``resolve_engine_knobs`` / ``build_layout``): the tuner can never
  propose a config the CLI would reject.
- :mod:`ddp_tpu.tune.costmodel` — XLA-priced dominance pruning
  through ``Xprof.instrument``'s ``lower().compile()`` path; pruned
  fraction reported, unpriceable knobs never pruned.
- :mod:`ddp_tpu.tune.measure` — wall-clock for the survivors with
  bench.py's harness (step p50/p99, compile-budget assert, transfer
  guard armed, token streams captured for the identity check).
- :mod:`ddp_tpu.tune.cache` — ``tuning_cache.json`` beside the
  checkpoints, keyed model-shape × platform × backend × device-kind
  × site-version; explicit CLI flags always beat cache entries;
  trainer / serve / fleet load it by default (``--tuned auto``).

``scripts/autotune.py`` is the CLI; :mod:`ddp_tpu.tune.tuner` the
orchestration.
"""

from ddp_tpu.tune.cache import (
    SITE_VERSIONS,
    TuningCache,
    apply_tuned,
    cache_key,
    default_cache_path,
    env_signature,
    model_signature,
    resolve_cache,
    train_signature,
)
from ddp_tpu.tune.costmodel import (
    CostEntry,
    ProgramCoster,
    dominates,
    prune_dominated,
)
from ddp_tpu.tune.measure import canonical_trace, measure_serve
from ddp_tpu.tune.space import (
    Candidate,
    SpaceReport,
    decode_block_space,
    serve_space,
    zero_space,
)
from ddp_tpu.tune.tuner import tune_serve, tune_zero

__all__ = [
    "SITE_VERSIONS",
    "TuningCache",
    "apply_tuned",
    "cache_key",
    "default_cache_path",
    "env_signature",
    "model_signature",
    "resolve_cache",
    "train_signature",
    "CostEntry",
    "ProgramCoster",
    "dominates",
    "prune_dominated",
    "canonical_trace",
    "measure_serve",
    "Candidate",
    "SpaceReport",
    "decode_block_space",
    "serve_space",
    "zero_space",
    "tune_serve",
    "tune_zero",
]

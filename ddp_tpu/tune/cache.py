"""Persistent tuning cache — the "load by default" half of the loop.

``tuning_cache.json`` lives beside the checkpoint dir and maps a
**cache key** to the winning knob config plus its provenance (how it
was chosen: measured p50s, pruned fraction, search wall-clock). The
key is built like the AOT signature cache's:

    site | model-shape signature | platform | backend | device kind | vN

so a cache tuned on one (model shape, hardware) pair can never leak
onto another: change the model dims, the backend, or the device kind
and the lookup misses — the caller falls back to defaults, exactly
today's behavior. ``vN`` is the **knob-site version**: bump
``SITE_VERSIONS[site]`` whenever a site's knob semantics change
(renamed knob, different validity floor) and every stale entry
invalidates itself.

Precedence is fixed and tested: **explicit CLI flags > cache entry >
built-in default** (:func:`apply_tuned` implements it for every
caller — serve, fleet, trainer — so the rule can't drift per
surface). A cache hit costs zero search; ``--tuned off`` never opens
the file.

Writes are atomic (tmp + ``os.replace``, the checkpoint idiom) and
last-writer-wins per key — concurrent tuners on different shapes
merge, same shape overwrites.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Optional

# Bump a site's version whenever its knob semantics change; old cache
# entries for that site then miss and re-tune instead of silently
# applying values with a different meaning.
SITE_VERSIONS = {
    "serve": 1,
    "zero": 1,
    "decode_block": 1,
    "fleet": 1,
}

CACHE_BASENAME = "tuning_cache.json"


def default_cache_path(checkpoint_dir: str) -> str:
    """``tuning_cache.json`` beside the checkpoints — tuned configs
    travel with the weights they were tuned for."""
    return os.path.join(checkpoint_dir, CACHE_BASENAME)


def env_signature() -> tuple[str, str, str]:
    """(platform, backend, device_kind) of the default device — the
    hardware half of the cache key."""
    import jax

    d = jax.devices()[0]
    return (
        d.platform,
        jax.default_backend(),
        str(getattr(d, "device_kind", "unknown")),
    )


def model_signature(spec) -> str:
    """Shape signature of an ``LMSpec`` — every field that changes the
    compiled program set or the knob optimum."""
    return (
        f"lm:v{spec.vocab_size}:l{spec.total_len}:d{spec.d_model}"
        f":dep{spec.depth}:h{spec.num_heads}"
        f":kv{getattr(spec, 'num_kv_heads', 0)}"
        f":e{getattr(spec, 'num_experts', 0)}"
    )


def train_signature(config) -> str:
    """Shape signature of a trainer config (the zero site's key)."""
    return (
        f"train:{config.model}:dim{config.model_dim}"
        f":dep{config.model_depth}:h{config.num_heads}"
        f":seq{config.seq_len}:v{config.vocab_size}"
    )


def cache_key(
    site: str,
    model_sig: str,
    *,
    platform: Optional[str] = None,
    backend: Optional[str] = None,
    device_kind: Optional[str] = None,
) -> str:
    """The full lookup key; env fields default to the live process's.

    Keyed exactly like the AOT signature cache: any change to the
    shape OR the hardware is a different key, and a site-version bump
    orphans every old entry.
    """
    if platform is None or backend is None or device_kind is None:
        p, b, k = env_signature()
        platform = platform or p
        backend = backend or b
        device_kind = device_kind or k
    version = SITE_VERSIONS.get(site, 1)
    return f"{site}|{model_sig}|{platform}|{backend}|{device_kind}|v{version}"


class TuningCache:
    """The on-disk key→entry map. Missing/corrupt files read as empty
    (a broken cache degrades to defaults, never to a crash)."""

    SCHEMA = 1

    def __init__(self, path: str):
        self.path = path
        self.entries: dict[str, dict] = {}
        self.load()

    def load(self) -> None:
        self.entries = {}
        try:
            with open(self.path) as f:
                doc = json.load(f)
            if isinstance(doc, dict) and doc.get("schema") == self.SCHEMA:
                ent = doc.get("entries")
                if isinstance(ent, dict):
                    self.entries = ent
        except (OSError, ValueError):
            pass

    def lookup(self, key: str) -> Optional[dict]:
        """The stored entry ({config, provenance}) or None."""
        ent = self.entries.get(key)
        if isinstance(ent, dict) and isinstance(ent.get("config"), dict):
            return ent
        return None

    def store(
        self,
        key: str,
        config: dict,
        *,
        provenance: Optional[dict] = None,
    ) -> None:
        self.entries[key] = {
            "config": dict(config),
            "provenance": dict(provenance or {}),
            "written_at": time.time(),
        }

    def save(self) -> None:
        """Atomic write-through (tmp + ``os.replace``): a reader never
        sees a torn file, a crash never corrupts the old one."""
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        doc = {"schema": self.SCHEMA, "entries": self.entries}
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
        os.replace(tmp, self.path)


def resolve_cache(tuned: str, checkpoint_dir: Optional[str]) -> Optional[TuningCache]:
    """``--tuned auto|off|<path>`` → an open cache or None.

    ``off`` (or ``auto`` with no checkpoint dir) returns None without
    touching the filesystem — the byte-identical-to-today path. An
    explicit path opens even when the file doesn't exist yet (the
    tuner writes through it).
    """
    if tuned == "off":
        return None
    if tuned == "auto":
        if not checkpoint_dir:
            return None
        return TuningCache(default_cache_path(checkpoint_dir))
    return TuningCache(tuned)


def apply_tuned(
    current: dict[str, Any],
    entry_config: dict[str, Any],
    *,
    explicit: set[str] | frozenset[str] = frozenset(),
) -> tuple[dict[str, Any], dict[str, Any], list[str]]:
    """Merge a cache entry under the fixed precedence rule.

    ``current`` maps knob name → the caller's present value; a knob is
    filled from the cache only when it is NOT in ``explicit`` (the
    names the user set on the command line — explicit flags always
    win). Returns ``(merged, applied, overridden)``: the merged knob
    dict, the subset actually taken from the cache, and the cached
    knobs the user overrode — both land in the provenance record so a
    tuned run is distinguishable from a default one in every triage
    surface.
    """
    merged = dict(current)
    applied: dict[str, Any] = {}
    overridden: list[str] = []
    for name, value in entry_config.items():
        if name not in merged:
            continue  # a knob this surface doesn't own
        if name in explicit:
            overridden.append(name)
            continue
        merged[name] = value
        applied[name] = value
    return merged, applied, sorted(overridden)

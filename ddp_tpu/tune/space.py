"""Declarative knob search spaces, one per tunable site.

A **site** is a place the codebase already exposes a measured knob
surface: the serve scheduler (``min_bucket`` / ``prefill_chunk`` /
``step_token_budget`` / spec γ / ``page_size`` / ``kv_pages``), the
zero optimizer (``bucket_mb`` × ``gather_dtype`` × hier-vs-flat), and
the Pallas decode kernel (``block_k``). Each site enumerates a small
grid and filters it through a **validity predicate that IS the
engine's own construction validation** — ``serve_space`` calls
``serve.engine.resolve_engine_knobs`` (the exact function
``ServeEngine.__init__`` runs) and ``zero_space`` calls
``parallel.zero.build_layout`` + the gather-dtype table — so the
tuner can never propose a config the CLI would reject: there is no
second copy of the rules to drift.

Candidates that resolve to the same effective config (pow2 snapping
makes grids alias: ``min_bucket 5`` and ``8`` both resolve to 8)
dedupe on the resolved tuple — measuring an alias twice would charge
wall-clock for zero information. Every drop is counted and reported
(``proposed`` vs ``valid`` vs ``aliased``), never silent.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass(frozen=True)
class Candidate:
    """One proposable knob assignment at a site."""

    site: str
    config: tuple[tuple[str, Any], ...]  # sorted (knob, value) pairs

    @property
    def knobs(self) -> dict[str, Any]:
        return dict(self.config)

    def key(self) -> str:
        return ",".join(f"{k}={v}" for k, v in self.config)


def _cand(site: str, knobs: dict[str, Any]) -> Candidate:
    return Candidate(site=site, config=tuple(sorted(knobs.items())))


@dataclass
class SpaceReport:
    """An enumerated site: the valid candidates plus the accounting
    that proves nothing was silently capped."""

    site: str
    proposed: int = 0  # raw grid size
    rejected: int = 0  # failed the engine's own validation
    aliased: int = 0  # valid but resolved identical to an earlier one
    candidates: list[Candidate] = field(default_factory=list)
    # candidate.key() → the resolved effective config (what the engine
    # would actually run); measurement and provenance use this, not
    # the raw proposal.
    resolved: dict[str, dict] = field(default_factory=dict)


# ---- serve scheduler site -------------------------------------------

# Knob grids: small by design — the cost model prunes before
# wall-clock, but the grid itself should stay enumerable in one
# report line.
SERVE_MIN_BUCKETS = (4, 8, 16)
SERVE_CHUNKS = (16, 32, 64)
SERVE_BUDGET_SCALES = (1.0, 1.5, 2.0)  # × the floor for the config


def serve_space(
    spec,
    *,
    slots: int = 4,
    prefill_len: Optional[int] = None,
    spec_tokens: tuple[int, ...] = (0,),
    page_sizes: tuple[int, ...] = (0,),
    draft_spec=None,
) -> SpaceReport:
    """Enumerate the serve scheduler's knob surface.

    γ values other than 0 and paged values other than 0 are only
    proposed when the caller actually has a draft model / wants the
    paged layout in scope — the tuner tunes what the deployment can
    run, not the whole engine feature matrix. Validity and resolution
    both come from ``resolve_engine_knobs``; budget candidates are
    expressed as scales of each config's own starvation floor so the
    grid tracks the validity frontier instead of fighting it.
    """
    from ddp_tpu.serve.engine import resolve_engine_knobs

    report = SpaceReport(site="serve")
    for mb, ck, bscale, gamma, psize in itertools.product(
        SERVE_MIN_BUCKETS,
        SERVE_CHUNKS,
        SERVE_BUDGET_SCALES,
        spec_tokens,
        page_sizes,
    ):
        report.proposed += 1
        knobs = {
            "min_bucket": mb,
            "prefill_chunk": ck,
            "spec_tokens": gamma,
            "page_size": psize,
        }
        try:
            base = resolve_engine_knobs(
                spec,
                slots=slots,
                prefill_len=prefill_len,
                prefill_chunk=ck,
                min_bucket=mb,
                page_size=psize,
                spec_tokens=gamma,
                draft_spec=draft_spec,
                has_draft_params=draft_spec is not None,
            )
            # Budget floor for THIS config's resolved bucket geometry.
            floor = (
                base["min_bucket"]
                + slots * base["tokens_per_decode"]
            )
            budget = int(round(floor * bscale))
            resolved = resolve_engine_knobs(
                spec,
                slots=slots,
                prefill_len=prefill_len,
                prefill_chunk=ck,
                min_bucket=mb,
                step_token_budget=budget,
                page_size=psize,
                spec_tokens=gamma,
                draft_spec=draft_spec,
                has_draft_params=draft_spec is not None,
            )
        except ValueError:
            report.rejected += 1
            continue
        knobs["step_token_budget"] = budget
        cand = _cand("serve", knobs)
        eff = {
            k: resolved[k]
            for k in (
                "chunk",
                "min_bucket",
                "step_token_budget",
                "spec_tokens",
                "tokens_per_decode",
                "page_size",
                "kv_pages",
            )
        }
        if any(r == eff for r in report.resolved.values()):
            report.aliased += 1
            continue
        report.candidates.append(cand)
        report.resolved[cand.key()] = eff
    return report


# ---- zero optimizer site --------------------------------------------

ZERO_BUCKET_MB = (1.0, 4.0, 16.0)
ZERO_GATHER_DTYPES = ("fp32", "bf16")


def zero_space(
    params,
    world: int,
    *,
    dcn: int = 1,
) -> SpaceReport:
    """bucket_mb × gather_dtype × hier, validated by the strategy's
    own constructors (``build_layout`` raises on a bad bucket_mb; the
    gather dtype must be in the strategy's table; hier needs a DCN
    axis)."""
    from ddp_tpu.parallel.zero import GATHER_DTYPES, build_layout

    report = SpaceReport(site="zero")
    hiers = (False, True) if dcn > 1 else (False,)
    for mb, gd, hier in itertools.product(
        ZERO_BUCKET_MB, ZERO_GATHER_DTYPES, hiers
    ):
        report.proposed += 1
        if gd not in GATHER_DTYPES:
            report.rejected += 1
            continue
        try:
            layout = build_layout(params, world, bucket_mb=mb)
        except ValueError:
            report.rejected += 1
            continue
        knobs = {
            "zero_bucket_mb": mb,
            "zero_gather_dtype": gd,
            "hier": hier,
        }
        cand = _cand("zero", knobs)
        eff = dict(knobs)
        eff["buckets"] = len(layout.buckets)
        eff["padded_total"] = layout.padded_total
        if any(r == eff for r in report.resolved.values()):
            report.aliased += 1
            continue
        report.candidates.append(cand)
        report.resolved[cand.key()] = eff
    return report


# ---- Pallas decode-block site ---------------------------------------

DECODE_BLOCKS = (32, 64, 128, 256, 512)


def decode_block_space(total_len: int) -> SpaceReport:
    """``block_k`` for ``ops/decode.flash_decode_attention``.

    Every request is constructible (``pick_block_k`` snaps to the
    largest divisor), so validity never rejects — but the snap makes
    grids alias hard (all requests ≥ L collapse to L's largest
    divisor), and the dedupe is what keeps TPU measurement cheap.
    """
    from ddp_tpu.ops.decode import pick_block_k

    report = SpaceReport(site="decode_block")
    for bk in DECODE_BLOCKS:
        report.proposed += 1
        eff = {"block_k": pick_block_k(total_len, bk)}
        knobs = {"block_k": bk}
        cand = _cand("decode_block", knobs)
        if any(r == eff for r in report.resolved.values()):
            report.aliased += 1
            continue
        report.candidates.append(cand)
        report.resolved[cand.key()] = eff
    return report

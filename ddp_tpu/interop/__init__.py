"""Interop with the reference's torch checkpoint format.

A reference user's training run lives in ``./checkpoints/epoch_N.pt``
files (train_ddp.py:204-209). This package converts those to/from this
framework's Orbax checkpoints so a migration keeps its training
progress — the missing piece of "switch frameworks mid-run".
"""

from ddp_tpu.interop.torch_checkpoint import (
    export_torch_checkpoint,
    import_torch_checkpoint,
    params_from_torch_state_dict,
    params_to_torch_state_dict,
)

__all__ = [
    "export_torch_checkpoint",
    "import_torch_checkpoint",
    "params_from_torch_state_dict",
    "params_to_torch_state_dict",
]

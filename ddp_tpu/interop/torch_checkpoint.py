"""Reference-format (.pt) checkpoint import/export for SimpleCNN.

The reference saves ``{"epoch", "model": state_dict, "optimizer":
opt_state_dict}`` via ``torch.save`` every epoch (train_ddp.py:204-209)
with keys ``net.0.*``/``net.2.*`` (the two convs inside the
``nn.Sequential``) and ``fl.*`` (the linear head) — model.py:8-16.

Layout translation, not just renaming:

- conv weights: torch is OIHW, Flax/TPU is HWIO — transpose (2,3,1,0);
- the linear head follows a flatten whose element order differs: torch
  flattens NCHW activations (channel-major), this framework flattens
  NHWC (channel-minor). The imported ``fl.weight`` is therefore
  re-gathered per output unit — reshape (out, C, H, W) → transpose to
  (out, H, W, C) → flatten → transpose — so the imported network
  computes the SAME function on the same images, not merely the same
  parameter multiset.

Optimizer state is NOT translated: the reference runs momentum-less SGD
whose torch state dict is empty (verified from its shipped
``epoch_1.pt`` — SURVEY.md §2a #8), and cross-framework moment tensors
would be layout-ambiguous for anything richer. Import starts a fresh
optimizer; the epoch counter and parameters carry over.

torch is imported lazily — the training path never needs it.
"""

from __future__ import annotations

import math
from typing import Any, Mapping

import numpy as np


def _strip_ddp_prefix(state_dict: Mapping[str, Any]) -> dict[str, Any]:
    """Drop the ``module.`` prefix a DDP-wrapped ``state_dict()`` adds.

    The reference saves ``model.module.state_dict()`` (already
    unwrapped, train_ddp.py:206) but re-prefixes on load
    (train_ddp.py:182); accept both forms.
    """
    return {
        (k[len("module.") :] if k.startswith("module.") else k): v
        for k, v in state_dict.items()
    }


def params_from_torch_state_dict(state_dict: Mapping[str, Any]) -> dict:
    """Reference SimpleCNN ``state_dict`` → Flax ``params`` pytree.

    Accepts torch tensors or numpy arrays as values. Returns
    ``{"conv1": {...}, "conv2": {...}, "fc": {...}}`` matching
    ``ddp_tpu.models.cnn.SimpleCNN``.
    """
    sd = {
        k: np.asarray(getattr(v, "numpy", lambda: v)())
        for k, v in _strip_ddp_prefix(state_dict).items()
    }
    expected = {
        "net.0.weight", "net.0.bias", "net.2.weight", "net.2.bias",
        "fl.weight", "fl.bias",
    }
    missing = expected - sd.keys()
    if missing:
        raise KeyError(
            f"not a reference SimpleCNN state_dict: missing {sorted(missing)}"
        )

    w1 = sd["net.0.weight"]  # (O, I, kh, kw)
    w2 = sd["net.2.weight"]
    fl = sd["fl.weight"]  # (num_classes, C*H*W) in NCHW flatten order
    out_dim, flat = fl.shape
    channels = w2.shape[0]
    if flat % channels:
        raise ValueError(
            f"fl.weight width {flat} is not divisible by the final "
            f"conv's {channels} channels"
        )
    hw = flat // channels
    side = math.isqrt(hw)
    if side * side != hw:
        raise ValueError(f"non-square spatial dim: {hw}")
    # channel-major (C,H,W) flatten → channel-minor (H,W,C) flatten
    fc_kernel = (
        fl.reshape(out_dim, channels, side, side)
        .transpose(0, 2, 3, 1)
        .reshape(out_dim, flat)
        .T
    )
    return {
        "conv1": {
            "kernel": w1.transpose(2, 3, 1, 0),  # OIHW → HWIO
            "bias": sd["net.0.bias"],
        },
        "conv2": {
            "kernel": w2.transpose(2, 3, 1, 0),
            "bias": sd["net.2.bias"],
        },
        "fc": {"kernel": fc_kernel, "bias": sd["fl.bias"]},
    }


def params_to_torch_state_dict(params: Mapping[str, Any]) -> dict:
    """Flax SimpleCNN ``params`` → reference-keyed torch ``state_dict``.

    The exact inverse of :func:`params_from_torch_state_dict`; the
    returned dict contains torch tensors ready for ``torch.save``.
    """
    import torch

    k1 = np.asarray(params["conv1"]["kernel"])  # (kh, kw, I, O)
    k2 = np.asarray(params["conv2"]["kernel"])
    fc = np.asarray(params["fc"]["kernel"])  # (H*W*C, num_classes)
    flat, out_dim = fc.shape
    channels = k2.shape[-1]
    side = math.isqrt(flat // channels)
    fl = (
        fc.T.reshape(out_dim, side, side, channels)
        .transpose(0, 3, 1, 2)
        .reshape(out_dim, flat)
    )
    to_t = lambda a: torch.from_numpy(np.ascontiguousarray(a))
    return {
        "net.0.weight": to_t(k1.transpose(3, 2, 0, 1)),  # HWIO → OIHW
        "net.0.bias": to_t(np.asarray(params["conv1"]["bias"])),
        "net.2.weight": to_t(k2.transpose(3, 2, 0, 1)),
        "net.2.bias": to_t(np.asarray(params["conv2"]["bias"])),
        "fl.weight": to_t(fl),
        "fl.bias": to_t(np.asarray(params["fc"]["bias"])),
    }


def import_torch_checkpoint(path: str) -> tuple[dict, int]:
    """Load a reference ``epoch_N.pt`` → ``(flax_params, epoch)``.

    ``weights_only=True``: checkpoint files are data, not code — no
    pickle execution from an untrusted file.
    """
    import torch

    ckpt = torch.load(path, map_location="cpu", weights_only=True)
    if not isinstance(ckpt, dict) or "model" not in ckpt:
        raise ValueError(
            f"{path}: expected the reference's {{epoch, model, optimizer}} "
            "checkpoint layout"
        )
    return params_from_torch_state_dict(ckpt["model"]), int(ckpt.get("epoch", 0))


def export_torch_checkpoint(
    path: str,
    params: Mapping[str, Any],
    epoch: int,
    *,
    lr: float = 0.01,
    momentum: float = 0,
) -> None:
    """Write ``{epoch, model, optimizer}`` the reference can consume.

    The optimizer entry mirrors the reference's momentum-less SGD save:
    empty ``state``, one param group listing the six tensors — enough
    for its (never actually restored — train_ddp.py:88) optimizer slot.
    ``lr``/``momentum`` default to the reference's hard-coded recipe
    (train_ddp.py:41); pass the run's actual values so the artifact
    doesn't misstate the training config to other consumers.
    """
    import torch

    state_dict = params_to_torch_state_dict(params)
    torch.save(
        {
            "epoch": int(epoch),
            "model": state_dict,
            "optimizer": {
                "state": {},
                "param_groups": [
                    {
                        "lr": lr,
                        "momentum": momentum,
                        "params": list(range(len(state_dict))),
                    }
                ],
            },
        },
        path,
    )

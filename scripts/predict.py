#!/usr/bin/env python
"""Inference CLI: classify with a trained checkpoint, no trainer needed.

The reference ends at training (no eval, no inference — SURVEY.md §5);
this closes the deployment half of the loop:

    python scripts/predict.py --model simple_cnn --dataset mnist
    python scripts/predict.py --model resnet18 --dataset cifar10 \
        --images batch.npy --out preds.npy

Restores the latest (or ``--epoch N``) checkpoint template-free — the
checkpoint's own metadata supplies the tree, so the optimizer that
produced it is irrelevant. With ``--dataset``, runs the test split and
prints accuracy as one JSON line; with ``--images`` (a .npy of NHWC
uint8/float), writes predicted classes to ``--out`` (.npy) and prints a
summary line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Importing the package applies the JAX_PLATFORMS env pin (see
# ddp_tpu/__init__.py): CPU-forced invocations never touch the TPU
# tunnel, and never hang when it is unreachable.
import ddp_tpu  # noqa: F401,E402


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--checkpoint_dir", default="./checkpoints")
    p.add_argument("--epoch", type=int, default=None, help="default: latest")
    p.add_argument("--model", default="simple_cnn")
    p.add_argument("--model_depth", type=int, default=None)
    p.add_argument("--num_classes", type=int, default=None)
    p.add_argument("--dataset", default=None, help="evaluate its test split")
    p.add_argument("--data_root", default="./data")
    p.add_argument("--synthetic_data", action="store_true")
    p.add_argument("--images", default=None, help=".npy of NHWC images")
    p.add_argument("--out", default=None, help=".npy for predicted classes")
    p.add_argument("--batch_size", type=int, default=256)
    p.add_argument(
        "--compute_dtype", default="float32", choices=("float32", "bfloat16")
    )
    # Causal-LM generation (--model causal_lm): KV-cache decode.
    p.add_argument("--prompt", default=None, help="text prompt (byte tokens)")
    p.add_argument(
        "--prompt_tokens", default=None, help="comma-separated token ids"
    )
    p.add_argument("--max_new_tokens", type=int, default=64)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument(
        "--top_k", type=int, default=0,
        help="sample only among the k highest-probability tokens (0 = off)",
    )
    p.add_argument(
        "--top_p", type=float, default=1.0,
        help="nucleus sampling: smallest token set with mass >= p (1 = off)",
    )
    p.add_argument(
        "--beam_width", type=int, default=1,
        help="beam-search decode width (>1 implies deterministic "
        "search; mutually exclusive with sampling flags)",
    )
    p.add_argument("--gen_seed", type=int, default=0)
    # Architecture is derived from the checkpoint's param shapes; only
    # the head count (invisible in shapes) is a flag.
    p.add_argument("--num_heads", type=int, default=4)
    args = p.parse_args()
    if args.model == "causal_lm":
        if (args.prompt is None) == (args.prompt_tokens is None):
            p.error(
                "--model causal_lm needs exactly one of "
                "--prompt / --prompt_tokens"
            )
        # Mirror generate()'s validation as clean CLI errors instead
        # of ValueError tracebacks.
        if not 0.0 < args.top_p <= 1.0:
            p.error(f"--top_p must be in (0, 1], got {args.top_p}")
        if args.top_k < 0:
            p.error(f"--top_k must be >= 0, got {args.top_k}")
        if args.temperature <= 0.0 and (args.top_k or args.top_p < 1.0):
            p.error(
                "--top_k/--top_p only apply when sampling: set "
                "--temperature > 0 (greedy decoding ignores them)"
            )
        if args.beam_width < 1:
            p.error(f"--beam_width must be >= 1, got {args.beam_width}")
        if args.beam_width > 1 and (
            args.temperature > 0.0 or args.top_k or args.top_p < 1.0
        ):
            p.error(
                "--beam_width > 1 is a deterministic search; drop "
                "--temperature/--top_k/--top_p"
            )
        _generate_lm(args)
        return
    if (args.dataset is None) == (args.images is None):
        p.error("exactly one of --dataset / --images is required")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddp_tpu.data.registry import NUM_CLASSES, load_split
    from ddp_tpu.models import get_model
    from ddp_tpu.parallel.common import _preprocess, _train_kwarg
    from ddp_tpu.train.checkpoint import CheckpointManager

    mgr = CheckpointManager(args.checkpoint_dir)
    params, model_state, epoch = mgr.restore_for_inference(args.epoch)
    mgr.close()

    num_classes = args.num_classes or NUM_CLASSES.get(args.dataset or "", 10)
    model_kw = {}
    if args.model_depth is not None:
        model_kw["depth"] = args.model_depth
    model = get_model(args.model, num_classes=num_classes, **model_kw)
    compute_dtype = (
        jnp.bfloat16 if args.compute_dtype == "bfloat16" else jnp.float32
    )
    train_kw = _train_kwarg(model, False)
    if compute_dtype != jnp.float32:
        # Cast once on the host, not inside the jitted per-batch call.
        params = jax.tree.map(lambda v: v.astype(compute_dtype), params)

    @jax.jit
    def forward(images):
        x = _preprocess(images, compute_dtype)
        logits = model.apply({"params": params, **model_state}, x, **train_kw)
        return jnp.argmax(logits.astype(jnp.float32), -1)

    def predict_all(images):
        if len(images) == 0:
            return np.zeros((0,), np.int32)
        preds = []
        for i in range(0, len(images), args.batch_size):
            chunk = np.asarray(images[i : i + args.batch_size])
            n = len(chunk)
            # Pad the tail so one compiled shape serves every batch.
            if n < args.batch_size:
                chunk = np.concatenate(
                    [chunk, chunk[:1].repeat(args.batch_size - n, 0)]
                )
            preds.append(np.asarray(forward(jnp.asarray(chunk)))[:n])
        return np.concatenate(preds)

    if args.dataset:
        test = load_split(
            args.dataset, args.data_root, "test",
            allow_synthetic=args.synthetic_data,
        )
        preds = predict_all(test.images)
        acc = float((preds == test.labels).mean())
        print(
            json.dumps(
                {
                    "epoch": epoch,
                    "dataset": args.dataset,
                    "n": int(len(test.labels)),
                    "accuracy": round(acc, 4),
                }
            )
        )
    else:
        images = np.load(args.images)
        if images.ndim == 3:  # single image → batch of one
            images = images[None]
        preds = predict_all(images)
        if args.out:
            np.save(args.out, preds)
        print(
            json.dumps(
                {
                    "epoch": epoch,
                    "n": int(len(preds)),
                    "out": args.out,
                    "predictions": preds[:16].tolist(),
                }
            )
        )


def _generate_lm(args) -> None:
    """Restore a causal-LM checkpoint and decode from it (KV cache).

    vocab_size, total_len, d_model and depth are DERIVED from the
    restored parameter shapes (embed [V, d], pos_embed [1, L, d],
    blockN count) — trusting CLI flags here would silently clamp
    positions past the real table (JAX OOB-slice semantics) and emit
    garbage. Only --num_heads (not recoverable from shapes) comes from
    the flag, validated against d_model. With a byte vocabulary
    (≥256), --prompt text is encoded as raw bytes and the continuation
    decoded back to text.
    """
    import jax.numpy as jnp
    import numpy as np

    from ddp_tpu.models.generate import generate
    from ddp_tpu.train.checkpoint import (
        CheckpointManager,
        derive_spec_with_sidecar,
    )

    mgr = CheckpointManager(args.checkpoint_dir)
    params, _, epoch = mgr.restore_for_inference(args.epoch)
    mgr.close()
    # A tokenizer saved next to the checkpoints (BPE training runs,
    # data/text.py) is part of the model: prompts encode through it
    # and continuations decode back to text. Absent file = byte vocab.
    tokenizer = None
    tok_path = os.path.join(args.checkpoint_dir, "tokenizer.json")
    if os.path.exists(tok_path):
        from ddp_tpu.data.bpe import BPETokenizer

        tokenizer = BPETokenizer.load(tok_path)
    # MoE checkpoints decode too (round 5): generate.py routes each
    # block by the presence of "moe" in its param tree. The lm_spec
    # sidecar the trainer writes beside the epochs supplies the fields
    # shapes cannot carry (num_heads, MoE routing config); CLI
    # --num_heads remains the fallback for sidecar-less checkpoints.
    try:
        spec = derive_spec_with_sidecar(
            args.checkpoint_dir, params, num_heads_fallback=args.num_heads
        )
    except ValueError as e:
        raise SystemExit(
            f"checkpoint in {args.checkpoint_dir}: {e}"
        )

    if args.prompt_tokens is not None:
        toks = [int(t) for t in args.prompt_tokens.split(",") if t.strip()]
    elif tokenizer is not None:
        toks = tokenizer.encode(args.prompt).tolist()
        if tokenizer.vocab_size > spec.vocab_size:
            raise SystemExit(
                f"tokenizer at {tok_path} has {tokenizer.vocab_size} "
                f"ids but the checkpoint embeds {spec.vocab_size}"
            )
    else:
        toks = list(args.prompt.encode("utf-8"))
        bad = [t for t in toks if t >= spec.vocab_size]
        if bad:
            raise SystemExit(
                f"--prompt bytes {sorted(set(bad))} exceed vocab_size "
                f"{spec.vocab_size}; use --prompt_tokens"
            )
    prompt = jnp.asarray([toks], jnp.int32)
    if args.beam_width > 1:
        from ddp_tpu.models.generate import beam_search

        beams, scores = beam_search(
            spec,
            params,
            prompt,
            max_new_tokens=args.max_new_tokens,
            beam_width=args.beam_width,
        )
        out = np.asarray(beams)[0, 0]  # best beam
        extra = {
            "beam_width": args.beam_width,
            "beam_scores": [round(float(s), 4) for s in np.asarray(scores)[0]],
        }
    else:
        out = np.asarray(
            generate(
                spec,
                params,
                prompt,
                max_new_tokens=args.max_new_tokens,
                temperature=args.temperature,
                top_k=args.top_k,
                top_p=args.top_p,
                seed=args.gen_seed,
            )
        )[0]
        extra = {
            "temperature": args.temperature,
            "top_k": args.top_k,
            "top_p": args.top_p,
        }
    new = out[len(toks):]
    record = {
        "epoch": epoch,
        "prompt_tokens": toks,
        "tokens": new.tolist(),
        **extra,
    }
    if tokenizer is not None:
        record["text"] = tokenizer.decode(new)
    elif spec.vocab_size >= 256 and max(new.tolist(), default=0) < 256:
        record["text"] = bytes(int(t) for t in new).decode(
            "utf-8", errors="replace"
        )
    print(json.dumps(record))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""One-screen triage report from a metrics JSONL stream.

    python scripts/health_report.py metrics.jsonl

Summarizes what the first five minutes of an incident actually need:
step-time p50/p99 and the input-wait fraction (is it the data
pipeline?), MFU and goodput (is the chip earning its keep?), the
grad-norm trajectory (was it diverging before it died?), anomaly
sentry events, NaN provenance, and recompile counts (shape leak?).
Reads only the JSONL the trainer always writes — works on a live
file mid-run, a dead run's tail, or a finished run.

Output is deterministic for a given input (golden-pinned in
tests/test_health.py), so it is also greppable from cron/CI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddp_tpu.utils.metrics import StatSummary  # noqa: E402


def _fmt(v, nd: int = 4) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def load_records(path: str) -> list[dict]:
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                # A live (or killed) run's last line can be partial;
                # the report must still answer from the rest.
                continue
    return records


def build_report(records: list[dict]) -> str:
    steps = [r for r in records if r.get("kind") == "step"]
    epochs = [r for r in records if r.get("kind") == "epoch"]
    health = [r for r in records if r.get("kind") == "health"]
    finals = [r for r in records if r.get("kind") == "final"]

    lines = ["ddp_tpu run health report", "=" * 25]

    losses = [r["loss"] for r in steps if r.get("loss") is not None]
    lines.append(
        f"steps logged  : {len(steps)} (epochs {len(epochs)})"
    )
    if losses:
        lines.append(
            f"loss          : first {_fmt(losses[0])} -> last "
            f"{_fmt(losses[-1])} (min {_fmt(min(losses))})"
        )
        nulls = sum(1 for r in steps if r.get("loss") is None)
        if nulls:
            lines.append(
                f"                {nulls} step record(s) with "
                "non-finite loss (serialized null)"
            )
    gnorms = [
        (r.get("step"), r["grad_norm"])
        for r in steps
        if r.get("grad_norm") is not None
    ]
    if gnorms:
        peak_step, peak = max(gnorms, key=lambda t: t[1])
        lines.append(
            f"grad norm     : first {_fmt(gnorms[0][1])} -> last "
            f"{_fmt(gnorms[-1][1])} (max {_fmt(peak)} @ step {peak_step})"
        )

    # Step wall time from the attribution fields (--trace_dir runs);
    # falls back to per-epoch seconds/batches when untraced.
    times = StatSummary()
    wait = StatSummary()
    for r in steps:
        if "compute_s" in r:
            wall = (
                r.get("input_wait_s", 0.0)
                + r.get("dispatch_s", 0.0)
                + r["compute_s"]
            )
            times.add(wall)
            if wall > 0:
                wait.add(r.get("input_wait_s", 0.0) / wall)
    if times.count:
        frac = wait.snapshot().get("mean")
        lines.append(
            f"step time     : p50 {_fmt(times.percentile(50), 4)}s  "
            f"p99 {_fmt(times.percentile(99), 4)}s  "
            f"(input-wait {_fmt(100.0 * frac, 1)}%)"
        )
    elif epochs:
        per = [
            e["seconds"] / e["batches"]
            for e in epochs
            if e.get("batches")
        ]
        if per:
            lines.append(
                f"step time     : ~{_fmt(sum(per) / len(per), 4)}s "
                "mean (epoch-level; re-run with --trace_dir for "
                "per-step attribution)"
            )

    mfus = [e["mfu"] for e in epochs if e.get("mfu") is not None]
    if mfus:
        lines.append(f"mfu           : last {_fmt(mfus[-1], 6)}")
    # Prefer the final record's full goodput snapshot (it carries the
    # restart count); fall back to the latest epoch fraction mid-run.
    final_gp = finals[-1].get("goodput") if finals else None
    if isinstance(final_gp, dict):
        lines.append(
            f"goodput       : {_fmt(final_gp.get('goodput'), 6)} "
            f"({_fmt(final_gp.get('restarts'))} restart(s))"
        )
    else:
        epoch_gps = [
            e["goodput"] for e in epochs if e.get("goodput") is not None
        ]
        if epoch_gps:
            lines.append(f"goodput       : {_fmt(epoch_gps[-1], 6)}")

    # Restart/fallback triage (the fault-tolerance layer): how many
    # times the run was relaunched, and whether auto-resume ever had
    # to quarantine a corrupt checkpoint and fall back to an earlier
    # epoch ("fallback" records from the trainer's restore path).
    fallbacks = [r for r in records if r.get("kind") == "fallback"]
    restart_n = (
        final_gp.get("restarts") if isinstance(final_gp, dict) else None
    )
    if restart_n or fallbacks:
        lines.append(
            f"restarts      : {restart_n or 0} restart(s), "
            f"{len(fallbacks)} checkpoint fallback(s)"
        )
        if fallbacks:
            fb = fallbacks[-1]
            lines.append(
                f"                last fallback: epoch "
                f"{_fmt(fb.get('epoch'))} quarantined -> resumed "
                f"epoch {_fmt(fb.get('resumed_epoch'))}"
            )

    # Elastic triage (world resize): one run_start record per
    # generation carries the live world; the trajectory plus the
    # goodput sidecar's downtime split answers "did we shrink, and
    # what did the reshapes cost vs the plain crashes". Absent on
    # pre-elastic streams (no run_start records), so their reports
    # stay byte-identical.
    run_starts = [r for r in records if r.get("kind") == "run_start"]
    if run_starts:
        worlds = [
            r.get("data_shards") or r.get("world_size")
            for r in run_starts
        ]
        worlds = [int(w) for w in worlds if w]
        traj = " -> ".join(str(w) for w in worlds) if worlds else "?"
        n_resize = sum(
            1 for a, b in zip(worlds, worlds[1:]) if a != b
        )
        line = (
            f"elastic       : {len(run_starts)} generation(s), "
            f"world {traj}"
        )
        if n_resize:
            line += f" ({n_resize} resize(s))"
        if isinstance(final_gp, dict) and (
            "resize_downtime_s" in final_gp
            or "restart_downtime_s" in final_gp
        ):
            line += (
                f"; downtime resize "
                f"{_fmt(final_gp.get('resize_downtime_s', 0.0), 1)}s / "
                f"restart "
                f"{_fmt(final_gp.get('restart_downtime_s', 0.0), 1)}s"
            )
        lines.append(line)

    recompiles = sum(e.get("recompiles", 0) for e in epochs)
    if any("recompiles" in e for e in epochs):
        lines.append(f"recompiles    : {recompiles}")

    # Compiled-program triage (--xprof streams): what the run paid in
    # XLA builds — and, when a compile was a RE-compile, the culprit
    # label and shape-diff. Only printed when the stream carries
    # "compile" records, so pre-xprof reports stay byte-identical.
    compiles = [r for r in records if r.get("kind") == "compile"]
    if compiles:
        total = sum(r.get("compile_time_s") or 0.0 for r in compiles)
        by_label: dict[str, int] = {}
        for c in compiles:
            lbl = c.get("label") or "?"
            by_label[lbl] = by_label.get(lbl, 0) + 1
        detail = ", ".join(
            f"{k}: {v}" for k, v in sorted(by_label.items())
        )
        lines.append(
            f"compiles      : {len(compiles)} ({detail}), "
            f"{_fmt(total, 2)}s total"
        )
        recompiled = [c for c in compiles if c.get("shape_diff")]
        if recompiled:
            last = recompiled[-1]
            lines.append(
                f"                last recompile: {last.get('label')} "
                f"[{last.get('shape_diff')}] "
                f"{_fmt(last.get('compile_time_s'), 2)}s"
            )

    # Device-memory triage (--xprof): the high-water across the run
    # and the latest headroom (absent off-TPU — no honest limit).
    # STREAM order, not steps-then-epochs concatenation: a run that
    # finished epoch N and then OOM'd mid-epoch N+1 has its freshest
    # (lowest) headroom in step records written AFTER the epoch-N
    # record, and the "latest" value must be the last one written.
    hbm = [
        r
        for r in records
        if r.get("kind") in ("step", "epoch")
        and r.get("hbm_high_water_bytes") is not None
    ]
    if hbm:
        high = max(int(r["hbm_high_water_bytes"]) for r in hbm)
        line = f"hbm           : high-water {high:,} bytes"
        fracs = [
            r["hbm_headroom_frac"]
            for r in hbm
            if r.get("hbm_headroom_frac") is not None
        ]
        if fracs:
            line += f" (headroom {_fmt(100.0 * fracs[-1], 1)}%)"
        lines.append(line)

    # Collective-payload estimate (the ddp/zero update strategies
    # stamp it — parallel/zero.py): only printed when present, so
    # pre-zero streams keep their golden output byte-identical. The
    # hierarchical zero step additionally stamps the per-fabric split
    # (comm_bytes_ici/dcn) — rendered inline when present, so flat
    # streams (and every existing golden) stay byte-identical too.
    comm = [
        r
        for r in steps + epochs
        if r.get("comm_bytes") is not None
    ]
    if comm:
        last = comm[-1]
        line = f"comm/step     : {last['comm_bytes']:,} bytes (estimate"
        if last.get("comm_bytes_dcn") is not None:
            line += (
                f"; ici {last.get('comm_bytes_ici', 0):,} / "
                f"dcn {last['comm_bytes_dcn']:,}"
            )
        lines.append(line + ")")

    # Serve triage (ISSUE 11): user-facing latency percentiles, queue
    # wait, SLO burn and speculative acceptance — only when the stream
    # carries serve records (scripts/serve.py --metrics_file), so
    # pre-existing trainer streams stay byte-identical.
    serve_reqs = [r for r in records if r.get("kind") == "serve_request"]
    serve_steps = [r for r in records if r.get("kind") == "serve_step"]
    slo_breaches = [r for r in records if r.get("kind") == "slo_breach"]
    if serve_reqs or serve_steps or slo_breaches:
        by_status: dict[str, int] = {}
        ttft, tpot, queue = StatSummary(), StatSummary(), StatSummary()
        acc = StatSummary()
        for r in serve_reqs:
            s = r.get("status", "?")
            by_status[s] = by_status.get(s, 0) + 1
            for summ, key in (
                (ttft, "ttft_s"), (tpot, "tpot_s"), (queue, "queue_s"),
                (acc, "spec_acceptance"),
            ):
                if r.get(key) is not None:
                    summ.add(r[key])
        detail = ", ".join(
            f"{k}: {v}" for k, v in sorted(by_status.items())
        )
        steps_note = (
            f", {len(serve_steps)} engine step(s)" if serve_steps else ""
        )
        lines.append(
            f"serve         : {len(serve_reqs)} request(s)"
            + (f" ({detail})" if detail else "")
            + steps_note
        )
        for label, summ in (
            ("serve ttft", ttft), ("serve tpot", tpot),
            ("serve queue", queue),
        ):
            if summ.count:
                lines.append(
                    f"{label:<14}: p50 {_fmt(summ.percentile(50), 4)}s  "
                    f"p99 {_fmt(summ.percentile(99), 4)}s"
                )
        if acc.count:
            lines.append(
                f"spec accept   : mean "
                f"{_fmt(acc.snapshot().get('mean'), 4)} over "
                f"{acc.count} request(s)"
            )
        # Paged-KV page/prefix triage (PR 12): only when the stream
        # carries paged serve_step fields — fixed-lane streams (and
        # every pre-paging golden) stay byte-identical.
        paged_steps = [
            r for r in serve_steps if r.get("pages_free") is not None
        ]
        if paged_steps:
            last = paged_steps[-1]
            hits = sum(
                1 for r in serve_reqs if r.get("prefix_hit_tokens")
            )
            misses = sum(
                1
                for r in serve_reqs
                if r.get("prefix_hit_tokens") == 0
            )
            lines.append(
                f"pages         : free {_fmt(last.get('pages_free'))}"
                f", resident {_fmt(last.get('pages_resident'))}"
                f", shared {_fmt(last.get('pages_shared'))}; prefix "
                f"hit rate {_fmt(last.get('prefix_hit_rate'), 4)} "
                f"({hits} hit / {misses} miss)"
            )
        if slo_breaches:
            last = slo_breaches[-1]
            lines.append(
                f"slo           : {len(slo_breaches)} breach event(s), "
                f"last {last.get('objective')} burn "
                f"{_fmt(last.get('burn_rate_fast'), 1)} (fast) / "
                f"{_fmt(last.get('burn_rate_slow'), 1)} (slow)"
            )

    # Fleet triage (serve/fleet.py): the router/manager poll records
    # carry cumulative counters, so the LAST one is the fleet's
    # current shape — replicas up/draining/dead, breakers shedding,
    # replay/hedge accounting, restarts. Gated on record presence:
    # trainer and single-replica serve streams (and every existing
    # golden) stay byte-identical.
    fleet_polls = [r for r in records if r.get("kind") == "fleet_poll"]
    if fleet_polls:
        f = fleet_polls[-1]
        lines.append(
            f"fleet         : {f.get('replicas_healthy', 0)}/"
            f"{f.get('replicas', 0)} healthy"
            f", {f.get('replicas_draining', 0)} draining"
            f", {f.get('replicas_dead', 0)} dead"
            f"; breakers open {f.get('breaker_open', 0)} "
            f"({f.get('breaker_opens_total', 0)} lifetime)"
        )
        lines.append(
            f"fleet traffic : {f.get('dispatched_total', 0)} dispatched"
            f", {f.get('replays_total', 0)} replayed"
            f", hedges {f.get('hedge_wins_total', 0)}/"
            f"{f.get('hedges_total', 0)} won"
            f"; restarts {f.get('restarts_total', 0)}"
            f", rolling {f.get('rolling_restarts_total', 0)}"
        )
        # Disaggregation triage (PR 16): the migration counters ride
        # the poll record only when the router runs role-aware or
        # directory dispatch — classic fleet streams (and their
        # goldens) carry no key and print no line.
        if "migrations_total" in f or "directory_pulls_total" in f:
            pulls = f.get("directory_pulls_total", 0)
            hits = f.get("directory_pull_hits_total", 0)
            ms = f.get("migration_seconds") or {}
            lines.append(
                f"fleet disagg  : {f.get('migrations_total', 0)} "
                f"migration(s) ({f.get('pages_migrated_total', 0)} "
                f"pages, {f.get('migration_failures_total', 0)} "
                f"failed)"
                f", prefill handoffs {f.get('prefill_handoffs_total', 0)}"
                f"; directory pulls {hits}/{pulls} hit"
                + (
                    f"; migrate p50 {_fmt(ms.get('p50'), 4)}s"
                    f" p95 {_fmt(ms.get('p95'), 4)}s"
                    if ms.get("count")
                    else ""
                )
            )

    # Lifecycle triage (PR 20): one serve_reload record per /reload
    # attempt — swaps, named rejections, rollbacks, and the version
    # the engine last landed on. Gated on record presence so trainer
    # and pre-lifecycle serve streams (and their goldens) stay
    # byte-identical.
    reloads = [r for r in records if r.get("kind") == "serve_reload"]
    if reloads:
        swapped = [r for r in reloads if r.get("outcome") == "swapped"]
        rejected = [r for r in reloads if r.get("outcome") == "rejected"]
        rolled = [r for r in reloads if r.get("rolled_back")]
        line = (
            f"lifecycle     : {len(swapped)}/{len(reloads)} "
            f"reload(s) swapped"
            f", {len(rejected)} rejected"
            f", {len(rolled)} rolled back"
        )
        reasons = sorted({r.get("reason") for r in rejected if r.get("reason")})
        if reasons:
            line += f" ({', '.join(reasons)})"
        if swapped and swapped[-1].get("model_version"):
            line += f"; now {swapped[-1]['model_version']}"
        lines.append(line)

    # Fleet-trace triage (PR 19): trace_merge.py --metrics_file stamps
    # one cumulative fleet_trace record per merge, so the LAST one is
    # the freshest fleet reconstruction — requests stitched across
    # router + replica trace dirs, how many survived causal
    # validation, and which hop is the tail's bottleneck. Gated on
    # record presence so every existing golden stays byte-identical.
    fleet_traces = [r for r in records if r.get("kind") == "fleet_trace"]
    if fleet_traces:
        ft = fleet_traces[-1]
        n = ft.get("requests", 0)
        ok = ft.get("causal_ok", 0)
        frac = (ok / n) if n else 0.0
        line = (
            f"fleet trace   : {n} request(s) reconstructed, "
            f"{ok} causal-ok ({_fmt(100.0 * frac, 1)}%)"
            f"; hedged {ft.get('hedged', 0)}"
            f", migrated {ft.get('migrated', 0)}"
        )
        if ft.get("worst_hop"):
            line += (
                f"; worst hop {ft['worst_hop']} "
                f"p99 {_fmt(ft.get('worst_hop_p99_s'), 4)}s"
            )
        lines.append(line)

    # MPMD pipeline triage (parallel/mpmd.py): stage-tagged step
    # records plus the supervisor's mpmd_run/mpmd_restart stamps.
    # Gated on those markers, so SPMD trainer and serve streams (and
    # every existing golden) stay byte-identical.
    mpmd_steps = [r for r in steps if r.get("stage") is not None]
    mpmd_runs = [r for r in records if r.get("kind") == "mpmd_run"]
    mpmd_restarts = [
        r for r in records if r.get("kind") == "mpmd_restart"
    ]
    if mpmd_steps or mpmd_runs or mpmd_restarts:
        stage_ids = sorted({int(r["stage"]) for r in mpmd_steps})
        if mpmd_runs and mpmd_runs[-1].get("stages"):
            n_stages = int(mpmd_runs[-1]["stages"])
        else:
            n_stages = len(stage_ids)
        lead = [
            r
            for r in mpmd_steps
            if stage_ids
            and r.get("stage") == stage_ids[0]
            and r.get("loss") is not None
        ]
        lead.sort(key=lambda r: r.get("step", 0))
        traj = (
            f"loss {_fmt(lead[0]['loss'])} -> {_fmt(lead[-1]['loss'])}"
            if lead
            else "loss ?"
        )
        bubbles = [
            r["bubble_s"] / r["wall_s"]
            for r in mpmd_steps
            if r.get("bubble_s") is not None and r.get("wall_s")
        ]
        bub = (
            f", bubble {_fmt(100.0 * sum(bubbles) / len(bubbles), 1)}%"
            if bubbles
            else ""
        )
        n_restarts = (
            mpmd_runs[-1].get("restarts")
            if mpmd_runs and mpmd_runs[-1].get("restarts") is not None
            else len(mpmd_restarts)
        )
        lines.append(
            f"mpmd          : {n_stages} stage(s), {traj}"
            f"{bub}, {n_restarts} restart(s)"
        )

    # Autotuner triage (ddp_tpu.tune): surfaces whether this run's
    # knobs came from a tuning-cache hit, what was applied and what an
    # explicit flag overrode. Gated on the record — untuned streams
    # (and every existing golden) carry no "tuning" records and stay
    # byte-identical.
    tunes = [r for r in records if r.get("kind") == "tuning"]
    if tunes:
        parts = []
        for t in tunes:
            applied = t.get("applied") or {}
            overridden = t.get("overridden") or []
            knobs = ", ".join(
                f"{k}={v}" for k, v in sorted(applied.items())
            )
            part = (
                f"{t.get('site', '?')} "
                f"{'hit' if t.get('cache_hit') else 'miss'}"
                f" ({len(applied)} applied"
                + (f": {knobs}" if knobs else "")
                + (
                    f"; {len(overridden)} overridden by flags"
                    if overridden
                    else ""
                )
                + ")"
            )
            parts.append(part)
        lines.append(f"tuning        : {'; '.join(parts)}")

    sentry = [h for h in health if h.get("detector") != "nonfinite"]
    if sentry:
        by_det: dict[str, int] = {}
        for h in sentry:
            d = h.get("detector", "?")
            by_det[d] = by_det.get(d, 0) + 1
        detail = ", ".join(f"{k}: {v}" for k, v in sorted(by_det.items()))
        lines.append(f"anomalies     : {len(sentry)} ({detail})")
    else:
        lines.append("anomalies     : none")

    nonfinite = [h for h in health if h.get("detector") == "nonfinite"]
    if nonfinite:
        first = nonfinite[0]
        layer = first.get("layer") or "<loss only>"
        lines.append(
            f"nonfinite     : layer {layer} at step {first.get('step')}"
        )
    else:
        lines.append("nonfinite     : none")

    if finals:
        f = finals[-1]
        tail = (
            f"accuracy {_fmt(f.get('accuracy'))}  "
            f"loss {_fmt(f.get('loss'))}"
        )
        if f.get("perplexity") is not None:
            tail += f"  perplexity {_fmt(f.get('perplexity'))}"
        lines.append(f"final         : {tail}")
    else:
        lines.append("final         : (run not finished)")

    return "\n".join(lines) + "\n"


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("metrics_file", help="JSONL stream from --metrics_file")
    args = p.parse_args()
    records = load_records(args.metrics_file)
    if not records:
        raise SystemExit(f"{args.metrics_file}: no readable records")
    sys.stdout.write(build_report(records))


if __name__ == "__main__":
    main()

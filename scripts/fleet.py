#!/usr/bin/env python
"""Fleet serving CLI: N supervised replicas behind the health-gated
router (ddp_tpu.serve.fleet; docs/SERVING.md "Fleet serving").

    python scripts/fleet.py --replicas 3 --port 8100 \
        -- --init_demo --slots 2 --page_size 16
    curl -s localhost:8100/generate -d \
        '{"prompt_tokens": [1, 2, 3], "max_new_tokens": 16}'

Everything after ``--`` is forwarded VERBATIM to every replica's
``scripts/serve.py`` (same checkpoint, same engine knobs — each
replica gets its own ``--port``). The frontend exposes:

  POST /generate   routed with prefix affinity + least-loaded spill,
                   bounded retry, optional hedging (--hedge_after),
                   per-replica circuit breakers; responses carry a
                   ``router`` digest (replica, attempts, replays,
                   hedge outcome, fleet trace id)
  GET  /healthz    fleet liveness (>= 1 dispatchable replica)
  GET  /statusz    router + manager state, plus the live
                   obs/aggregate.py fleet view scraped from members
  GET  /metricsz   linted ddp_tpu_fleet_* gauges
  POST /rollz      rolling restart: drain -> wait -> restart ->
                   re-admit, one replica at a time, zero dropped

``--roles prefill,decode,decode`` splits the fleet into a
disaggregated prefill/decode topology and ``--directory`` turns on the
fleet-global prefix tier — both ride the replicas' POST /pages
transfer plane (docs/SERVING.md "Disaggregated serving").

``--chaos "kill:replica1@request8"`` arms fleet drills
(runtime/chaos.py grammar) fired on the router's dispatch counter.
SIGTERM drains the FLEET: the frontend stops admitting (503 +
Retry-After), replicas drain their lanes, then everything exits.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8100)
    p.add_argument(
        "--workdir", default="/tmp/ddp_tpu_fleet",
        help="per-replica logs land here (replicaN.log)",
    )
    p.add_argument(
        "--max_restarts", type=int, default=2,
        help="per-replica restart budget (PR-5 semantics: classified "
        "exit, capped exponential backoff)",
    )
    p.add_argument("--restart_backoff", type=float, default=0.5)
    p.add_argument(
        "--poll_interval", type=float, default=0.25,
        help="supervision cadence: /healthz probes, breaker half-open "
        "probes, process liveness",
    )
    p.add_argument(
        "--hedge_after", type=float, default=None,
        help="tail-latency hedging: duplicate a request still "
        "unanswered after this many seconds to a second replica — "
        "first completion wins, the loser is cancelled (off by "
        "default)",
    )
    p.add_argument(
        "--retry_max", type=int, default=3,
        help="re-dispatch budget per request (connection-level "
        "failures; jittered exponential backoff between tries)",
    )
    p.add_argument("--retry_backoff", type=float, default=0.05)
    p.add_argument(
        "--breaker_threshold", type=int, default=3,
        help="consecutive failures that open a replica's circuit "
        "breaker (a refused connection opens it immediately)",
    )
    p.add_argument(
        "--breaker_cooldown", type=float, default=2.0,
        help="open -> half-open probe interval",
    )
    p.add_argument(
        "--affinity_page", type=int, default=16,
        help="prefix-affinity granularity: the prompt's leading "
        "page-aligned tokens hash to a preferred replica so its "
        "radix prefix cache stays warm (0 = least-loaded only; "
        "match the replicas' --page_size)",
    )
    p.add_argument(
        "--roles", default=None,
        help="comma-separated per-replica roles (prefill|decode|"
        "hybrid), e.g. 'prefill,decode,decode' — must name every "
        "replica. Long prompts prefill on the prefill tier, then the "
        "KV pages migrate to a decode replica over POST /pages "
        "(docs/SERVING.md 'Disaggregated serving'). Default: every "
        "replica hybrid, identical to the classic fleet",
    )
    p.add_argument(
        "--prefill_cutoff", type=int, default=64,
        help="disagg length classifier: prompts with at least this "
        "many page-aligned tokens go to the prefill tier (only "
        "meaningful with --roles)",
    )
    p.add_argument(
        "--directory", action="store_true",
        help="fleet-global prefix tier: the router remembers which "
        "replica owns each leading-page prefix and has a missing "
        "replica PULL those pages over /pages instead of "
        "re-prefilling (generalizes prefix affinity across churn)",
    )
    p.add_argument(
        "--migration_timeout", type=float, default=10.0,
        help="budget for one page migration (export + push); on "
        "expiry the router skips the migration and the target "
        "prefills locally — never a torn page set",
    )
    p.add_argument(
        "--chaos", default=None,
        help="fleet drills, e.g. 'kill:replica1@request8,"
        "stall:replica0@request4:2.5s' — fired on the router's "
        "dispatch counter (runtime/chaos.py grammar)",
    )
    p.add_argument(
        "--metrics_file", default=None,
        help="fleet_poll JSONL records (scripts/health_report.py "
        "prints the fleet triage lines from them)",
    )
    p.add_argument(
        "--trace_dir", default=None,
        help="fleet-wide distributed tracing: the router records a "
        "span per dispatch/retry/hedge/migration hop and exports to "
        "TRACE_DIR/router on drain; every replica runs with "
        "--trace_dir TRACE_DIR/replicaN --reqtrace so "
        "scripts/trace_merge.py can stitch one causal timeline per "
        "request across the fleet (docs/OBSERVABILITY.md)",
    )
    p.add_argument(
        "--drain_timeout", type=float, default=30.0,
        help="SIGTERM: stop admitting at the frontend, then give "
        "replicas this long to finish lanes before the kill",
    )
    p.add_argument(
        "--tuned", default="auto", metavar="auto|off|PATH",
        help="tuning cache (ddp_tpu.tune): 'auto' loads "
        "tuning_cache.json beside the forwarded --checkpoint_dir "
        "and fills router knobs (--hedge_after) left at defaults "
        "from the cached winner — an explicit flag always wins; "
        "'off' disables. Replicas resolve their own --tuned from "
        "the forwarded serve args",
    )
    p.add_argument(
        "serve_args", nargs=argparse.REMAINDER,
        help="everything after -- goes verbatim to every replica's "
        "scripts/serve.py",
    )
    args = p.parse_args()
    serve_args = list(args.serve_args)
    if serve_args and serve_args[0] == "--":
        serve_args = serve_args[1:]
    if any(a in ("--port", "--host") for a in serve_args):
        raise SystemExit(
            "replica --port/--host are manager-assigned; drop them "
            "from the forwarded serve args"
        )

    # Tuning cache: the router's own knob surface (hedge_after today).
    # The fleet's key is model-shape-agnostic — hedging tracks traffic
    # and hardware, not parameter dims.
    tuning = None
    if args.tuned != "off":
        from ddp_tpu.tune import apply_tuned, cache_key, resolve_cache

        ckpt = "./checkpoints"
        if "--checkpoint_dir" in serve_args:
            i = serve_args.index("--checkpoint_dir")
            if i + 1 < len(serve_args):
                ckpt = serve_args[i + 1]
        _cache = resolve_cache(args.tuned, ckpt)
        _ent = (
            _cache.lookup(cache_key("fleet", "any"))
            if _cache is not None
            else None
        )
        if _ent is not None:
            current = {"hedge_after": args.hedge_after}
            explicit = {
                k for k, v in current.items() if v is not None
            }
            merged, applied, overridden = apply_tuned(
                current, _ent["config"], explicit=explicit
            )
            args.hedge_after = merged["hedge_after"]
            tuning = {
                "cache": _cache.path,
                "applied": applied,
                "overridden": overridden,
            }

    from ddp_tpu.serve.fleet import (
        ROLE_HYBRID,
        ROLES,
        FleetChaos,
        FleetServer,
        ReplicaManager,
        Router,
        RouterConfig,
    )
    from ddp_tpu.utils.metrics import MetricsWriter

    roles = None
    if args.roles:
        roles = [r.strip() for r in args.roles.split(",")]
        bad = [r for r in roles if r not in ROLES]
        if bad:
            raise SystemExit(
                f"unknown role(s) {bad}; pick from {list(ROLES)}"
            )
        if len(roles) != args.replicas:
            raise SystemExit(
                f"--roles names {len(roles)} replicas but "
                f"--replicas is {args.replicas}"
            )
    metrics = MetricsWriter(args.metrics_file)
    if tuning:
        metrics.write(
            "tuning",
            site="fleet",
            cache_hit=True,
            cache=tuning["cache"],
            applied=tuning["applied"],
            overridden=tuning["overridden"],
        )
    manager = ReplicaManager(
        args.replicas,
        serve_args,
        workdir=args.workdir,
        max_restarts=args.max_restarts,
        restart_backoff=args.restart_backoff,
        poll_interval=args.poll_interval,
        metrics=metrics,
        roles=roles,
        trace_dir=args.trace_dir,
    )
    tracer = None
    if args.trace_dir:
        from ddp_tpu.obs.tracer import Tracer

        tracer = Tracer(enabled=True)
    config = RouterConfig(
        retry_max=args.retry_max,
        retry_backoff_s=args.retry_backoff,
        hedge_after_s=args.hedge_after,
        affinity_page=args.affinity_page,
        breaker_threshold=args.breaker_threshold,
        breaker_cooldown_s=args.breaker_cooldown,
        trace_seed=int.from_bytes(os.urandom(8), "little"),
        disagg=bool(roles) and any(r != ROLE_HYBRID for r in roles),
        prefill_cutoff_tokens=args.prefill_cutoff,
        directory=args.directory,
        migration_timeout_s=args.migration_timeout,
    )
    stop_event = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop_event.set())
    chaos = FleetChaos(args.chaos, manager) if args.chaos else None
    try:
        manager.start()
        router = manager.attach_router(
            Router(
                manager.replicas,
                config,
                on_dispatch=chaos.on_dispatch if chaos else None,
                tracer=tracer,
            )
        )
        healthy = manager.wait_healthy()
        with FleetServer(
            manager, router, host=args.host, port=args.port,
            chaos=chaos,
        ) as server:
            print(
                json.dumps(
                    {
                        "fleet": server.url,
                        "metricsz": server.url + "/metricsz",
                        "replicas": [
                            r.url for r in manager.replicas
                        ],
                        "all_healthy": healthy,
                        "hedge_after": args.hedge_after,
                        "affinity_page": args.affinity_page,
                        **({"roles": roles} if roles else {}),
                        **(
                            {"directory": True}
                            if args.directory else {}
                        ),
                        **(
                            {"chaos": args.chaos} if args.chaos else {}
                        ),
                        **(
                            {"trace_dir": args.trace_dir}
                            if args.trace_dir else {}
                        ),
                        **({"tuning": tuning} if tuning else {}),
                    }
                ),
                flush=True,
            )
            try:
                stop_event.wait()
            except KeyboardInterrupt:
                pass
            # Fleet-wide drain: frontend first (new admissions get
            # 503 + Retry-After), then the members finish their lanes
            # inside manager.stop(drain_timeout) below.
            server.begin_drain()
            print(
                json.dumps({"draining": True}), flush=True
            )
    finally:
        manager.stop(drain_timeout=args.drain_timeout)
        # Router trace exports after the members stop so the drain's
        # final hop spans (503s, cancelled hedges) are in the file;
        # an unwritable dir must not mask the metrics close below.
        if tracer is not None:
            try:
                path = tracer.export_to_dir(
                    os.path.join(args.trace_dir, "router")
                )
                print(json.dumps({"router_trace": path}), flush=True)
            except OSError as e:
                print(
                    json.dumps({"router_trace_error": str(e)}),
                    file=sys.stderr, flush=True,
                )
        metrics.close()


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Convert stale-qkv-layout checkpoints to the current format.

The layout ladder (train/checkpoint.py CHECKPOINT_FORMAT): round 3
reordered the fused qkv projection's output columns from [q|k|v, head,
head_dim] to [head, q|k|v, head_dim] (format 2 — TP shards are whole
heads); round 4 moved GROUPED-QUERY checkpoints to group-major columns
([kv-group: q·G | k | v] × H_kv, format 3 — GQA×TP shards are whole kv
groups; MHA trees are identical in 2 and 3). Shapes never change, so
stale checkpoints would restore without error and silently scramble
attention — the restore path refuses them (train/checkpoint.py
``_check_qkv_format``) and points here.

    python scripts/convert_qkv_layout.py --checkpoint_dir ./checkpoints \
        --num_heads 4 [--num_kv_heads 2] [--epoch N] [--out_dir DIR]

NON-DESTRUCTIVE: converted epochs are written to ``--out_dir``
(default ``<checkpoint_dir>_converted``); the source directory is never
touched, so a crash mid-conversion cannot destroy the only copy of an
irreplaceable checkpoint. Point the trainer at the new directory when
done. Every ``attn/qkv`` kernel (last dim) and bias is permuted in
``params`` AND the optimizer state (Adam moments share the layout).

Same-topology note: the source is read template-free (the conversion
deliberately knows nothing about which model/optimizer produced it),
which requires running under a device topology that can host the saved
shardings — e.g. the same ``--xla_force_host_platform_device_count``
the training run used, or any single-host layout for replicated saves.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import ddp_tpu  # noqa: F401,E402  (applies the JAX_PLATFORMS env pin)
import numpy as np  # noqa: E402


def _path_keys(path):
    """Stringified tree-path keys — the one place the converter and
    its verifier decide what counts as a qkv leaf."""
    return [str(getattr(k, "key", k)) for k in path]


def permute_qkv_columns(tree, num_heads: int):
    """[..., 3, H, Dh]-ordered trailing axis → [..., H, 3, Dh]."""
    import jax

    def fix(path, leaf):
        if "qkv" not in _path_keys(path):
            return leaf
        arr = np.asarray(leaf)
        if arr.ndim == 0 or arr.shape[-1] % (3 * num_heads):
            return leaf
        dh = arr.shape[-1] // (3 * num_heads)
        shaped = arr.reshape(*arr.shape[:-1], 3, num_heads, dh)
        return np.swapaxes(shaped, -3, -2).reshape(arr.shape)

    return jax.tree_util.tree_map_with_path(fix, tree)


def permute_gqa_columns(tree, num_heads: int, num_kv_heads: int):
    """Format-2 GQA block layout → format-3 group-major (round 4).

    Old trailing-axis order: [q_0..q_{H−1} | k_0..k_{K−1} |
    v_0..v_{K−1}], each head_dim wide. New: for each kv group g,
    [q_{gG}..q_{gG+G−1}, k_g, v_g]. A GQA checkpoint's qkv leaves are
    uniformly (H + 2K)·Dh wide in this framework (every dense block
    shares ``num_kv_heads``; MoE+GQA is rejected at construction), so
    every divisible qkv leaf is permuted; pass the H and K the
    checkpoint was trained with.
    """
    import jax

    H, K = num_heads, num_kv_heads
    G = H // K
    n_cols = H + 2 * K  # head-sized column blocks

    def fix(path, leaf):
        if "qkv" not in _path_keys(path):
            return leaf
        arr = np.asarray(leaf)
        if arr.ndim == 0 or arr.shape[-1] % n_cols:
            return leaf
        dh = arr.shape[-1] // n_cols
        head_order = []
        for g in range(K):
            head_order.extend(range(g * G, (g + 1) * G))  # q heads
            head_order.append(H + g)  # k_g
            head_order.append(H + K + g)  # v_g
        perm = np.concatenate(
            [np.arange(h * dh, (h + 1) * dh) for h in head_order]
        )
        return arr[..., perm]

    return jax.tree_util.tree_map_with_path(fix, tree)


def verify_gqa_qkv(tree, num_heads: int, num_kv_heads: int):
    """Return the qkv kernel leaves that do NOT match the GQA layout.

    ``permute_gqa_columns`` silently skips any leaf whose trailing dim
    is not divisible by H + 2K — so a wrong ``--num_kv_heads`` either
    leaves every GQA leaf untouched, or (when the divisibility
    accidentally holds) mis-groups columns; both would then be stamped
    format 3, laundering a scrambled checkpoint past the restore guard.
    The shape invariant below catches every such case EXCEPT a
    ratio-preserving wrong pair (H/m, K/m) — e.g. true (4, 2) given as
    (2, 1) — where the expected out-dim (H+2K)·(in//H) depends only on
    the K/H ratio; nothing in a template-free checkpoint encodes H
    itself (d_model = H·Dh for every plausible split), so that case is
    undetectable here and the docs/error text must not overclaim.
    The invariant: every qkv KERNEL in this
    framework is (…, C, (H + 2K)·Dh) with Dh = C // H (models/vit.py
    Block.__call__ builds the fused projection from d_model = H·Dh;
    pipelined checkpoints stack stages in leading dims). Keyed on the
    ``kernel`` leaf name so a stacked bias ([S, out]) is never misread
    as a kernel. Biases carry no in-dim to derive Dh from; the kernels
    they ride with share the layout code, so kernel verification
    covers them.
    """
    import jax

    H, K = num_heads, num_kv_heads
    bad = []

    def chk(path, leaf):
        keys = _path_keys(path)
        if "qkv" not in keys or keys[-1] != "kernel":
            return leaf
        arr = np.asarray(leaf)
        if arr.ndim < 2:
            return leaf
        in_dim, out_dim = arr.shape[-2], arr.shape[-1]
        if in_dim % H or out_dim != (H + 2 * K) * (in_dim // H):
            bad.append(("/".join(keys), (in_dim, out_dim)))
        return leaf

    jax.tree_util.tree_map_with_path(chk, tree)
    return bad


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--checkpoint_dir", default="./checkpoints")
    p.add_argument(
        "--out_dir", default=None,
        help="destination (default: <checkpoint_dir>_converted); the "
        "source is left untouched",
    )
    p.add_argument("--num_heads", type=int, required=True)
    p.add_argument(
        "--num_kv_heads", type=int, default=0,
        help="grouped-query checkpoints only: the K the run used — "
        "converts the format-2 GQA block layout to the format-3 "
        "group-major layout (round 4)",
    )
    p.add_argument(
        "--epoch", type=int, default=None,
        help="convert one epoch (default: every epoch in the dir)",
    )
    args = p.parse_args()
    out_dir = args.out_dir or (
        args.checkpoint_dir.rstrip("/\\") + "_converted"
    )
    if os.path.abspath(out_dir) == os.path.abspath(args.checkpoint_dir):
        print("--out_dir must differ from --checkpoint_dir", file=sys.stderr)
        return 2

    from ddp_tpu.parallel.ddp import TrainState
    from ddp_tpu.train.checkpoint import (
        CHECKPOINT_FORMAT,
        CheckpointManager,
        _has_gqa_qkv,
    )

    src = CheckpointManager(args.checkpoint_dir, async_save=False)
    dst = CheckpointManager(out_dir, async_save=False)
    epochs = [args.epoch] if args.epoch is not None else src.all_epochs()
    if not epochs:
        print(f"no checkpoints in {args.checkpoint_dir}", file=sys.stderr)
        return 1
    for epoch in epochs:
        # Template-free read: preserves the saved tree structure
        # (optimizer states with empty nodes included) without knowing
        # which model/optimizer wrote it. read_partial is no good here
        # — its metadata-derived abstract tree chokes on the optimizer
        # state's empty (None) nodes.
        tree = dict(src._mgr.restore(epoch))
        fmt = int(np.asarray(tree.pop("fmt", 1)))
        if fmt >= CHECKPOINT_FORMAT:
            print(f"epoch {epoch}: already format {fmt}, skipping")
            continue
        gqa = bool(
            args.num_kv_heads
            and args.num_kv_heads != args.num_heads
        )
        if fmt == 2 and not gqa and _has_gqa_qkv(tree.get("params", {})):
            # Without the permutation the output would be stamped
            # format 3 with the OLD column order inside — laundering a
            # scrambled checkpoint past the restore guard forever.
            print(
                f"epoch {epoch}: holds GQA attention weights (qkv "
                "out-dim ≠ 3×in-dim) — pass --num_kv_heads <K> so the "
                "2→3 group-major permutation actually runs",
                file=sys.stderr,
            )
            return 2
        if fmt < 2 and gqa:
            # Format 1 predates GQA: every qkv leaf is MHA-shaped and
            # the GQA permutation would corrupt it.
            print(
                f"epoch {epoch}: format-1 checkpoints predate GQA — "
                "drop --num_kv_heads",
                file=sys.stderr,
            )
            return 2
        for key in ("params", "opt_state"):
            if key not in tree:
                continue
            if fmt < 2:
                # 1 → 2: q/k/v-major → head-major (predates GQA, so
                # every format-1 qkv leaf is MHA-shaped).
                tree[key] = permute_qkv_columns(tree[key], args.num_heads)
            if gqa and fmt >= 2:
                # 2 → 3: GQA block layout → group-major.
                tree[key] = permute_gqa_columns(
                    tree[key], args.num_heads, args.num_kv_heads
                )
        # A wrong H (or K) makes the permutes silently skip (or
        # mis-group) leaves; refuse to stamp the new format unless
        # every qkv kernel has the expected out-dim. MHA conversions
        # verify with K = H (out = 3H·Dh) — the 1→2 path has the same
        # silent-skip laundering mode as 2→3.
        k_eff = args.num_kv_heads if gqa else args.num_heads
        bad = verify_gqa_qkv(
            {k: tree[k] for k in ("params", "opt_state") if k in tree},
            args.num_heads, k_eff,
        )
        if bad:
            print(
                f"epoch {epoch}: {len(bad)} qkv kernel(s) do not "
                f"match --num_heads {args.num_heads} "
                f"--num_kv_heads {k_eff} (expect out = "
                "(H+2K)*(in//H)); first: "
                f"{bad[0][0]} shape {bad[0][1]} — wrong H/K would "
                "stamp an unconverted or scrambled checkpoint as "
                f"format {CHECKPOINT_FORMAT}, refusing (note: a "
                "ratio-preserving wrong pair like H/2, K/2 cannot be "
                "detected — double-check against the training config)",
                file=sys.stderr,
            )
            return 2
        state = TrainState(
            step=tree["step"],
            params=tree["params"],
            opt_state=tree.get("opt_state", {}),
            model_state=tree.get("model_state", {}),
        )
        # overwrite=True: re-running the converter (e.g. after a wrong
        # --num_heads) must replace its own previous output — the
        # SOURCE dir is the safe copy, not the destination.
        saved = dst.save(
            epoch, state, overwrite=True,
            steps_per_epoch=int(np.asarray(tree.get("spe", 0))),
            mid_batch=int(np.asarray(tree.get("mid_batch", 0))),
        )
        if not saved:
            print(f"epoch {epoch}: save skipped unexpectedly",
                  file=sys.stderr)
            return 1
        print(f"epoch {epoch}: converted to format {CHECKPOINT_FORMAT} "
              f"→ {out_dir}")
    src.close()
    dst.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Convert format-1 checkpoints to the head-major fused-qkv layout.

Round 3 reordered the fused qkv projection's output columns from
[q|k|v, head, head_dim] to [head, q|k|v, head_dim] (models/vit.py
MultiHeadAttention) so contiguous tensor-parallel shards of the kernel
are whole heads. Shapes are identical, so old checkpoints would restore
without error and silently scramble attention — the restore path
refuses them (train/checkpoint.py ``_check_qkv_format``) and points
here.

    python scripts/convert_qkv_layout.py --checkpoint_dir ./checkpoints \
        --num_heads 4 [--epoch N] [--out_dir ./checkpoints_fmt2]

NON-DESTRUCTIVE: converted epochs are written to ``--out_dir``
(default ``<checkpoint_dir>_fmt2``); the source directory is never
touched, so a crash mid-conversion cannot destroy the only copy of an
irreplaceable checkpoint. Point the trainer at the new directory when
done. Every ``attn/qkv`` kernel (last dim) and bias is permuted in
``params`` AND the optimizer state (Adam moments share the layout).

Same-topology note: the source is read template-free (the conversion
deliberately knows nothing about which model/optimizer produced it),
which requires running under a device topology that can host the saved
shardings — e.g. the same ``--xla_force_host_platform_device_count``
the training run used, or any single-host layout for replicated saves.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import ddp_tpu  # noqa: F401,E402  (applies the JAX_PLATFORMS env pin)
import numpy as np  # noqa: E402


def permute_qkv_columns(tree, num_heads: int):
    """[..., 3, H, Dh]-ordered trailing axis → [..., H, 3, Dh]."""
    import jax

    def fix(path, leaf):
        keys = [str(getattr(k, "key", k)) for k in path]
        if "qkv" not in keys:
            return leaf
        arr = np.asarray(leaf)
        if arr.ndim == 0 or arr.shape[-1] % (3 * num_heads):
            return leaf
        dh = arr.shape[-1] // (3 * num_heads)
        shaped = arr.reshape(*arr.shape[:-1], 3, num_heads, dh)
        return np.swapaxes(shaped, -3, -2).reshape(arr.shape)

    return jax.tree_util.tree_map_with_path(fix, tree)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--checkpoint_dir", default="./checkpoints")
    p.add_argument(
        "--out_dir", default=None,
        help="destination (default: <checkpoint_dir>_fmt2); the source "
        "is left untouched",
    )
    p.add_argument("--num_heads", type=int, required=True)
    p.add_argument(
        "--epoch", type=int, default=None,
        help="convert one epoch (default: every epoch in the dir)",
    )
    args = p.parse_args()
    out_dir = args.out_dir or args.checkpoint_dir.rstrip("/\\") + "_fmt2"
    if os.path.abspath(out_dir) == os.path.abspath(args.checkpoint_dir):
        print("--out_dir must differ from --checkpoint_dir", file=sys.stderr)
        return 2

    from ddp_tpu.parallel.ddp import TrainState
    from ddp_tpu.train.checkpoint import (
        CHECKPOINT_FORMAT,
        CheckpointManager,
    )

    src = CheckpointManager(args.checkpoint_dir, async_save=False)
    dst = CheckpointManager(out_dir, async_save=False)
    epochs = [args.epoch] if args.epoch is not None else src.all_epochs()
    if not epochs:
        print(f"no checkpoints in {args.checkpoint_dir}", file=sys.stderr)
        return 1
    for epoch in epochs:
        # Template-free read: preserves the saved tree structure
        # (optimizer states with empty nodes included) without knowing
        # which model/optimizer wrote it. read_partial is no good here
        # — its metadata-derived abstract tree chokes on the optimizer
        # state's empty (None) nodes.
        tree = dict(src._mgr.restore(epoch))
        fmt = int(np.asarray(tree.pop("fmt", 1)))
        if fmt >= CHECKPOINT_FORMAT:
            print(f"epoch {epoch}: already format {fmt}, skipping")
            continue
        for key in ("params", "opt_state"):
            if key in tree:
                tree[key] = permute_qkv_columns(tree[key], args.num_heads)
        state = TrainState(
            step=tree["step"],
            params=tree["params"],
            opt_state=tree.get("opt_state", {}),
            model_state=tree.get("model_state", {}),
        )
        # overwrite=True: re-running the converter (e.g. after a wrong
        # --num_heads) must replace its own previous output — the
        # SOURCE dir is the safe copy, not the destination.
        saved = dst.save(
            epoch, state, overwrite=True,
            steps_per_epoch=int(np.asarray(tree.get("spe", 0))),
            mid_batch=int(np.asarray(tree.get("mid_batch", 0))),
        )
        if not saved:
            print(f"epoch {epoch}: save skipped unexpectedly",
                  file=sys.stderr)
            return 1
        print(f"epoch {epoch}: converted to format {CHECKPOINT_FORMAT} "
              f"→ {out_dir}")
    src.close()
    dst.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
